"""Fault-injection framework + retry policy + preemption handler
(utils/faults.py, utils/retry.py, elastic/preemption.py).

Everything here is deterministic: retry schedules run on a fake clock
(zero real sleeping), fault rules are seeded, the stall watchdog test
uses a deliberately-blocked executor with a sub-second abort window,
and the preemption test swaps the exit function for a recorder.
"""

import os
import pickle
import signal
import urllib.error
import urllib.request

import pytest

from horovod_tpu.core.exceptions import HorovodInternalError
from horovod_tpu.elastic import preemption
from horovod_tpu.elastic.state import ObjectState
from horovod_tpu.utils import faults, metrics, retry


@pytest.fixture(autouse=True)
def _fresh():
    faults.reset()
    retry.set_default_policy(None)
    metrics.reset()
    yield
    faults.reset()
    retry.set_default_policy(None)
    metrics.reset()
    preemption.uninstall()


class FakeClock:
    """Monotonic clock + sleep pair: sleeping advances the clock."""

    def __init__(self, t0=100.0):
        self.t = t0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def _fast_policy(**kw):
    """A zero-real-time policy for exercising call sites."""
    clk = FakeClock()
    kw.setdefault("clock", clk.clock)
    kw.setdefault("sleep", clk.sleep)
    return retry.RetryPolicy(**kw), clk


# ------------------------------------------------------------- spec parsing

def test_spec_parses_points_actions_and_params():
    faults.configure(
        "http.put:error:0.3:seed=7;worker:kill:rank=2:step=5,"
        "collective:delay:secs=0.01:times=3"
    )
    assert faults.enabled()
    assert len(faults.rules()) == 3


def test_empty_spec_disables():
    faults.configure("")
    assert not faults.enabled()
    assert faults.inject("http.put") is None


@pytest.mark.parametrize("bad", [
    "http.put",                 # no action
    "http.put:explode",         # unknown action
    "http.put:error:nonsense",  # bare field not a probability
    "http.put:error:1.5",       # probability out of range
])
def test_malformed_specs_raise(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.configure(bad)


def test_error_action_raises_connection_error():
    faults.configure("http.put:error")
    with pytest.raises(faults.InjectedFault) as ei:
        faults.inject("http.put", scope="s", key="k")
    # transport-shaped: real retry paths must treat it like ECONNRESET
    assert isinstance(ei.value, ConnectionError)
    assert "http.put" in str(ei.value)


def test_point_prefix_matching():
    faults.configure("http:error")
    with pytest.raises(faults.InjectedFault):
        faults.inject("http.get")
    # prefix is dot-anchored: "http" must not match "httpx"
    assert faults.inject("httpx.get") is None


def test_context_constraints_must_be_present_and_equal():
    faults.configure("worker:kill:rank=2:step=5")
    recorded = []
    faults._exit = recorded.append
    try:
        faults.inject("worker", rank=1, step=5)   # wrong rank
        faults.inject("worker", rank=2, step=4)   # wrong step
        faults.inject("worker", rank=2)           # step absent: no fire
        assert recorded == []
        faults.inject("worker", rank=2, step=5)
        assert recorded == [1]
    finally:
        faults._exit = os._exit


def test_kill_exit_code_override():
    faults.configure("worker:kill:code=83")
    recorded = []
    faults._exit = recorded.append
    try:
        faults.inject("worker")
        assert recorded == [83]
    finally:
        faults._exit = os._exit


def test_probability_is_seeded_and_deterministic():
    def fire_pattern():
        faults.configure("p:error:0.3:seed=7")
        pattern = []
        for _ in range(50):
            try:
                faults.inject("p")
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        return pattern

    a, b = fire_pattern(), fire_pattern()
    assert a == b, "same seed must fire identically"
    assert 0 < sum(a) < 50, "0.3 must neither always nor never fire"


def test_times_and_after_limits():
    faults.configure("p:error:times=2:after=1")
    outcomes = []
    for _ in range(5):
        try:
            faults.inject("p")
            outcomes.append("ok")
        except faults.InjectedFault:
            outcomes.append("err")
    # call 1 skipped (after=1), calls 2-3 fire (times=2), rest heal
    assert outcomes == ["ok", "err", "err", "ok", "ok"]


def test_delay_action_sleeps_in_caller():
    faults.configure("collective:delay:secs=0.25")
    slept = []
    orig = faults._sleep
    faults._sleep = slept.append
    try:
        assert faults.inject("collective", name="g0") is None
        assert slept == [0.25]
    finally:
        faults._sleep = orig


def test_cofired_rules_all_execute_before_error_raises():
    """A co-fired error rule must not swallow other fired rules'
    actions or accounting (their times budget is already spent)."""
    metrics.enable()
    faults.configure("p:error:times=1;p:delay:secs=0.1:times=1")
    slept = []
    orig = faults._sleep
    faults._sleep = slept.append
    try:
        with pytest.raises(faults.InjectedFault):
            faults.inject("p")
    finally:
        faults._sleep = orig
    assert slept == [0.1], "co-fired delay must run before the raise"
    snap = metrics.registry.snapshot()
    assert snap["hvd_faults_injected_total"]["p,delay"] == 1.0
    assert snap["hvd_faults_injected_total"]["p,error"] == 1.0


def test_retry_configure_from_knobs():
    from horovod_tpu.core.knobs import Knobs

    retry.configure(Knobs(retry_max_attempts=2, retry_base_delay_seconds=9.0))
    p = retry.default_policy()
    assert p.max_attempts == 2 and p.base_delay_s == 9.0


def test_flap_is_cooperative():
    faults.configure("discovery.poll:flap:times=1")
    assert faults.inject("discovery.poll") == "flap"
    assert faults.inject("discovery.poll") is None


def test_injection_counters_reach_registry():
    metrics.enable()
    faults.configure("p:error:times=1")
    with pytest.raises(faults.InjectedFault):
        faults.inject("p")
    snap = metrics.registry.snapshot()
    assert snap["hvd_faults_injected_total"]["p,error"] == 1.0


def test_disabled_inject_is_nearly_free():
    import time as _time

    assert not faults.enabled()
    n = 20000
    t0 = _time.perf_counter()
    for _ in range(n):
        faults.inject("http.put")
    per_call = (_time.perf_counter() - t0) / n
    assert per_call < 5e-6, f"disabled inject costs {per_call * 1e6:.2f}us"


# ------------------------------------------------------------- RetryPolicy

def test_retry_succeeds_after_transient_failures():
    clk = FakeClock()
    policy = retry.RetryPolicy(
        max_attempts=5, base_delay_s=0.1, multiplier=2.0, jitter_frac=0.0,
        clock=clk.clock, sleep=clk.sleep,
    )
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionResetError("transient")
        return "ok"

    metrics.enable()
    assert policy.call(flaky, point="t.point") == "ok"
    assert len(attempts) == 3
    # exponential, jitter-free schedule: 0.1 then 0.2
    assert clk.sleeps == [pytest.approx(0.1), pytest.approx(0.2)]
    snap = metrics.registry.snapshot()
    assert snap["hvd_retries_total"]["t.point"] == 2.0
    assert "hvd_retry_giveups_total" not in snap


def test_retry_gives_up_after_max_attempts():
    clk = FakeClock()
    policy = retry.RetryPolicy(
        max_attempts=3, base_delay_s=0.1, jitter_frac=0.0,
        clock=clk.clock, sleep=clk.sleep,
    )
    metrics.enable()
    attempts = []

    def always_fails():
        attempts.append(1)
        raise ConnectionResetError("down")

    with pytest.raises(ConnectionResetError):
        policy.call(always_fails, point="t.giveup")
    assert len(attempts) == 3
    snap = metrics.registry.snapshot()
    assert snap["hvd_retry_giveups_total"]["t.giveup"] == 1.0


def test_retry_max_delay_caps_backoff():
    clk = FakeClock()
    policy = retry.RetryPolicy(
        max_attempts=6, base_delay_s=1.0, max_delay_s=2.5, multiplier=4.0,
        jitter_frac=0.0, clock=clk.clock, sleep=clk.sleep,
    )
    calls = [0]

    def fails_forever():
        calls[0] += 1
        raise OSError("x")

    with pytest.raises(OSError):
        policy.call(fails_forever)
    assert clk.sleeps == [1.0, 2.5, 2.5, 2.5, 2.5]


def test_retry_jitter_is_seeded_and_bounded():
    def schedule():
        clk = FakeClock()
        policy = retry.RetryPolicy(
            max_attempts=4, base_delay_s=1.0, max_delay_s=100.0,
            jitter_frac=0.5, seed=11, clock=clk.clock, sleep=clk.sleep,
        )
        with pytest.raises(OSError):
            policy.call(lambda: (_ for _ in ()).throw(OSError("x")))
        return clk.sleeps

    a, b = schedule(), schedule()
    assert a == b, "seeded jitter must reproduce"
    for delay, nominal in zip(a, (1.0, 2.0, 4.0)):
        assert 0.5 * nominal <= delay <= 1.5 * nominal
    assert any(d != n for d, n in zip(a, (1.0, 2.0, 4.0)))


def test_retry_deadline_bounds_total_time():
    clk = FakeClock()
    policy = retry.RetryPolicy(
        max_attempts=100, base_delay_s=1.0, max_delay_s=1.0,
        jitter_frac=0.0, deadline_s=3.5, clock=clk.clock, sleep=clk.sleep,
    )
    calls = [0]

    def fails():
        calls[0] += 1
        raise OSError("x")

    with pytest.raises(OSError):
        policy.call(fails)
    # t=0 fail, sleep 1 (x3) → t=3 … at t>=3.5 the deadline expires
    assert clk.t - 100.0 <= 4.0
    assert calls[0] <= 5


def test_non_retryable_raises_immediately():
    policy, clk = _fast_policy(max_attempts=5)
    calls = [0]

    def bad_request():
        calls[0] += 1
        raise ValueError("not transport")

    with pytest.raises(ValueError):
        policy.call(bad_request)
    assert calls[0] == 1 and clk.sleeps == []


def test_deadline_uses_injected_monotonic_clock():
    clk = FakeClock(t0=50.0)
    d = retry.Deadline(10.0, clock=clk.clock)
    assert not d.expired()
    assert d.remaining() == pytest.approx(10.0)
    clk.t += 10.01
    assert d.expired()
    assert retry.Deadline(None, clock=clk.clock).remaining() == float("inf")


def test_retries_land_in_step_jsonl_and_summary(tmp_path, capsys):
    """Retries recorded mid-step surface in the per-step JSONL record
    and in scripts/metrics_summary.py output (the recovery-metrics
    visibility contract of docs/faults.md)."""
    import json
    import sys

    metrics.enable()
    log = str(tmp_path / "m.jsonl")
    metrics.step_stats.open_log(log)
    with metrics.step():
        metrics.record_retry("http.put")
        metrics.record_retry("http.put")
        metrics.record_retry_giveup("http.get")
    metrics.step_stats.close_log()
    rec = json.loads(open(log).read().splitlines()[-1])
    assert rec["retries"] == {"http.put": 2}
    assert rec["retry_giveups"] == {"http.get": 1}

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    try:
        import metrics_summary
    finally:
        sys.path.pop(0)
    assert metrics_summary.main([log]) == 0
    out = capsys.readouterr().out
    assert "control-plane retries: http.put=2" in out
    assert "retry GIVE-UPS: http.get=1" in out


# ------------------------------------------------- http client under chaos

def _kv_server():
    from horovod_tpu.runner.http.http_server import KVStoreServer

    srv = KVStoreServer()
    port = srv.start_server()
    return srv, port


def test_http_put_get_survive_injected_errors():
    from horovod_tpu.runner.http import http_client

    srv, port = _kv_server()
    try:
        metrics.enable()
        policy, _ = _fast_policy(max_attempts=5)
        retry.set_default_policy(policy)
        # first two attempts of each verb die client-side, then heal
        faults.configure("http.put:error:times=2;http.get:error:times=2")
        http_client.put("127.0.0.1", port, "sc", "k", b"v")
        assert http_client.get("127.0.0.1", port, "sc", "k") == b"v"
        snap = metrics.registry.snapshot()
        assert snap["hvd_retries_total"]["http.put"] == 2.0
        assert snap["hvd_retries_total"]["http.get"] == 2.0
        assert "hvd_retry_giveups_total" not in snap
    finally:
        srv.shutdown_server()


def test_http_server_injected_503_is_retried():
    from horovod_tpu.runner.http import http_client

    srv, port = _kv_server()
    try:
        policy, _ = _fast_policy(max_attempts=5)
        retry.set_default_policy(policy)
        faults.configure("http.server:error:times=2")
        http_client.put("127.0.0.1", port, "sc", "k", b"v2")
        assert http_client.get("127.0.0.1", port, "sc", "k") == b"v2"
    finally:
        srv.shutdown_server()


def test_http_get_404_is_not_retried():
    from horovod_tpu.runner.http import http_client

    srv, port = _kv_server()
    try:
        calls = []
        policy, _ = _fast_policy(max_attempts=5)
        retry.set_default_policy(policy)
        orig = urllib.request.urlopen

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        urllib.request.urlopen = counting
        try:
            assert http_client.get("127.0.0.1", port, "sc", "nope") is None
        finally:
            urllib.request.urlopen = orig
        assert len(calls) == 1, "404 must not burn retry attempts"
    finally:
        srv.shutdown_server()


def test_wait_for_key_monotonic_deadline_and_recovery():
    from horovod_tpu.runner.http import http_client

    srv, port = _kv_server()
    try:
        policy, _ = _fast_policy(max_attempts=2)
        retry.set_default_policy(policy)
        srv.store.setdefault("sc", {})["k"] = b"there"
        assert http_client.wait_for_key(
            "127.0.0.1", port, "sc", "k", timeout_s=5.0
        ) == b"there"
        with pytest.raises(TimeoutError):
            http_client.wait_for_key(
                "127.0.0.1", port, "sc", "missing", timeout_s=0.3
            )
    finally:
        srv.shutdown_server()


# ------------------------------------------------- discovery under chaos

def test_discovery_flap_and_retry():
    from horovod_tpu.runner.elastic.discovery import (
        ADDED, NO_UPDATE, REMOVED, FixedHosts, HostManager,
    )

    policy, _ = _fast_policy(max_attempts=4)
    retry.set_default_policy(policy)
    mgr = HostManager(FixedHosts({"a": 1, "b": 1}))
    assert mgr.update_available_hosts() == ADDED
    # one flapped poll: everything vanishes, then comes back
    faults.configure("discovery.poll:flap:times=1")
    assert mgr.update_available_hosts() == REMOVED
    assert mgr.current_hosts.count_available_slots() == 0
    assert mgr.update_available_hosts() == ADDED
    assert mgr.current_hosts.count_available_slots() == 2
    # transient poll errors retry inside one update call
    faults.configure("discovery.poll:error:times=2")
    assert mgr.update_available_hosts() == NO_UPDATE


# ------------------------------------------------------ stall watchdog

def test_stall_watchdog_aborts_stuck_collective():
    import threading

    import numpy as np

    from horovod_tpu.ops.eager_runtime import EagerRuntime

    release = threading.Event()

    def stuck_executor(batch, tensors):
        release.wait(timeout=30.0)  # the data plane never completes
        return {}

    metrics.enable()
    rt = EagerRuntime(
        rank=0, size=1, executor=stuck_executor, cycle_ms=1.0,
        stall_abort_s=0.4,
    )
    try:
        h = rt.allreduce_async("stuck", np.ones(4, np.float32))
        with pytest.raises(HorovodInternalError, match="stalled"):
            rt.synchronize(h, timeout_s=10.0)
        snap = metrics.registry.snapshot()
        assert snap["hvd_stall_aborts_total"][""] == 1.0
    finally:
        release.set()
        rt.shutdown()


def test_no_watchdog_when_disabled_completes_normally():
    import numpy as np

    from horovod_tpu.ops.eager_runtime import EagerRuntime

    rt = EagerRuntime(rank=0, size=1, cycle_ms=1.0, stall_abort_s=0.0)
    try:
        h = rt.allreduce_async("fine", np.ones(3, np.float32))
        out = rt.synchronize(h, timeout_s=10.0)
        np.testing.assert_allclose(out, np.ones(3, np.float32))
    finally:
        rt.shutdown()


def test_collective_fault_point_raises_internal_error():
    import numpy as np

    from horovod_tpu.ops.eager_runtime import EagerRuntime

    faults.configure("collective:error:name=g1")
    rt = EagerRuntime(rank=0, size=1, cycle_ms=1.0)
    try:
        with pytest.raises(HorovodInternalError):
            rt.allreduce_async("g1", np.ones(2, np.float32))
        # other tensors unaffected
        h = rt.allreduce_async("g2", np.ones(2, np.float32))
        rt.synchronize(h, timeout_s=10.0)
    finally:
        rt.shutdown()


# --------------------------------------------------------- preemption

def test_preemption_handler_commits_and_exits_with_code(tmp_path):
    state = ObjectState(step=7, lr=0.1)
    state.step = 12  # uncommitted progress
    ckpt = str(tmp_path / "emergency.pkl")
    codes = []
    assert preemption.install(
        state=state, checkpoint_path=ckpt, exit_fn=codes.append
    )
    os.kill(os.getpid(), signal.SIGTERM)
    assert codes == [preemption.PREEMPTED_EXIT_CODE]
    # the signal committed the in-flight step
    assert state._saved["step"] == 12
    assert os.path.exists(ckpt)

    fresh = ObjectState(step=0, lr=0.0)
    preemption.emergency_restore(fresh, ckpt)
    assert fresh.step == 12 and fresh.lr == pytest.approx(0.1)


def test_preemption_handler_fires_once(tmp_path):
    state = ObjectState(step=1)
    codes = []
    preemption.install(state=state, exit_fn=codes.append)
    os.kill(os.getpid(), signal.SIGTERM)
    os.kill(os.getpid(), signal.SIGTERM)
    assert codes == [preemption.PREEMPTED_EXIT_CODE]


def test_emergency_restore_rejects_unknown_attrs(tmp_path):
    state = ObjectState(step=3)
    path = str(tmp_path / "e.pkl")
    preemption.emergency_save(state, path)
    other = ObjectState(epoch=0)  # differently-shaped state
    with pytest.raises(ValueError, match="unregistered"):
        preemption.emergency_restore(other, path)


def test_emergency_save_is_atomic(tmp_path):
    state = ObjectState(step=5)
    path = str(tmp_path / "nested" / "e.pkl")
    preemption.emergency_save(state, path)
    epoch, saved = preemption.emergency_read(path)
    assert saved["step"] == 5
    assert not [p for p in os.listdir(tmp_path / "nested")
                if ".tmp." in p], "tmp file must be renamed away"


def test_driver_maps_preempted_code_to_aborted():
    """A worker exiting with PREEMPTED_EXIT_CODE reaches the barrier as
    ABORTED — terminal, but never blacklisted."""
    from horovod_tpu.runner.elastic.discovery import FixedHosts, HostManager
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.registration import ABORTED, SUCCESS
    from horovod_tpu.runner.elastic.settings import ElasticSettings

    first_round = {"fired": False}

    def exec_fn(command, env, slot, events):
        if slot.rank == 1 and not first_round["fired"]:
            first_round["fired"] = True
            return preemption.PREEMPTED_EXIT_CODE
        return 0

    driver = ElasticDriver(
        HostManager(FixedHosts({"hostA": 1, "hostB": 1})),
        ElasticSettings(min_np=2, max_np=2, timeout_s=10.0,
                        discovery_interval_s=0.1, reset_limit=4),
        command=["true"],
        env={},
        exec_fn=exec_fn,
    )
    try:
        assert driver.run() == 0
        assert not driver._host_manager.is_blacklisted("hostA")
        assert not driver._host_manager.is_blacklisted("hostB")
        assert driver._resets == 1, "preemption costs one round, no more"
    finally:
        driver.stop()
