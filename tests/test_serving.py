"""Serving subsystem: engine buckets, dynamic batching, HTTP front
end, multi-replica dispatch — and the loopback e2e the subsystem ships
against: a 2-replica serving set over the MLP model restored from a
real orbax checkpoint, driven by scripts/serving_loadgen.py --check,
surviving an injected replica death with zero client-visible failures,
and draining in-flight requests on SIGTERM before exiting 83.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO_ROOT))

from horovod_tpu import checkpoint  # noqa: E402
from horovod_tpu.runner.compute_service import ComputeService  # noqa: E402
from horovod_tpu.runner.util.secret import make_secret_key  # noqa: E402
from horovod_tpu.serving import (  # noqa: E402
    DynamicBatcher,
    InferenceEngine,
    QueueFull,
    ReplicaSet,
    RequestTimeout,
    ServingServer,
    parse_buckets,
    predict_remote,
)
IN_DIM = 8
FEATURES = (16, 8, 4)


def _mlp():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.mlp import MLP

    mod = MLP(features=FEATURES)
    params = mod.init(jax.random.PRNGKey(0),
                      jnp.ones((2, IN_DIM)))["params"]
    return mod, params


def _make_checkpoint(tmp_path) -> str:
    mod, params = _mlp()
    path = str(tmp_path / "serving_ckpt")
    checkpoint.save_model(
        path, params,
        metadata={"serving": {"model": "mlp",
                              "features": list(FEATURES),
                              "input_shape": [IN_DIM],
                              "dtype": "float32"}})
    return path


def _direct_forward(x):
    mod, params = _mlp()
    return np.asarray(mod.apply({"params": params}, np.asarray(x)))


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def test_parse_buckets_and_covering_choice():
    assert parse_buckets("1,4,16,64") == (1, 4, 16, 64)
    assert parse_buckets("16;4,4") == (4, 16)
    with pytest.raises(ValueError):
        parse_buckets("0,4")

    mod, params = _mlp()
    eng = InferenceEngine(
        lambda p, x: mod.apply({"params": p}, x), params,
        buckets=(1, 4, 16))
    assert [eng.bucket_for(n) for n in (1, 2, 4, 5, 16)] == [
        1, 4, 4, 16, 16]
    assert eng.bucket_for(40) == 16  # above top: __call__ chunks


def test_engine_from_checkpoint_matches_direct_forward(tmp_path):
    ck = _make_checkpoint(tmp_path)
    eng = InferenceEngine.from_checkpoint(ck, buckets=(1, 4, 8))
    eng.warmup((IN_DIM,))
    rng = np.random.RandomState(0)
    for n in (1, 3, 8, 20):  # padded, exact, and chunked-above-top
        x = rng.randn(n, IN_DIM).astype(np.float32)
        np.testing.assert_allclose(
            eng(x), _direct_forward(x), rtol=1e-5, atol=1e-5)
    # executables cached by (bucket, feature shape, dtype): the four
    # sizes above all share one shape and hit buckets 1/4/8 only
    assert {k[0] for k in eng._cache} == {1, 4, 8}
    assert all(k[1] == (IN_DIM,) for k in eng._cache)
    # a float64 request canonicalizes to the float32 program instead
    # of compiling a duplicate executable
    n_before = len(eng._cache)
    y64 = eng(rng.randn(2, IN_DIM))  # float64 input
    assert y64.dtype == np.float32
    assert len(eng._cache) == n_before
    # the checkpoint's declared input_shape is a contract: violating
    # it is a clean client error, not a flax shape crash (which would
    # read as replica death to the dispatch tier)
    with pytest.raises(ValueError, match="declared input_shape"):
        eng(rng.randn(2, IN_DIM + 1).astype(np.float32))


def test_batcher_rejects_request_larger_than_queue_capacity():
    """A request the queue can never hold is a client error (400-class
    ValueError), not retryable 429 backpressure."""
    bat = DynamicBatcher(lambda x: x, max_batch=4, max_wait_ms=0.0,
                         queue_limit=8).start()
    try:
        with pytest.raises(ValueError, match="admission capacity"):
            bat.submit(np.zeros((9, 2), np.float32))
    finally:
        bat.close()


def test_engine_on_mesh_replicated(hvd8):
    """Mesh path: params placed per parallel/ sharding rules (catch-all
    = replicated), I/O mesh-committed, numerics unchanged."""
    from horovod_tpu.parallel.mesh import make_mesh

    mod, params = _mlp()
    mesh = make_mesh()
    eng = InferenceEngine(
        lambda p, x: mod.apply({"params": p}, x), params,
        buckets=(1, 4), mesh=mesh)
    x = np.random.RandomState(1).randn(3, IN_DIM).astype(np.float32)
    np.testing.assert_allclose(
        eng(x), _direct_forward(x), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# batcher
# ---------------------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests():
    batches = []

    def run(x):
        batches.append(x.shape[0])
        return x * 2.0

    bat = DynamicBatcher(run, max_batch=16, max_wait_ms=150.0,
                         queue_limit=64).start()
    try:
        futs = [bat.submit(np.full((2, 3), float(i)), timeout_s=5.0)
                for i in range(4)]
        outs = [f.result(5.0) for f in futs]
        for i, y in enumerate(outs):
            np.testing.assert_allclose(y, np.full((2, 3), 2.0 * i))
        # all four 2-example requests coalesced into one 8-example run
        assert batches == [8]
    finally:
        bat.close()


def test_batcher_queue_full_and_draining():
    release = threading.Event()

    def run(x):
        release.wait(5.0)
        return x

    bat = DynamicBatcher(run, max_batch=4, max_wait_ms=0.0,
                         queue_limit=4).start()
    try:
        first = bat.submit(np.zeros((4, 2)), timeout_s=5.0)
        time.sleep(0.05)  # worker picked it up and is blocked in run()
        bat.submit(np.zeros((3, 2)), timeout_s=5.0)
        with pytest.raises(QueueFull):
            bat.submit(np.zeros((2, 2)), timeout_s=5.0)
        release.set()
        first.result(5.0)
    finally:
        bat.close()
    from horovod_tpu.serving import Draining

    with pytest.raises(Draining):
        bat.submit(np.zeros((1, 2)))


def test_batcher_expired_request_times_out_without_wasting_a_slot():
    executed = []

    def run(x):
        executed.append(x.shape[0])
        time.sleep(0.15)
        return x

    bat = DynamicBatcher(run, max_batch=4, max_wait_ms=0.0,
                         queue_limit=16).start()
    try:
        a = bat.submit(np.zeros((1, 2)), timeout_s=5.0)
        time.sleep(0.05)  # a is executing (sleeping in run)
        b = bat.submit(np.zeros((1, 2)), timeout_s=0.01)  # expires queued
        a.result(5.0)
        with pytest.raises(RequestTimeout):
            b.result(5.0)
        time.sleep(0.1)
        assert executed == [1]  # b never reached the model
    finally:
        bat.close()


def test_batcher_isolates_incompatible_shapes():
    """A request with a different example shape coalesces into its OWN
    batch — it can fail alone, but never fails or upcasts the
    homogeneous requests sharing its window."""
    batches = []

    def run(x):
        batches.append((x.shape, str(x.dtype)))
        return x

    bat = DynamicBatcher(run, max_batch=16, max_wait_ms=150.0,
                         queue_limit=64).start()
    try:
        a = bat.submit(np.zeros((2, 4), np.float32), timeout_s=5.0)
        odd = bat.submit(np.zeros((1, 9), np.float32), timeout_s=5.0)
        b = bat.submit(np.zeros((3, 4), np.float32), timeout_s=5.0)
        wide = bat.submit(np.zeros((1, 4), np.float64), timeout_s=5.0)
        for f, shape in ((a, (2, 4)), (odd, (1, 9)), (b, (3, 4)),
                         (wide, (1, 4))):
            assert f.result(5.0).shape == shape
        assert sorted(batches) == [
            ((1, 4), "float64"), ((1, 9), "float32"),
            ((5, 4), "float32")], batches
    finally:
        bat.close()


# ---------------------------------------------------------------------------
# server + replica set (in-process)
# ---------------------------------------------------------------------------

def test_server_auth_health_and_metrics_mount():
    key = b"per-job-secret"
    srv = ServingServer(lambda x, t: x + 1.0, key=key)
    port = srv.start()
    addr = f"127.0.0.1:{port}"
    try:
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            predict_remote(addr, x, 5.0, key=key), x + 1.0)
        # wrong auth -> 401, never reaches predict_fn
        body = json.dumps({"inputs": x.tolist()}).encode()
        req = urllib.request.Request(
            f"http://{addr}/v1/predict", data=body, method="POST",
            headers={"X-Hvd-Auth": "0" * 64})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5.0)
        assert ei.value.code == 401
        # probe routes stay open
        with urllib.request.urlopen(
                f"http://{addr}/healthz", timeout=5.0) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(
                f"http://{addr}/metrics", timeout=5.0) as r:
            assert r.status == 200
        # draining -> 503 for predicts, healthz says so
        srv.draining = True
        with pytest.raises(urllib.error.HTTPError) as ei:
            predict_remote(addr, x, 5.0, key=key)
        assert ei.value.code == 503
    finally:
        srv.shutdown()


def test_healthz_status_body_distinguishes_idle_from_wedged():
    """/healthz carries queue depth, in-flight count and bucket-cache
    size (unauthenticated, probe-friendly) — the replica entrypoint
    wires batcher.pending and engine.cached_executables through
    health_extra (replica_set.py)."""
    from horovod_tpu.serving.batcher import DynamicBatcher
    from horovod_tpu.serving.engine import InferenceEngine

    import jax.numpy as jnp

    engine = InferenceEngine(
        lambda p, x: x * p, jnp.float32(2.0), buckets=(1, 4),
        feature_shape=(3,))
    batcher = DynamicBatcher(engine, max_batch=4, max_wait_ms=1.0,
                             queue_limit=16).start()
    srv = ServingServer(
        batcher.__call__,
        health_extra=lambda: {"buckets": list(engine.buckets),
                              "queued": batcher.pending,
                              "bucket_cache": engine.cached_executables})
    port = srv.start()
    try:
        x = np.ones((2, 3), dtype=np.float32)
        np.testing.assert_allclose(
            predict_remote(f"127.0.0.1:{port}", x, 5.0), x * 2.0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5.0) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok"
        assert h["inflight"] == 0
        assert h["queued"] == 0
        assert h["buckets"] == [1, 4]
        assert h["bucket_cache"] >= 1  # the executed bucket is cached
    finally:
        srv.shutdown()
        batcher.close(drain=False)


def test_replica_set_least_loaded_failover_and_revival():
    good = ServingServer(lambda x, t: x * 3.0)
    bad = ServingServer(lambda x, t: (_ for _ in ()).throw(
        ConnectionError("replica dying")))
    gp, bp = good.start(), bad.start()
    rs = ReplicaSet({0: f"127.0.0.1:{gp}", 1: f"127.0.0.1:{bp}"})
    try:
        x = np.ones((2, 2), np.float32)
        # drive enough requests that the least-loaded router must try
        # replica 1 at least once; every one succeeds anyway
        for _ in range(6):
            np.testing.assert_allclose(rs.predict(x, 5.0), x * 3.0)
        assert 1 in rs.dead  # ejected after its 503
        assert 0 not in rs.dead
        rs.revive(1)
        assert 1 not in rs.dead
    finally:
        good.shutdown()
        bad.shutdown()


def test_replica_set_429_retries_elsewhere_without_ejecting():
    """Backpressure (429) from a saturated replica reroutes the
    request but keeps the replica in rotation — only death-shaped
    failures (transport, 5xx) eject."""
    from horovod_tpu.serving import QueueFull

    good = ServingServer(lambda x, t: x + 7.0)
    busy = ServingServer(lambda x, t: (_ for _ in ()).throw(
        QueueFull("admission queue at capacity")))
    gp, bp = good.start(), busy.start()
    rs = ReplicaSet({0: f"127.0.0.1:{gp}", 1: f"127.0.0.1:{bp}"})
    try:
        x = np.zeros((1, 2), np.float32)
        for _ in range(6):
            np.testing.assert_allclose(rs.predict(x, 5.0), x + 7.0)
        assert rs.dead == {}, rs.dead
    finally:
        good.shutdown()
        busy.shutdown()


# ---------------------------------------------------------------------------
# loopback e2e (subprocess replicas, the acceptance scenario)
# ---------------------------------------------------------------------------

def _spawn_replica(ckpt, index, svc_port, secret_str, tmp_path,
                   extra_env=None, extra_args=()):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(_REPO_ROOT),
        "HVD_TPU_SECRET_KEY": secret_str,
        # single CPU device is plenty for a replica and compiles faster
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    })
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.serving.replica_set",
         "--checkpoint", ckpt, "--index", str(index),
         "--register", f"127.0.0.1:{svc_port}",
         "--buckets", "1,4,8", *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(tmp_path),
    )
    return proc


def _await_ready(proc, timeout_s=120.0):
    """Read stdout until the READY line; returns the bound port."""
    out_lines = []
    result = {}

    def reader():
        for line in proc.stdout:
            out_lines.append(line)
            if "SERVING_REPLICA_READY" in line:
                result["port"] = int(line.rsplit("port=", 1)[1])
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(timeout_s)
    if "port" not in result:
        proc.kill()
        raise AssertionError(
            "replica never became ready; output:\n" + "".join(out_lines))
    return result["port"]


def _drain_stdout(proc):
    t = threading.Thread(
        target=lambda: proc.stdout.read(), daemon=True)
    t.start()
    return t


@pytest.fixture
def serving_pair(tmp_path):
    """ComputeService + 2 registered replica subprocesses; replica 1
    carries a fault rule that kills its executor after 2 batches."""
    secret = make_secret_key()
    svc = ComputeService(secret)
    ckpt = _make_checkpoint(tmp_path)
    procs = []
    try:
        procs.append(_spawn_replica(
            ckpt, 0, svc.port, secret.decode(), tmp_path))
        procs.append(_spawn_replica(
            ckpt, 1, svc.port, secret.decode(), tmp_path,
            extra_env={"HOROVOD_TPU_FAULT_SPEC":
                       "serving.replica_exec:error:after=2"}))
        ports = [_await_ready(p) for p in procs]
        for p in procs:
            _drain_stdout(p)
        yield {"secret": secret, "service": svc, "ports": ports,
               "procs": procs, "ckpt": ckpt, "tmp": tmp_path}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        svc.shutdown()


def test_malformed_input_is_400_through_the_stack_and_never_ejects():
    """A client error (empty batch) must come back 400 — not 500 —
    through replica AND front door, and must not read as replica death
    to the dispatch tier."""
    bat = DynamicBatcher(lambda x: x, max_batch=4, max_wait_ms=0.0,
                         queue_limit=16).start()
    replica = ServingServer(bat.__call__)
    rp = replica.start()
    rs = ReplicaSet({0: f"127.0.0.1:{rp}"})
    front = ServingServer(rs.predict)
    fp = front.start()
    try:
        body = json.dumps({"inputs": []}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{fp}/v1/predict", data=body,
            method="POST",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10.0)
        assert ei.value.code == 400, ei.value.code
        assert rs.dead == {}, rs.dead
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(
            predict_remote(f"127.0.0.1:{fp}", x, 5.0), x)
    finally:
        front.shutdown()
        replica.shutdown()
        bat.close()


def test_serving_e2e_failover_correctness_and_loadgen(serving_pair):
    """Acceptance (a), (b), (d): every response matches the direct
    forward pass while replica 1's executor is fault-killed mid-run,
    and the loadgen --check artifact carries real latency + batching
    metrics."""
    secret = serving_pair["secret"]
    ports = serving_pair["ports"]
    # the front door discovers replicas through the authenticated
    # registry, exactly like data-service trainers do
    workers = serving_pair["service"]._workers.get("serving", {})
    assert sorted(workers) == [0, 1], workers
    rs = ReplicaSet(workers, key=secret)
    front = ServingServer(rs.predict, key=secret)
    fport = front.start()
    try:
        # (a)+(b): 24 sequential requests; replica 1 dies after 2
        # executed batches, the set fails over, zero client failures
        rng = np.random.RandomState(7)
        for i in range(24):
            n = int(rng.randint(1, 5))
            x = rng.randn(n, IN_DIM).astype(np.float32)
            y = predict_remote(f"127.0.0.1:{fport}", x, 10.0, key=secret)
            np.testing.assert_allclose(
                y, _direct_forward(x), rtol=1e-4, atol=1e-4)
        assert 1 in rs.dead, (
            "fault-injected replica 1 was never ejected — the fault "
            f"rule did not fire (dead={rs.dead})")
        assert serving_pair["procs"][0].poll() is None

        # (d): the shipped load generator's smoke gate over the same
        # front door, scraping both replicas' /metrics
        artifact = serving_pair["tmp"] / "SERVING_e2e.json"
        env = dict(os.environ)
        env["HVD_TPU_SECRET_KEY"] = secret.decode()
        cmd = [
            sys.executable, str(_REPO_ROOT / "scripts/serving_loadgen.py"),
            "--url", f"http://127.0.0.1:{fport}",
            "--requests", "40", "--concurrency", "4",
            "--input-shape", str(IN_DIM), "--examples", "1:4",
            "--seed", "3", "--out", str(artifact), "--check",
            "--scrape", f"http://127.0.0.1:{ports[0]}/metrics",
            "--scrape", f"http://127.0.0.1:{ports[1]}/metrics",
        ]
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=180, env=env)
        assert res.returncode == 0, (
            f"loadgen --check failed:\n{res.stdout}\n{res.stderr}")
        rep = json.loads(artifact.read_text())
        assert rep["requests_failed"] == 0
        assert rep["requests_ok"] == 40
        for q in ("p50", "p95", "p99"):
            assert rep["latency_ms"][q] > 0, rep["latency_ms"]
        assert rep["batch_fill_ratio_mean"] > 0
        assert rep["padding_waste_frac"] is not None
    finally:
        front.shutdown()


def test_serving_e2e_sigterm_drains_inflight_then_exits_83(tmp_path):
    """Acceptance (c): SIGTERM while a request sits in the batching
    window → the response still arrives, then the process exits with
    the preemption code (83), which the elastic driver maps to ABORTED
    (no blacklist)."""
    secret = make_secret_key()
    svc = ComputeService(secret)
    ckpt = _make_checkpoint(tmp_path)
    # a wide co-arrival window so the in-flight request is guaranteed
    # to still be queued when the signal lands
    proc = _spawn_replica(ckpt, 0, svc.port, secret.decode(), tmp_path,
                          extra_args=("--max-wait-ms", "3000"))
    try:
        port = _await_ready(proc)
        _drain_stdout(proc)
        x = np.random.RandomState(5).randn(2, IN_DIM).astype(np.float32)
        got = {}

        def requester():
            try:
                got["y"] = predict_remote(
                    f"127.0.0.1:{port}", x, 20.0, key=secret)
            except Exception as e:  # noqa: BLE001
                got["error"] = e

        t = threading.Thread(target=requester, daemon=True)
        t.start()
        time.sleep(0.4)  # request admitted, sitting in the 3s window
        assert proc.poll() is None
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=30)
        assert "error" not in got, f"drained request failed: {got}"
        np.testing.assert_allclose(
            got["y"], _direct_forward(x), rtol=1e-4, atol=1e-4)
        rc = proc.wait(timeout=30)
        from horovod_tpu.elastic.preemption import PREEMPTED_EXIT_CODE

        assert rc == PREEMPTED_EXIT_CODE, rc
        # post-drain: the server refuses new work rather than hanging
        with pytest.raises((urllib.error.URLError, ConnectionError,
                            OSError)):
            predict_remote(f"127.0.0.1:{port}", x, 2.0, key=secret)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        svc.shutdown()


# ---------------------------------------------------------------------------
# request tracing (PR 10): one id from the front door through the
# batcher into the flight ring — a slow /v1/predict is one grep away
# ---------------------------------------------------------------------------

def test_request_id_sanitization_units():
    from horovod_tpu.serving import tracing

    assert tracing.sanitize("abc-123_x.Y:z") == "abc-123_x.Y:z"
    # unsafe chars stripped, length bounded
    assert tracing.sanitize("réq/abc-123!!") == "rqabc-123"
    assert len(tracing.sanitize("a" * 500)) == 64
    # a client must not be able to blank out tracing
    minted = tracing.sanitize("//${}")
    assert minted and minted.isalnum()
    assert tracing.sanitize("") != tracing.sanitize("")


def test_request_id_propagates_front_door_to_replica_traces():
    """The client's X-Request-Id travels front door -> dispatch ->
    replica -> batcher, every tier stamping the SAME (sanitized) id
    into its flight events, and the reply echoes it."""
    from horovod_tpu.utils import flight

    flight.reset()
    flight.configure(enabled_override=True, rank=0, handlers=False)
    bat = DynamicBatcher(lambda x: x * 2.0, max_batch=4, max_wait_ms=0.0,
                         queue_limit=16).start()
    replica = ServingServer(bat.__call__)
    rp = replica.start()
    rs = ReplicaSet({0: f"127.0.0.1:{rp}"})
    front = ServingServer(rs.predict)
    fp = front.start()
    try:
        x = np.ones((2, 3), np.float32)
        body = json.dumps({"inputs": x.tolist()}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{fp}/v1/predict", data=body,
            method="POST",
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "träce/me-42!"})
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            rid = resp.headers.get("X-Request-Id")
            payload = json.loads(resp.read())
        np.testing.assert_allclose(
            np.asarray(payload["outputs"], np.float32), x * 2.0)
        assert rid == "trceme-42"  # sanitized form of the client id

        events = flight.snapshot()
        # both HTTP tiers logged the request under the same id
        reqs = [e for e in events
                if e[3] == "serving_request" and e[4] == rid]
        assert len(reqs) == 2, events
        assert all(e[5]["code"] == 200 for e in reqs)
        # the dispatch hop names the id it forwarded
        disp = [e for e in events if e[3] == "serving_dispatch"]
        assert disp and disp[-1][5]["req"] == rid
        # the batch that served it carries the id in its member list
        batches = [e for e in events if e[3] == "serving_batch"]
        assert batches and rid in batches[-1][5]["ids"]

        # no client header -> a fresh id is minted, never blank
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{fp}/v1/predict", data=body,
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2, timeout=10.0) as resp:
            minted = resp.headers.get("X-Request-Id")
        assert minted  # front door minted one
    finally:
        front.shutdown()
        replica.shutdown()
        bat.close()
        # reset() alone: a bare configure() would re-ENABLE the ring
        # and install signal handlers for the rest of the session
        flight.reset()
