"""init/rank/size/topology tests.

Reference analog: the query surface exercised throughout
test/parallel/test_torch.py (hvd.rank/size/local_rank) and
test/single/test_run.py's topology helpers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.core.basics import _parse_mesh_spec


def test_init_and_sizes(hvd8):
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.rank() == 0  # controller owns device 0
    assert hvd.local_rank() == 0
    assert hvd.is_homogeneous()


def test_not_initialized_raises():
    with pytest.raises(hvd.HorovodTpuError):
        hvd.size()


def test_double_init_is_noop(hvd8):
    hvd.init()
    assert hvd.size() == 8


def test_build_flags(hvd8):
    assert hvd.xla_built() and hvd.xla_enabled()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.gloo_built()


def test_rank_inside_shard_map(hvd8):
    mesh = hvd.mesh()

    def body(x):
        return x + hvd.rank()

    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"))
    )(jnp.zeros(8))
    np.testing.assert_array_equal(np.asarray(out), np.arange(8.0))


def test_local_rank_inside_shard_map(hvd8):
    mesh = hvd.mesh()

    def body(x):
        return x + hvd.local_rank()

    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"))
    )(jnp.zeros(8))
    np.testing.assert_array_equal(np.asarray(out), np.arange(8.0) % 8)


def test_mesh_spec_parsing():
    assert _parse_mesh_spec("dp=8", 8) == ((8,), ("dp",))
    assert _parse_mesh_spec("dp=4,tp=2", 8) == ((4, 2), ("dp", "tp"))
    assert _parse_mesh_spec("dp=-1,tp=2", 8) == ((4, 2), ("dp", "tp"))
    with pytest.raises(ValueError):
        _parse_mesh_spec("dp=3", 8)
    with pytest.raises(ValueError):
        _parse_mesh_spec("dp=-1,tp=-1", 8)


def test_custom_mesh_spec(monkeypatch):
    monkeypatch.setenv("HOROVOD_MESH", "dp=4,tp=2")
    hvd.init()
    assert hvd.mesh().axis_names == ("dp", "tp")
    assert hvd.size() == 8  # dp_axis defaults to all axes
    hvd.shutdown()


def test_init_with_comm_rejected():
    with pytest.raises(ValueError):
        hvd.init(comm=object())


def test_topology_op_family(hvd8):
    """In-graph topology queries (reference tensorflow/mpi_ops.py
    rank_op/size_op/...): plain jnp values eagerly, traced values that
    resolve per-device inside shard_map."""
    import jax
    from jax.sharding import PartitionSpec as P

    assert int(hvd.size_op()) == 8
    assert int(hvd.local_size_op()) == 8
    assert int(hvd.rank_op()) == 0  # coordinator-owned outside spmd
    assert int(hvd.local_rank_op()) == 0
    assert int(hvd.process_set_included_op(0)) == 1

    ps = hvd.add_process_set([1, 3, 5])
    try:
        assert int(hvd.size_op(process_set_id=ps.process_set_id)) == 3

        def f():
            # traced forms: per-device rank, set-rank table lookup,
            # inclusion mask
            return (hvd.rank_op().reshape(1),
                    hvd.rank_op(ps.process_set_id).reshape(1),
                    hvd.process_set_included_op(
                        ps.process_set_id).reshape(1))

        r, sr, inc = jax.jit(shard_map(
            f, mesh=hvd.mesh(), in_specs=(),
            out_specs=(P("hvd"), P("hvd"), P("hvd")),
            check_vma=False))()
        assert list(r) == list(range(8))
        assert list(inc) == [0, 1, 0, 1, 0, 1, 0, 0]
        assert [int(sr[g]) for g in (1, 3, 5)] == [0, 1, 2]
        # non-members carry the documented -1 sentinel (mask with
        # process_set_included_op before indexing)
        assert [int(sr[g]) for g in (0, 2, 4, 6, 7)] == [-1] * 5
    finally:
        hvd.remove_process_set(ps)


def test_mpi_threads_supported_parity():
    assert hvd.mpi_threads_supported() is False
