"""Online fleet-health monitor (horovod_tpu/health/, docs/health.md).

Covers: the disabled no-op fast path (< 1 us/call, the flight/metrics
discipline), burn-rate window math with an injectable clock, envelope
hysteresis, rule-spec parsing (including loud failures), detector
classification on synthetic step records, the fleet evaluator's
straggler/silent-rank verdict, alert transitions -> incident records +
anomaly-triggered flight/prof capture, the serving-latency observer
path, the SLO-labeled serving histograms, the serving /healthz + /health
surfaces, knob wiring through hvd.init, and (slow) the world-2
health_check.py gate."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from horovod_tpu import health
from horovod_tpu.health import detectors, fleet, rules
from horovod_tpu.utils import flight, metrics, prof

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_health():
    health.reset()
    metrics.reset()
    flight.reset()
    prof.reset()
    yield
    health.reset()
    metrics.reset()
    flight.reset()
    prof.reset()


# ------------------------------------------------------------ no-op path

def test_disabled_observes_nothing():
    assert not health.enabled()
    health.observe_step({"step": 1, "step_time_s": 9.0})
    health.observe_serving("ttft", "interactive", 9.0)
    assert health.verdict() == {"health": "off", "alerts_active": 0}
    assert health.incident_count() == 0


def test_disabled_overhead_under_1us_per_call():
    """HOROVOD_HEALTH=0 acceptance: the disabled observer (module flag
    check + return) must cost < 1 us per call — and the metrics-side
    slot stays None so an instrumented step never even reaches it."""
    assert not health.enabled()
    assert metrics._step_observer is None
    n = 200_000
    obs = health.observe_step
    rec = {"step": 1, "step_time_s": 0.01}
    t0 = time.perf_counter()
    for _ in range(n):
        obs(rec)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 1e-6, f"no-op observe costs {per_call * 1e9:.0f} ns"


# ------------------------------------------------------------ burn rate

def test_burn_rate_window_math():
    t = [1000.0]
    br = rules.BurnRate(target_s=0.5, objective=0.99, fast_s=30.0,
                        slow_s=300.0, clock=lambda: t[0])
    # 50 good samples over 50s: zero burn
    for _ in range(50):
        t[0] += 1.0
        br.observe(0.1)
    assert br.burn(30.0) == 0.0
    assert not br.firing()
    # all-bad stream: burn = bad_frac / budget = 1 / 0.01 = 100
    for _ in range(400):
        t[0] += 1.0
        br.observe(2.0)
    assert br.burn(30.0) == pytest.approx(100.0)
    assert br.burn(300.0) > 6.0
    assert br.firing()
    assert not br.cleared()
    # recovery: the fast window refills with good samples
    for _ in range(35):
        t[0] += 1.0
        br.observe(0.1)
    assert br.burn(30.0) < 1.0
    assert br.cleared()
    # hysteresis through state(): firing holds until cleared
    assert br.state(currently_firing=True) is False


def test_burn_rate_fires_only_on_both_windows():
    """A short error burst trips the fast window but not the slow one:
    no page (the multiwindow discipline's whole point)."""
    t = [0.0]
    br = rules.BurnRate(target_s=0.5, objective=0.99, fast_s=30.0,
                        slow_s=300.0, clock=lambda: t[0])
    for _ in range(288):
        t[0] += 1.0
        br.observe(0.1)
    for _ in range(12):
        t[0] += 1.0
        br.observe(2.0)
    assert br.burn(30.0) >= 14.4       # fast window is all-bad enough
    assert br.burn(300.0) < 6.0        # but the slow window is not
    assert not br.firing()


def test_burn_rate_rejects_bad_objective():
    with pytest.raises(rules.RuleSpecError):
        rules.BurnRate(target_s=0.5, objective=1.0)


# ------------------------------------------------------------ envelope

def test_envelope_hysteresis():
    env = rules.Envelope(factor=1.5, window=16, min_samples=4,
                         breach_n=2, clear_n=3)
    for _ in range(6):
        env.observe(0.1)
    # one breaching sample is not enough (breach_n=2)
    env.observe(1.0)
    assert not env.state(currently_firing=False)
    env.observe(1.0)
    assert env.state(currently_firing=False)
    # clearing needs clear_n consecutive in-envelope samples
    env.observe(0.1)
    assert env.state(currently_firing=True)
    env.observe(0.1)
    env.observe(0.1)
    assert not env.state(currently_firing=True)


def test_envelope_drop_side():
    env = rules.Envelope(drop=0.3, window=16, min_samples=4,
                         breach_n=1, clear_n=1)
    for _ in range(5):
        env.observe(1.0)
    env.observe(0.5)  # 50% under the median: breach
    assert env.state(currently_firing=False)


# ------------------------------------------------------------ rule parsing

def test_default_rules_parse():
    rs = rules.parse_rules(rules.DEFAULT_RULES)
    assert [r.kind for r in rs].count("envelope") == 2
    assert [r.kind for r in rs].count("burn") == 3
    by_name = {r.name: r for r in rs}
    assert by_name["ttft_interactive"].slo == "interactive"
    assert by_name["step_time_envelope"].classes() == ("straggler-host",)


@pytest.mark.parametrize("spec", [
    "noname",                                    # no kind
    "x:watch:signal=ttft",                       # unknown kind
    "x:burn:signal=ttft",                        # burn without target
    "x:burn:target=0.5",                         # no signal
    "x:envelope:signal=mfu",                     # envelope without bound
    "x:burn:signal=ttft:target=abc",             # non-numeric
    "x:burn:signal=ttft:garbage",                # not key=value
])
def test_malformed_rules_fail_loudly(spec):
    with pytest.raises(rules.RuleSpecError):
        rules.parse_rules(spec)


def test_rule_engine_transitions():
    t = [0.0]
    eng = rules.RuleEngine(rules.parse_rules(
        "env:envelope:signal=step_time:factor=1.5:min=3:breach=1:clear=2"
    ), clock=lambda: t[0])
    for _ in range(4):
        eng.observe("step_time", 0.1)
    assert eng.evaluate() == []
    eng.observe("step_time", 1.0)
    (tr,) = eng.evaluate()
    assert tr["rule"] == "env" and tr["state"] == "fire"
    assert tr["classes"] == ["straggler-host"]
    assert eng.active_count() == 1
    eng.observe("step_time", 0.1)
    eng.observe("step_time", 0.1)
    (tr,) = eng.evaluate()
    assert tr["state"] == "clear"
    assert eng.active_count() == 0


# ------------------------------------------------------------ detectors

def _warm(det, n=10, dt=0.01, **extra):
    for i in range(n):
        det.update({"step": i, "step_time_s": dt, **extra})


def test_detector_straggler_host():
    det = detectors.StepDetectors(window=16, min_steps=4)
    _warm(det)
    (a,) = det.update({"step": 99, "step_time_s": 0.1})
    assert a["class"] == "straggler-host"
    assert a["signal"] == "step_time"
    assert a["reference"] == pytest.approx(0.01)


def test_detector_slow_link_from_wire_drift():
    det = detectors.StepDetectors(window=16, min_steps=4)
    _warm(det, attribution={"exposed_wire_frac": 0.1})
    anomalies = det.update({
        "step": 99, "step_time_s": 0.1,
        "attribution": {"exposed_wire_frac": 0.5},
    })
    assert {a["class"] for a in anomalies} == {"slow-link"}


def test_detector_input_bound_from_idle_rise():
    det = detectors.StepDetectors(window=16, min_steps=4)
    _warm(det, attribution={"idle_frac": 0.05})
    anomalies = det.update({
        "step": 99, "step_time_s": 0.1,
        "attribution": {"idle_frac": 0.6},
    })
    assert anomalies[0]["class"] == "input-bound"


def test_detector_compute_regression_from_mfu():
    det = detectors.StepDetectors(window=16, min_steps=4)
    _warm(det, mfu=0.5)
    anomalies = det.update({"step": 99, "step_time_s": 0.1, "mfu": 0.1})
    assert {a["class"] for a in anomalies} == {"compute-regression"}


def test_detector_retry_burst_and_queue_saturation():
    det = detectors.StepDetectors(window=16, min_steps=4,
                                  retry_burst=3, queue_factor=2.0)
    _warm(det, queue_depth=1)
    anomalies = det.update({
        "step": 99, "step_time_s": 0.01, "queue_depth": 8,
        "retries": {"http.put": 2}, "retry_giveups": {"http.put": 1},
    })
    classes = {a["class"] for a in anomalies}
    assert classes == {"slow-link", "queue-saturation"}


def test_detector_autotune_baseline_breach():
    """The persisted per-(model, topology) baseline guards steps even
    when THIS run's rolling median has drifted up with them."""
    det = detectors.StepDetectors(window=16, min_steps=4,
                                  baseline_step_s=0.01)
    _warm(det, n=10, dt=0.03)  # slow all run: rolling median 0.03
    (a,) = det.update({"step": 99, "step_time_s": 0.03})
    assert a["signal"] == "step_time_baseline"
    assert a["reference"] == pytest.approx(0.01)


def test_detector_spike_does_not_drag_its_reference():
    det = detectors.StepDetectors(window=16, min_steps=4)
    _warm(det)
    det.update({"step": 98, "step_time_s": 0.1})
    # the spike is IN the window now, but the median held
    (a,) = det.update({"step": 99, "step_time_s": 0.1})
    assert a["reference"] == pytest.approx(0.01)


def test_serving_detector_queue_wait_buildup():
    det = detectors.ServingDetectors(window=32, factor=2.0,
                                     floor_s=0.05, min_samples=8)
    for _ in range(10):
        assert det.update_queue_wait(0.01) == []
    out = []
    for _ in range(10):
        out.extend(det.update_queue_wait(0.5))
    assert out and out[0]["class"] == "queue-saturation"


# ------------------------------------------------------------ fleet view

def _summary(rank, now, recent=0.1, alerts=None):
    return {"rank": rank, "time_unix": now,
            "step_time_recent_s": recent, "steps": 20,
            "alerts": alerts or {}}


def test_fleet_ok_when_uniform():
    now = time.time()
    v = fleet.evaluate({r: _summary(r, now) for r in range(4)},
                       now_unix=now)
    assert v["status"] == "ok"
    assert v["suspected_straggler_ranks"] == []
    assert v["ranks"] == 4


def test_fleet_names_self_reported_straggler():
    now = time.time()
    s = {r: _summary(r, now) for r in range(4)}
    s[2]["alerts"] = {"step_time_envelope": {
        "active": True, "classes": ["straggler-host"]}}
    v = fleet.evaluate(s, now_unix=now)
    assert v["status"] == "degraded"
    assert v["suspected_straggler_ranks"] == [2]
    assert v["alerts_active"] == 1


def test_fleet_names_median_outlier():
    now = time.time()
    s = {r: _summary(r, now) for r in range(4)}
    s[3]["step_time_recent_s"] = 0.5  # 5x the fleet median
    v = fleet.evaluate(s, now_unix=now)
    assert v["suspected_straggler_ranks"] == [3]
    assert "straggler-host" in v["by_rank"]["3"]["classes"]


def test_fleet_silent_rank_is_suspect():
    now = time.time()
    s = {r: _summary(r, now) for r in range(3)}
    s[1]["time_unix"] = now - 60.0
    v = fleet.evaluate(s, now_unix=now)
    assert v["silent_ranks"] == [1]
    assert 1 in v["suspected_straggler_ranks"]


def test_fleet_empty_is_unknown_and_garbage_is_dropped():
    assert fleet.evaluate({})["status"] == "unknown"
    parsed = fleet.parse_summaries({
        "0": json.dumps({"rank": 0, "time_unix": 1.0}).encode(),
        "1": b"\x80\x04not json",         # never unpickled, just dropped
        "2@podA": json.dumps({"time_unix": 1.0}).encode(),
    })
    assert set(parsed) == {"0", "2@podA"}
    assert parsed["2@podA"]["rank"] == 2
    assert parsed["2@podA"]["pod"] == "podA"


# ------------------------------------------------ transitions + capture

def test_alert_fire_writes_incident_and_captures(tmp_path):
    flight.enable()
    incident = str(tmp_path / "incidents.jsonl")
    health.configure(
        enabled_override=True, rank=3, endpoint=None, interval_s=60.0,
        rules="env:envelope:signal=step_time:factor=1.5:min=3"
              ":breach=1:clear=2",
        incident_file=incident, capture=True)
    for i in range(4):
        health.observe_step({"step": i, "step_time_s": 0.01})
    assert health.verdict()["health"] == "ok"
    dumps_before = flight.dump_count()
    health.observe_step({"step": 5, "step_time_s": 1.0})
    v = health.verdict()
    assert v["health"] == "degraded" and v["alerts"] == ["env"]
    # forensics: a flight dump fired and the profiler owes one sample
    assert flight.dump_count() == dumps_before + 1
    assert prof._force_next
    # recovery clears the verdict and appends the clear record
    health.observe_step({"step": 6, "step_time_s": 0.01})
    health.observe_step({"step": 7, "step_time_s": 0.01})
    assert health.verdict()["health"] == "ok"
    with open(incident) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    assert [r["state"] for r in recs] == ["fire", "clear"]
    assert all(r["rank"] == 3 and r["rule"] == "env" for r in recs)
    assert health.incident_count() == 2


def test_alert_gauge_rides_the_exposition():
    health.configure(
        enabled_override=True, endpoint=None, interval_s=60.0,
        rules="env:envelope:signal=step_time:factor=1.5:min=3"
              ":breach=1:clear=2", capture=False)
    # past BOTH warmups: the envelope's (min=3) and the default
    # detector's (min_steps=8), so the anomaly counter moves too
    for i in range(9):
        health.observe_step({"step": i, "step_time_s": 0.01})
    health.observe_step({"step": 9, "step_time_s": 1.0})
    _, body = metrics.exposition()
    text = body.decode()
    assert 'hvd_alert_active{rule="env"} 1' in text
    assert 'hvd_health_incidents_total{rule="env",state="fire"}' in text
    assert "hvd_health_anomalies_total" in text
    assert metrics.lint_exposition(text) == []


def test_flight_anomaly_dump_rate_limited():
    flight.enable()
    assert flight.anomaly_dump("rule_a") is not None
    assert flight.anomaly_dump("rule_a") is None        # limited
    assert flight.anomaly_dump("rule_b") is not None    # per-rule
    assert flight.anomaly_dump("rule_a",
                               min_interval_s=0.0) is not None


def test_prof_request_sample_forces_next_step():
    prof.configure(every=0)  # sampling off by knobs
    prof.request_sample("anomaly:test")
    metrics.enable()
    with metrics.step():
        pass
    assert prof.sample_count() >= 1
    assert not prof._force_next


# ------------------------------------------------ metrics-stream wiring

def test_step_observer_feeds_detectors():
    metrics.enable()
    health.configure(enabled_override=True, endpoint=None,
                     interval_s=60.0, capture=False)
    for _ in range(3):
        with metrics.step():
            pass
    assert health.summary()["steps"] == 3
    # disable unhooks: further steps are not observed
    health.disable()
    with metrics.step():
        pass
    assert health.summary()["steps"] == 3


def test_serving_observer_feeds_burn_rules():
    health.configure(
        enabled_override=True, endpoint=None, interval_s=60.0,
        rules="qw:burn:signal=queue_wait:target=0.01:objective=0.5"
              ":fast=30:slow=30:fast_factor=1:slow_factor=1",
        capture=False)
    for _ in range(10):
        metrics.record_serving_queue_wait(0.5, slo="interactive")
    health._tick()  # serving rules advance on the publisher tick
    assert health.verdict()["health"] == "degraded"


def test_serving_histograms_carry_slo_label():
    metrics.enable()
    metrics.record_serving_ttft(0.12, slo="interactive")
    metrics.record_serving_tpot(0.03, slo="interactive")
    metrics.record_serving_queue_wait(0.01, slo="batch")
    _, body = metrics.exposition()
    text = body.decode()
    assert 'hvd_serving_ttft_seconds_count{slo="interactive"} 1' in text
    assert 'hvd_serving_tpot_seconds_count{slo="interactive"} 1' in text
    assert 'hvd_serving_queue_wait_seconds_count{slo="batch"} 1' in text
    assert metrics.lint_exposition(text) == []


def test_summary_publish_roundtrip():
    from horovod_tpu.runner.http.http_server import KVStoreServer

    kv = KVStoreServer()
    port = kv.start_server()
    try:
        health.configure(enabled_override=True, rank=1,
                         endpoint=("127.0.0.1", port),
                         interval_s=60.0, capture=False)
        health._tick()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=5) as r:
            v = json.loads(r.read())
        assert v["status"] == "ok"
        assert v["ranks"] == 1
        assert "1" in v["by_rank"]
    finally:
        kv.shutdown_server()


def test_serving_server_health_routes():
    from horovod_tpu.serving.server import ServingServer

    health.configure(enabled_override=True, endpoint=None,
                     interval_s=60.0, capture=False)
    srv = ServingServer(predict_fn=lambda x, t: x)
    port = srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok"
        assert h["health"] == "ok"           # the folded-in verdict
        assert h["alerts_active"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=5) as r:
            v = json.loads(r.read())
        assert v["health"] == "ok"
    finally:
        srv.shutdown()


# ------------------------------------------------------------ knob wiring

def test_default_off(monkeypatch):
    import horovod_tpu as hvd

    hvd.init()
    try:
        assert not health.enabled()
    finally:
        hvd.shutdown()


def test_knob_enables_and_shutdown_disables(monkeypatch, tmp_path):
    import horovod_tpu as hvd

    incident = str(tmp_path / "inc.jsonl")
    monkeypatch.setenv("HVD_TPU_HEALTH", "1")
    monkeypatch.setenv("HVD_TPU_HEALTH_STEP_TIME_FACTOR", "2.5")
    monkeypatch.setenv("HVD_TPU_HEALTH_INCIDENT_FILE", incident)
    hvd.init()
    try:
        assert health.enabled()
        assert metrics.enabled()  # health implies metrics
        assert health._step_det.step_time_factor == 2.5
        assert health._incident_path == incident
    finally:
        hvd.shutdown()
    assert not health.enabled()


def test_bad_rules_knob_fails_loudly():
    class _Knobs:
        health_enabled = True
        health_rules = "broken-rule"

    with pytest.raises(rules.RuleSpecError):
        health.configure(_Knobs())
    assert not health.enabled()


# ------------------------------------------------------------ e2e gate

@pytest.mark.slow
def test_health_check_gate():
    """The world-2 smoke gate end to end: injected rank-1 delay named
    live, alert fires and clears, forensics on the sink."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts",
                                      "health_check.py")],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout
    assert '"ok": true' in proc.stdout
