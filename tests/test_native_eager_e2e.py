"""End-to-end native eager pipeline: N real processes, the public hvd
API, the C++ negotiation control plane, and the XLA executor data plane.

This is the integration the reference calls its defining property: a
user's per-op eager calls flow through negotiation into the data plane
(/root/reference/horovod/common/operations.cc:273 PerformOperation, :1400
EnqueueTensorAllreduces). Workers submit tensors in DIFFERENT orders with
DISTINCT per-rank values; numeric results must still be correct — the
consistency only the controller can provide.

World mechanics: each worker is one JAX process with one CPU device,
joined through jax.distributed (gloo CPU collectives), exactly how the
launcher wires TPU pod hosts (SURVEY.md §2.6). The axon sitecustomize is
dropped from PYTHONPATH because its PJRT plugin pins single-process
topology.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "native_eager_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _worker_env(rank: int, size: int, jax_port: int, native_port: int):
    env = dict(os.environ)
    # drop the axon TPU tunnel: its PJRT plugin registers a 1-process
    # topology that blocks multi-process CPU worlds
    env.pop("PYTHONPATH", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual 8-device split in workers
    # what runner/exec_run.py slot_env publishes
    env["HVD_TPU_NATIVE"] = "1"
    env["HVD_TPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{jax_port}"
    env["HVD_TPU_NUM_PROCESSES"] = str(size)
    env["HVD_TPU_PROCESS_ID"] = str(rank)
    env["HVD_TPU_NATIVE_COORDINATOR_ADDR"] = "127.0.0.1"
    env["HVD_TPU_NATIVE_COORDINATOR_PORT"] = str(native_port)
    return env


def _run_world(size: int, timeout_s: float = 240.0):
    jax_port, native_port = _free_port(), _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER],
            env=_worker_env(r, size, jax_port, native_port),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=_REPO,
        )
        for r in range(size)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout_s)
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = {}
    for r, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        line = next(
            (ln for ln in out.splitlines() if ln.startswith("RESULT ")), None
        )
        assert line is not None, f"rank {r} printed no RESULT:\n{out}"
        results[r] = json.loads(line[len("RESULT "):])
    return results


# world-3 (28s of subprocess spawns — the process-set scenarios need
# size >= 3) rides the slow tier so tier-1 stays inside its 870s
# budget (PR-1/PR-5 precedent: the largest test moves, coverage
# stays); world-2 keeps every other scenario in tier-1, and the
# subset logic world-3 adds is unit-covered by test_process_sets /
# test_native_runtime
@pytest.mark.parametrize(
    "size", [2, pytest.param(3, marks=pytest.mark.slow)])
def test_native_eager_end_to_end(size):
    out = _run_world(size)
    for r in range(size):
        for key in (
            "allreduce_ok", "average_ok", "allgather_ok", "broadcast_ok",
            "reducescatter_ok", "alltoall_ok", "grouped_ok",
            "grouped_sync_ok",
            "grouped_allgather_ok", "grouped_reducescatter_ok",
            "sparse_ok", "fast_path_ok", "dist_opt_ok",
            "compression_wire_ok", "process_set_ok", "join_ok",
        ):
            assert out[r][key], f"rank {r}: {key} failed: {out[r]}"
        # the steady-state layer saw real traffic
        assert out[r]["bytes_negotiated"] > 0
