"""Process-set collectives.

Reference analog: test/parallel/test_process_sets_static.py and the
process-set sweeps inside test_torch.py (reduce/gather/broadcast restricted
to subsets of ranks, with non-members unaffected).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.core.process_sets import ProcessSet


def run_spmd(body, per_rank_in, out_spec=P("hvd")):
    mesh = hvd.mesh()
    wrapped = lambda x: body(x[0])
    return jax.jit(
        shard_map(
            wrapped, mesh=mesh, in_specs=P("hvd"), out_specs=out_spec,
            check_vma=False,
        )
    )(per_rank_in)


def test_registration(hvd8):
    ps = hvd.add_process_set([0, 2, 4])
    assert ps.process_set_id == 1
    assert ps.size() == 3
    assert ps.included(2) and not ps.included(1)
    assert ps.rank(4) == 2
    assert hvd.get_process_set_by_id(1) is ps
    hvd.remove_process_set(ps)
    with pytest.raises(hvd.ProcessSetError):
        hvd.get_process_set_by_id(1)


def test_global_set_is_id_zero(hvd8):
    g = hvd.global_process_set()
    assert g.process_set_id == 0
    assert g.ranks == list(range(8))


def test_duplicate_set_rejected(hvd8):
    hvd.add_process_set([1, 3])
    with pytest.raises(hvd.ProcessSetError):
        hvd.add_process_set([3, 1])


def test_cannot_remove_global(hvd8):
    with pytest.raises(hvd.ProcessSetError):
        hvd.remove_process_set(0)


def test_out_of_range_ranks_rejected(hvd8):
    with pytest.raises(hvd.ProcessSetError):
        hvd.add_process_set([0, 99])


def test_allreduce_subset(hvd8):
    ps = hvd.add_process_set([1, 3, 5])
    x = jnp.arange(8.0).reshape(8, 1)  # rank r holds value r

    out = run_spmd(
        lambda t: hvd.allreduce(t, op=hvd.Sum, process_set=ps), x
    )
    got = np.asarray(out).reshape(8)
    # members get 1+3+5=9; non-members reduce alone (identity)
    expect = np.array([0.0, 9.0, 2.0, 9.0, 4.0, 9.0, 6.0, 7.0])
    np.testing.assert_allclose(got, expect)


def test_allreduce_subset_average(hvd8):
    ps = hvd.add_process_set([0, 4])
    x = jnp.arange(8.0).reshape(8, 1)
    out = run_spmd(
        lambda t: hvd.allreduce(t, op=hvd.Average, process_set=ps), x
    )
    got = np.asarray(out).reshape(8)
    # members hold the set-average; non-member outputs are unspecified
    # (the reference raises on non-member submission; SPMD programs are
    # uniform so non-members compute a don't-care value)
    assert got[0] == got[4] == 2.0  # (0+4)/2


def test_allgather_subset(hvd8):
    ps = hvd.add_process_set([2, 5, 7])
    x = (jnp.arange(8.0)[:, None, None] * jnp.ones((8, 2, 3))).astype(
        jnp.float32
    )

    out = run_spmd(
        lambda t: hvd.allgather(t, process_set=ps), x, out_spec=P("hvd")
    )
    # each member receives [6, 3] = concat of members' [2, 3] blocks
    got = np.asarray(out).reshape(8, 6, 3)
    expect_member = np.concatenate(
        [np.full((2, 3), r, dtype=np.float32) for r in (2, 5, 7)]
    )
    for r in (2, 5, 7):
        np.testing.assert_array_equal(got[r], expect_member)


def test_broadcast_subset(hvd8):
    ps = hvd.add_process_set([1, 2, 6])
    x = jnp.arange(8.0).reshape(8, 1)
    out = run_spmd(
        lambda t: hvd.broadcast(t, root_rank=2, process_set=ps), x
    )
    got = np.asarray(out).reshape(8)
    for r in (1, 2, 6):
        assert got[r] == 2.0


def test_broadcast_subset_root_must_be_member(hvd8):
    ps = hvd.add_process_set([1, 2, 6])
    with pytest.raises(hvd.HorovodInternalError):
        run_spmd(
            lambda t: hvd.broadcast(t, root_rank=0, process_set=ps),
            jnp.zeros((8, 1)),
        )


def test_reducescatter_subset(hvd8):
    ps = hvd.add_process_set([0, 3])
    # dim0=4 divides set size 2: each member gets a [2]-chunk
    x = jnp.stack([jnp.full((4,), float(r)) for r in range(8)])
    out = run_spmd(
        lambda t: hvd.reducescatter(t, op=hvd.Sum, process_set=ps),
        x,
        out_spec=P("hvd"),
    )
    got = np.asarray(out).reshape(8, 2)
    np.testing.assert_array_equal(got[0], [3.0, 3.0])  # chunk 0 of 0+3
    np.testing.assert_array_equal(got[3], [3.0, 3.0])  # chunk 1 of 0+3


def test_alltoall_subset(hvd8):
    ps = hvd.add_process_set([1, 4])
    # member r sends chunk j to set-member j; values encode (src, chunk)
    x = jnp.stack(
        [jnp.asarray([10.0 * r, 10.0 * r + 1]) for r in range(8)]
    )  # [8, 2]: chunk j = 10r+j
    out = run_spmd(
        lambda t: hvd.alltoall(t, process_set=ps), x, out_spec=P("hvd")
    )
    got = np.asarray(out).reshape(8, 2)
    # member 1 (set idx 0) receives chunk 0 from members 1,4 -> [10, 40]
    np.testing.assert_array_equal(got[1], [10.0, 40.0])
    # member 4 (set idx 1) receives chunk 1 from members 1,4 -> [11, 41]
    np.testing.assert_array_equal(got[4], [11.0, 41.0])


# ------------------------------------------------ top-level eager subset ops
#
# Single-controller eager semantics: the controller's tensor stands for
# every member's tensor, so a subset op over a set of size k behaves like
# k identical contributions (VERDICT r1: these used to raise).


def test_eager_subset_allreduce(hvd8):
    ps = hvd.add_process_set([1, 3, 5])
    x = jnp.ones((4,)) * 2.0
    out = hvd.allreduce(x, op=hvd.Sum, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 3)
    out = hvd.allreduce(x, op=hvd.Average, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_eager_subset_allgather(hvd8):
    ps = hvd.add_process_set([0, 2])
    x = jnp.arange(6.0).reshape(3, 2)
    out = hvd.allgather(x, process_set=ps)
    np.testing.assert_allclose(
        np.asarray(out), np.concatenate([np.asarray(x)] * 2, axis=0)
    )


def test_eager_subset_broadcast_and_reducescatter(hvd8):
    ps = hvd.add_process_set([2, 4, 6])
    x = jnp.arange(6.0)
    out = hvd.broadcast(x, root_rank=4, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    out = hvd.reducescatter(x, op=hvd.Sum, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x[:2]) * 3)


def test_sub_mesh(hvd8):
    ps = hvd.add_process_set([0, 2, 4, 6])
    sub = ps.sub_mesh()
    assert sub.devices.shape == (4,)
    assert sub.axis_names == ("hvd",)



def test_broadcast_subset_preserves_nonmembers(hvd8):
    """Non-members keep their input (review fix: singleton-group psum used
    to zero them)."""
    ps = hvd.add_process_set([1, 2, 6])
    x = jnp.arange(8.0).reshape(8, 1)
    out = run_spmd(
        lambda t: hvd.broadcast(t, root_rank=2, process_set=ps), x
    )
    got = np.asarray(out).reshape(8)
    expect = np.array([0.0, 2.0, 2.0, 3.0, 4.0, 5.0, 2.0, 7.0])
    np.testing.assert_array_equal(got, expect)


def test_average_subset_preserves_nonmembers(hvd8):
    ps = hvd.add_process_set([0, 4])
    x = jnp.arange(8.0).reshape(8, 1)
    out = run_spmd(
        lambda t: hvd.allreduce(t, op=hvd.Average, process_set=ps), x
    )
    got = np.asarray(out).reshape(8)
    expect = np.array([2.0, 1.0, 2.0, 3.0, 2.0, 5.0, 6.0, 7.0])
    np.testing.assert_array_equal(got, expect)
