"""Vocab-blocked fused LM-head cross-entropy vs the materializing math
(ops/fused_cross_entropy.py): values and both gradients must match the
naive logsumexp computation that builds the full [N, V] logits."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from horovod_tpu.ops.fused_cross_entropy import fused_linear_cross_entropy


def _naive(hidden, w, targets, valid=None, mean=True):
    """Materializing oracle with the MODEL losses' normalization: the
    user `valid` mask defines the denominator; out-of-range ids inside
    it contribute zero NLL but still count (causal_lm_loss semantics)."""
    x = hidden.reshape(-1, hidden.shape[-1]).astype(jnp.float32)
    logits = x @ w.astype(jnp.float32)
    t = targets.reshape(-1)
    va = jnp.ones(t.shape, bool) if valid is None else valid.reshape(-1)
    in_range = (t >= 0) & (t < w.shape[1])
    tc = jnp.where(in_range, t, 0)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
    nll = jnp.where(va & in_range, lse - tgt, 0.0)
    denom = jnp.maximum(jnp.sum(va), 1)
    return jnp.sum(nll) / (denom if mean else 1)


@pytest.mark.parametrize("block", [16, 64, 128])
def test_matches_naive_values_and_grads(block):
    rng = np.random.RandomState(0)
    N, H, V = 24, 32, 100  # V not a multiple of any block size
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(H, V)) * 0.1, jnp.float32)
    t = jnp.asarray(rng.randint(0, V, N))

    def fused(x, w):
        loss, _ = fused_linear_cross_entropy(x, w, t, block_vocab=block)
        return loss

    def naive(x, w):
        return _naive(x, w, t)

    lf, gf = jax.value_and_grad(fused, argnums=(0, 1))(x, w)
    ln, gn = jax.value_and_grad(naive, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-5)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5)


def test_masked_and_out_of_range_targets():
    """Invalid rows (MLM unmasked positions, -1 sentinels) contribute
    exactly zero loss and zero gradient."""
    rng = np.random.RandomState(1)
    N, H, V = 16, 16, 50
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(H, V)) * 0.1, jnp.float32)
    t = jnp.asarray(rng.randint(0, V, N)).at[3].set(-1)
    valid = jnp.asarray(rng.rand(N) < 0.5)

    def fused(x, w):
        loss, n = fused_linear_cross_entropy(
            x, w, t, valid=valid, block_vocab=32
        )
        return loss

    def naive(x, w):
        return _naive(x, w, t, valid=valid)

    lf, gf = jax.value_and_grad(fused, argnums=(0, 1))(x, w)
    ln, gn = jax.value_and_grad(naive, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-5)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5)
    # rows the mask kills must get zero dx
    dx = np.asarray(gf[0])
    dead = ~np.asarray(valid) | (np.asarray(t) < 0)
    np.testing.assert_allclose(dx[dead], 0.0, atol=1e-7)


def test_out_of_range_counts_in_denominator():
    """Normalization parity with causal_lm_loss: a non-sentinel id >= V
    (valid=True) contributes zero NLL but still counts in n and the
    mean's denominator."""
    rng = np.random.RandomState(5)
    N, H, V = 8, 8, 10
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(H, V)) * 0.1, jnp.float32)
    t = jnp.asarray(rng.randint(0, V, N)).at[0].set(V + 3)
    loss, n = fused_linear_cross_entropy(x, w, t, block_vocab=4)
    assert int(n) == N  # the corrupt id still counted
    ref = _naive(x, w, t)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def test_bf16_hidden_path():
    """Model-dtype activations: the matmuls run bf16→f32 like the head
    they replace; values agree with the f32 naive loss at bf16
    tolerance."""
    rng = np.random.RandomState(2)
    N, H, V = 32, 64, 80
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(H, V)) * 0.1, jnp.float32)
    t = jnp.asarray(rng.randint(0, V, N))
    loss, n = fused_linear_cross_entropy(x, w, t, block_vocab=32)
    ref = _naive(x.astype(jnp.float32), w, t)
    assert int(n) == N
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-2)


def test_sum_mode_and_count():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(size=(4, 6, 8)), jnp.float32)  # [B,T,H]
    w = jnp.asarray(rng.normal(size=(8, 20)) * 0.1, jnp.float32)
    t = jnp.asarray(rng.randint(0, 20, (4, 6)))
    s_loss, n = fused_linear_cross_entropy(x, w, t, mean=False)
    m_loss, _ = fused_linear_cross_entropy(x, w, t, mean=True)
    assert int(n) == 24
    np.testing.assert_allclose(float(s_loss) / 24, float(m_loss),
                               rtol=1e-6)


def test_fused_causal_lm_loss_matches_model_loss():
    """fused_causal_lm_loss(hidden, w, tokens) equals
    causal_lm_loss(logits, tokens) for a real tied-embedding
    transformer at f32."""
    import dataclasses

    from horovod_tpu.models import GPT2_SMALL, Transformer
    from horovod_tpu.models.transformer import causal_lm_loss
    from horovod_tpu.ops.fused_cross_entropy import fused_causal_lm_loss

    cfg = dataclasses.replace(
        GPT2_SMALL, num_layers=2, hidden_size=64, num_heads=4,
        max_seq_len=32, vocab_size=96, dtype=jnp.float32,
    )
    model = Transformer(cfg)
    rng = np.random.RandomState(7)
    toks = jnp.asarray(rng.randint(0, 96, (3, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    logits = model.apply({"params": params}, toks)
    ref, n_ref = causal_lm_loss(logits, toks)

    hidden = model.apply({"params": params}, toks, return_hidden=True)
    w = params["tok_emb"]["embedding"].T
    fused, n_fused = fused_causal_lm_loss(hidden, w, toks, block_vocab=32)
    assert int(n_ref) == int(n_fused)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-5)
