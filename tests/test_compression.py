"""Compressed collective data plane (docs/compression.md).

Covers the ISSUE-8 acceptance surface:
  * int8 quantize/dequant round-trip error bounds;
  * HOROVOD_COMPRESSION=none bitwise parity on the eager path
    (fast-path AND negotiated) and the SPMD path;
  * error-feedback residual carry across steps (optimizer-state leaves
    on SPMD, executor-held buffers on eager);
  * hierarchical outer-hop-only compression numerics vs the flat psum;
  * a small-MLP convergence test under int8+EF;
  * wire-byte accounting (logical vs sent) and knob/CLI plumbing.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.core.knobs import Knobs
from horovod_tpu.core.state import global_state
from horovod_tpu.optim import compression as comp
from horovod_tpu.ops import hierarchical


def _set_knobs(**kw):
    st = global_state()
    st.knobs = dataclasses.replace(st.knobs, **kw)


def _run8(body, per_rank_in, out_spec=P()):
    mesh = hvd.mesh()
    return jax.jit(
        shard_map(lambda x: body(x[0]), mesh=mesh, in_specs=P("hvd"),
                  out_specs=out_spec, check_vma=False)
    )(per_rank_in)


# ------------------------------------------------------------ primitives


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    for block in (64, 256):
        x = rng.uniform(-3, 3, (block * 7 + 13,)).astype(np.float32)
        dq = np.asarray(comp.quantize_dequantize(x, block))
        # per-block symmetric int8: |err| <= scale/2 = amax_block/254
        b = np.pad(x, (0, -len(x) % block)).reshape(-1, block)
        bound = np.repeat(np.abs(b).max(axis=1) / 254.0 + 1e-7, block)
        assert (np.abs(np.pad(x, (0, -len(x) % block)).reshape(-1)
                       - np.pad(dq, (0, -len(dq) % block)).reshape(-1))
                <= bound).all()


def test_int8_compressor_roundtrip():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 77).astype(np.float32))
    wire, ctx = hvd.Compression.int8.compress(x)
    assert wire.dtype == jnp.int8
    back = hvd.Compression.int8.decompress(wire, ctx)
    assert back.shape == x.shape and back.dtype == x.dtype
    assert float(jnp.abs(back - x).max()) <= float(
        jnp.abs(x).max()) / 127.0
    # non-floating payloads pass through untouched
    ints = jnp.arange(10, dtype=jnp.int32)
    w2, c2 = hvd.Compression.int8.compress(ints)
    assert c2 is None and (np.asarray(w2) == np.asarray(ints)).all()


def test_zero_block_quantizes_to_zero():
    q, s = comp.quantize_blocks(jnp.zeros((512,), jnp.float32), 256)
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(s) == 1.0).all()  # guarded divide
    assert (np.asarray(comp.dequantize_blocks(q, s, 256)) == 0).all()


def test_wire_sent_bytes():
    int8 = comp.parse_wire("int8")
    assert comp.wire_sent_bytes(1000, 4, None) == 4000
    assert comp.wire_sent_bytes(1000, 4, comp.parse_wire("bf16")) == 2000
    # padded payload + one f32 scale per 256-block
    assert comp.wire_sent_bytes(1000, 4, int8) == 1024 + 4 * 4
    assert 4000 / comp.wire_sent_bytes(1000, 4, int8) > 3.5


def test_parse_wire_and_knobs():
    assert comp.parse_wire("none") is None
    assert comp.parse_wire("bfloat16").kind == "bf16"  # legacy name
    spec = comp.parse_wire("int8", 128)
    assert spec.block == 128 and spec.error_feedback
    assert not comp.parse_wire("int8-raw").error_feedback
    with pytest.raises(ValueError):
        comp.parse_wire("int4")
    k = Knobs(compression="int8", compression_block=64)
    assert comp.resolve_wire(k) == comp.WireSpec("int8", 64, True)
    # legacy wire-dtype knob maps when HOROVOD_COMPRESSION is unset
    k2 = Knobs(compression="none", compression_wire_dtype="bfloat16")
    assert comp.resolve_wire(k2).kind == "bf16"
    assert hvd.Compression.from_knobs(Knobs()) is hvd.Compression.none
    assert (hvd.Compression.from_knobs(Knobs(compression="int8"))
            is hvd.Compression.int8)


def test_cli_env_mapping():
    from horovod_tpu.runner.util.config_parser import ARG_TO_ENV

    assert ARG_TO_ENV["compression"] == "HOROVOD_COMPRESSION"
    assert ARG_TO_ENV["compression_block"] == "HOROVOD_COMPRESSION_BLOCK"


def test_knobs_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_COMPRESSION", "int8")
    monkeypatch.setenv("HOROVOD_COMPRESSION_BLOCK", "128")
    k = Knobs.from_env()
    assert k.compression == "int8" and k.compression_block == 128


# ------------------------------------------------- SPMD collective forms


def test_quantized_psum_close_to_psum(hvd8):
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.uniform(-2, 2, (8, 1000)).astype(np.float32))
    exact = np.asarray(_run8(lambda v: jax.lax.psum(v, "hvd"), x))
    q = np.asarray(_run8(
        lambda v: comp.quantized_psum(v, "hvd", 8, 128), x))
    tol = 8 * 2.0 / 127 * 2  # two quantization stages over 8 ranks
    assert np.abs(q - exact).max() <= tol
    assert not np.array_equal(q, exact)  # it really quantized


def test_hierarchical_outer_int8_close_to_flat(hvd8):
    """Outer-hop-only compression: ICI legs full precision, DCN leg
    quantized — the result stays within one quantization stage of the
    flat psum (the inner reduce is exact)."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.uniform(-2, 2, (8, 999)).astype(np.float32))
    exact = np.asarray(_run8(lambda v: jax.lax.psum(v, "hvd"), x))
    spec = comp.parse_wire("int8", 128)
    for block in (2, 4):
        hq = np.asarray(_run8(lambda v: hierarchical.hierarchical_psum(
            v, ("hvd",), {"hvd": 8}, block, wire=spec), x))
        # inner sums of `block` ranks are exact; the outer gather
        # quantizes per-slice partial sums of magnitude <= 8*2
        assert np.abs(hq - exact).max() <= 2 * 8 * 2.0 / 127


def test_hierarchical_outer_bf16_close_to_flat(hvd8):
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.uniform(-2, 2, (8, 256)).astype(np.float32))
    exact = np.asarray(_run8(lambda v: jax.lax.psum(v, "hvd"), x))
    hb = np.asarray(_run8(lambda v: hierarchical.hierarchical_psum(
        v, ("hvd",), {"hvd": 8}, 4, wire=comp.parse_wire("bf16")), x))
    assert np.allclose(hb, exact, rtol=2e-2, atol=1e-1)


def test_hierarchical_wire_none_unchanged(hvd8):
    """wire=None must stay exactly the pre-compression hierarchy."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.uniform(-2, 2, (8, 64)).astype(np.float32))
    a = np.asarray(_run8(lambda v: hierarchical.hierarchical_psum(
        v, ("hvd",), {"hvd": 8}, 4), x))
    b = np.asarray(_run8(lambda v: hierarchical.hierarchical_psum(
        v, ("hvd",), {"hvd": 8}, 4, wire=None), x))
    assert np.array_equal(a, b)


def test_grad_path_hierarchical_routing_under_int8(hvd8):
    """With the hierarchy knob on, the int8 grad path routes through the
    outer-leg-compressed hierarchy and stays close to the exact mean."""
    _set_knobs(hierarchical_allreduce=True, hierarchical_local_size=4)
    rng = np.random.RandomState(6)
    g = jnp.asarray(rng.uniform(-1, 1, (8, 500)).astype(np.float32))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   compression=hvd.Compression.int8_raw)
    state = opt.init({"g": jnp.zeros((500,), jnp.float32)})

    def body(v):
        u, _ = opt.update({"g": v}, state, {"g": jnp.zeros_like(v)})
        return u["g"]

    red = np.asarray(_run8(body, g))
    exact = -np.asarray(g).mean(axis=0)  # sgd(1.0) update = -mean grad
    assert np.abs(red - exact).max() <= 4 * 8 / 127 / 8


# ------------------------------------------------------- SPMD none parity


def test_spmd_none_bitwise_parity(hvd8):
    """compression=None (knob none) must produce bit-identical updates
    to the explicit pre-PR Compression.none path."""
    rng = np.random.RandomState(7)
    g = jnp.asarray(rng.randn(8, 300).astype(np.float32))

    def updates_for(compression):
        opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                       compression=compression)
        state = opt.init({"g": jnp.zeros((300,), jnp.float32)})

        def body(v):
            u, _ = opt.update({"g": v}, state,
                              {"g": jnp.zeros_like(v)})
            return u["g"]

        return np.asarray(_run8(body, g))

    assert np.array_equal(updates_for(None),
                          updates_for(hvd.Compression.none))


# --------------------------------------------------------- error feedback


def test_error_feedback_residual_carries_across_steps(hvd8):
    """EF contract: the residual state leaves are non-zero after a step,
    change across steps, and make the RUNNING MEAN of compressed
    reductions converge to the exact value (unbiasedness) where the raw
    int8 wire keeps a persistent bias."""
    rng = np.random.RandomState(8)
    g = jnp.asarray(rng.uniform(-1, 1, (8, 400)).astype(np.float32))
    exact = np.asarray(g).mean(axis=0)
    mesh = hvd.mesh()

    def reductions(compression, steps=16):
        opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                       compression=compression)
        state = opt.init({"g": jnp.zeros((400,), jnp.float32)})
        specs = hvd.error_feedback_specs(state)

        def body(v, s):
            u, s = opt.update({"g": v[0]}, s, {"g": jnp.zeros_like(v[0])})
            return -u["g"], s  # sgd(1.0): -update == reduced grad

        js = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("hvd"), specs),
            out_specs=(P(), specs), check_vma=False))
        outs, s = [], state
        for _ in range(steps):
            r, s = js(g, s)
            outs.append(np.asarray(r))
        return outs, s

    ef_outs, ef_state = reductions(hvd.Compression.int8)
    raw_outs, _ = reductions(hvd.Compression.int8_raw)

    res = np.asarray(ef_state.residual["g"])
    assert res.shape == (8, 400)  # one row per rank
    assert np.abs(res).max() > 0  # residual actually carried

    ef_mean_err = np.abs(np.mean(ef_outs, axis=0) - exact).max()
    raw_mean_err = np.abs(np.mean(raw_outs, axis=0) - exact).max()
    step_err = np.abs(ef_outs[0] - exact).max()
    # EF's mean error collapses well below a single step's quantization
    # error; the raw wire's bias persists at the single-step scale
    assert ef_mean_err < step_err / 3
    assert ef_mean_err < raw_mean_err


def test_error_feedback_requires_specs(hvd8):
    """A full (n, ...) residual leaf inside shard_map means the caller
    forgot error_feedback_specs — fail at the cause."""
    opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                   compression=hvd.Compression.int8)
    state = opt.init({"g": jnp.zeros((64,), jnp.float32)})
    mesh = hvd.mesh()
    g = jnp.zeros((8, 64), jnp.float32)

    def body(v, s):
        u, s = opt.update({"g": v[0]}, s, {"g": jnp.zeros_like(v[0])})
        return u["g"], s

    with pytest.raises(ValueError, match="error_feedback_specs"):
        jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("hvd"), P()),
            out_specs=(P(), P()), check_vma=False))(g, state)


# ------------------------------------------------ small-MLP convergence


def test_small_mlp_converges_under_int8_ef(hvd8):
    """Acceptance: a small MLP trained under int8+EF reaches a final
    loss comparable to full precision."""
    mesh = hvd.mesh()
    rng = np.random.RandomState(9)
    params = {
        "w1": jnp.asarray(rng.randn(32, 32).astype(np.float32) * 0.3),
        "b1": jnp.zeros((32,), jnp.float32),
        "w2": jnp.asarray(rng.randn(32, 4).astype(np.float32) * 0.3),
    }
    x = jnp.asarray(rng.randn(8, 16, 32).astype(np.float32))
    y = jnp.asarray(rng.randn(8, 16, 4).astype(np.float32))

    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - yb) ** 2)

    def train(compression, steps=40):
        opt = hvd.DistributedOptimizer(optax.adam(3e-2),
                                       compression=compression)
        state = opt.init(params)
        specs = hvd.error_feedback_specs(state)

        def step(p, s, xb, yb):
            l, g = jax.value_and_grad(loss_fn)(p, xb[0], yb[0])
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, jax.lax.pmean(
                l, "hvd").reshape(1)

        js = jax.jit(shard_map(
            step, mesh=mesh, in_specs=(P(), specs, P("hvd"), P("hvd")),
            out_specs=(P(), specs, P()), check_vma=False))
        p, s = params, state
        first = last = None
        for _ in range(steps):
            p, s, l = js(p, s, x, y)
            if first is None:
                first = float(l[0])
            last = float(l[0])
        return first, last

    f0, l0 = train(hvd.Compression.none)
    f8, l8 = train(hvd.Compression.int8)
    assert l8 < f8 * 0.5  # it converges
    assert l8 <= l0 * 1.2 + 1e-3  # and lands near full precision


# --------------------------------------------------------- ZeRO / eager


def test_zero_compressed_reduce_scatter_close(hvd8):
    mesh = hvd.mesh()
    rng = np.random.RandomState(10)
    params = {"w": jnp.asarray(rng.randn(96, 4).astype(np.float32))}
    g = jnp.asarray(rng.uniform(-1, 1, (8, 96, 4)).astype(np.float32))

    def update_for(compression):
        opt = hvd.ShardedOptimizer(optax.sgd(1.0),
                                   compression=compression)
        state = opt.init(params)
        specs = hvd.sharded_state_specs(state)

        def body(p, s, v):
            u, s = opt.update({"w": v[0]}, s, p)
            return u["w"], s

        js = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(), specs, P("hvd")),
            out_specs=(P(), specs), check_vma=False))
        return np.asarray(js(params, state, g)[0])

    base = update_for(hvd.Compression.none)
    for compression in (hvd.Compression.bf16, hvd.Compression.int8):
        out = update_for(compression)
        assert np.abs(out - base).max() <= 8 * 1.0 / 127 / 8 * 4
        assert not np.array_equal(out, base)
    # state layout must be identical regardless of wire
    opt_a = hvd.ShardedOptimizer(optax.adam(1e-2),
                                 compression=hvd.Compression.none)
    opt_b = hvd.ShardedOptimizer(optax.adam(1e-2),
                                 compression=hvd.Compression.int8)
    la = jax.tree_util.tree_map(jnp.shape, opt_a.init(params))
    lb = jax.tree_util.tree_map(jnp.shape, opt_b.init(params))
    assert la == lb


def test_eager_none_bitwise_fastpath_and_negotiated():
    """HOROVOD_COMPRESSION=none on the eager runtime: fast-path AND
    negotiated results are bitwise identical to the uncompressed
    plane's exact loopback sum."""
    from horovod_tpu.ops.eager_runtime import EagerRuntime

    rt = EagerRuntime(0, 1, cycle_ms=1.0, fast_path=True,
                      fast_path_warmup=2, wire="none")
    try:
        x = np.random.RandomState(11).randn(257).astype(np.float32)
        outs = []
        for _ in range(6):
            h = rt.allreduce_async("t", x)
            outs.append(np.asarray(rt.synchronize(h, timeout_s=30)))
        assert rt.fast_path_stats()["active"]  # steady state reached
        assert rt.fast_path_stats()["plan_wire_key"] is None
        rt.set_fast_path(False)
        h = rt.allreduce_async("t", x)
        negotiated = np.asarray(rt.synchronize(h, timeout_s=30))
        for o in outs:
            assert np.array_equal(o, x)  # world-1 SUM == x, bitwise
        assert np.array_equal(negotiated, x)
    finally:
        rt.shutdown()


def test_eager_int8_wire_counters_and_ef_buffers():
    """Loopback executor under the int8 wire: the wire-byte counters
    report the >=3.5x ratio, results stay in quantization tolerance,
    and the executor carries error-feedback buffers across steps."""
    from horovod_tpu.ops.eager_runtime import EagerRuntime
    from horovod_tpu.utils import metrics

    metrics.enable()
    rt = EagerRuntime(0, 1, cycle_ms=1.0, fast_path=True,
                      fast_path_warmup=2, wire="int8")
    try:
        x = np.random.RandomState(12).randn(1000).astype(np.float32)

        def counters():
            snap = metrics.registry.snapshot()
            return (sum(snap.get("hvd_wire_bytes_logical_total",
                                 {}).values()),
                    sum(snap.get("hvd_wire_bytes_sent_total",
                                 {}).values()))

        l0, s0 = counters()
        outs = []
        for _ in range(8):
            h = rt.allreduce_async("t", x)
            outs.append(np.asarray(rt.synchronize(h, timeout_s=30)))
        l1, s1 = counters()
        assert (l1 - l0) / (s1 - s0) >= 3.5
        amax = np.abs(x).max()
        assert np.abs(outs[0] - x).max() <= 4 * amax / 127
        # EF: the residual buffer exists and the mean over steps beats
        # a single step's quantization error
        assert rt._executor._residuals
        mean_err = np.abs(np.mean(outs, axis=0) - x).max()
        assert mean_err < np.abs(outs[0] - x).max() or mean_err < 1e-4
        # plan froze under the int8 wire
        assert rt.fast_path_stats()["plan_wire_key"][0] == "int8"
    finally:
        rt.shutdown()
        metrics.disable()
        metrics.registry.clear()


def test_block_knob_reaches_spmd_wire_spec(hvd8):
    """HOROVOD_COMPRESSION_BLOCK must reach the SPMD/ZeRO paths through
    the knob-resolved compressor, not be shadowed by a class default —
    eager and SPMD must quantize on the same grid."""
    _set_knobs(compression="int8", compression_block=64)
    spec = comp.compressor_wire_spec(hvd.Compression.from_knobs())
    assert spec.block == 64
    assert comp.resolve_wire().block == 64  # executors agree
    # the ctx carries the grid, so decompress survives a knob change
    x = jnp.asarray(np.random.RandomState(0).randn(100).astype(np.float32))
    wire, ctx = hvd.Compression.int8.compress(x)
    _set_knobs(compression_block=256)
    back = hvd.Compression.int8.decompress(wire, ctx)
    assert float(jnp.abs(back - x).max()) <= float(jnp.abs(x).max()) / 127


def test_adasum_under_int8_knob_falls_back(hvd8):
    """op=ADASUM under the int8 knob must fall back to the uncompressed
    plane on every path instead of tracing live[0] off an empty axis
    list (or cast-reducing an int8 payload)."""
    _set_knobs(compression="int8")
    g = jnp.asarray(np.random.RandomState(0).randn(8, 64)
                    .astype(np.float32))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   op=hvd.ReduceOp.ADASUM)
    state = opt.init({"g": jnp.zeros((64,), jnp.float32)})

    def body(v):
        u, _ = opt.update({"g": v}, state, {"g": jnp.zeros_like(v)})
        return u["g"]

    out = np.asarray(_run8(body, g))  # must trace and run
    assert np.isfinite(out).all()


def test_error_feedback_with_grad_accumulation(hvd8):
    """int8+EF composes with backward_passes_per_step > 1: the specs
    helper recurses through the accumulation wrapper and the residual
    still carries across sync steps."""
    mesh = hvd.mesh()
    rng = np.random.RandomState(13)
    g = jnp.asarray(rng.uniform(-1, 1, (8, 128)).astype(np.float32))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   compression=hvd.Compression.int8,
                                   backward_passes_per_step=2)
    state = opt.init({"g": jnp.zeros((128,), jnp.float32)})
    specs = hvd.error_feedback_specs(state)

    def body(v, s):
        u, s = opt.update({"g": v[0]}, s, {"g": jnp.zeros_like(v[0])})
        return u["g"], s

    js = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("hvd"), specs),
        out_specs=(P(), specs), check_vma=False))
    s = state
    for _ in range(4):  # two full accumulate->sync cycles
        u, s = js(g, s)
    res = np.asarray(s.inner.residual["g"])
    assert res.shape == (8, 128) and np.abs(res).max() > 0
    exact = -np.asarray(g).mean(axis=0)
    assert np.abs(np.asarray(u) - exact).max() <= 8.0 / 127


def test_fusion_bucket_plan_unchanged_by_wire(monkeypatch):
    """(logical, wire) bucket keys: grouping BOUNDARIES are identical
    with compression on and off — the wire half never splits a dtype
    group, it only tags it (the ZeRO layout invariant)."""
    from horovod_tpu.ops.fusion import pytree_bucket_plan

    tree = {"a": jnp.zeros((100,), jnp.float32),
            "b": jnp.zeros((50,), jnp.float32),
            "c": jnp.zeros((10,), jnp.int32)}
    _, plans_off = pytree_bucket_plan(tree, threshold_bytes=1 << 20)
    monkeypatch.setenv("HOROVOD_COMPRESSION", "int8")
    _, plans_on = pytree_bucket_plan(tree, threshold_bytes=1 << 20)
    assert plans_off == plans_on
