"""Flight recorder + cross-rank forensics (utils/flight.py,
scripts/flight_analyze.py, docs/flight.md).

Covers: the disabled no-op fast path (< 1 us/call, matching the
metrics-registry pattern), ring bounding, dump format + parse
round-trip, the rendezvous PUT /flight/<rank> route with its receipt
stamp and GET /clock, straggler attribution against peer dumps, the
analyzer's merge/report, eager-runtime event emission, the SIGUSR2
on-demand trigger, knob wiring through hvd.init, and the worker →
rendezvous metrics push feeding the rank-aggregated /metrics."""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.utils import flight, metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_flight():
    flight.reset()
    yield
    flight.reset()


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ no-op path

def test_disabled_records_nothing():
    assert not flight.enabled()
    flight.record("enqueue", "g0", op=1)
    flight.record("fault", "collective")
    assert flight.event_count() == 0
    assert flight.dump("manual") is None


def test_disabled_overhead_under_1us_per_call():
    """HOROVOD_FLIGHT_RECORDER=0 acceptance: the no-op path (module
    flag check + return) must cost < 1 us per call — the same bound
    the metrics registry holds (tests/test_metrics.py)."""
    assert not flight.enabled()
    n = 200_000
    rec = flight.record
    t0 = time.perf_counter()
    for _ in range(n):
        rec("enqueue", "g0", op=1, handle=7)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 1e-6, f"no-op record costs {per_call * 1e9:.0f} ns"


# ------------------------------------------------------------ ring buffer

def test_ring_is_bounded_and_ordered():
    flight.enable(capacity=32)
    for i in range(100):
        flight.record("enqueue", f"g{i}")
    assert flight.event_count() == 32
    events = flight.snapshot()
    # oldest fell off the far end; sequence stays monotonic
    assert [e[4] for e in events] == [f"g{i}" for i in range(68, 100)]
    seqs = [e[0] for e in events]
    assert seqs == sorted(seqs)


def test_enable_preserves_events_on_resize():
    flight.enable(capacity=64)
    for i in range(10):
        flight.record("x", str(i))
    flight.enable(capacity=128)
    assert flight.event_count() == 10


# ------------------------------------------------------------ dump format

def test_dump_roundtrip(tmp_path):
    flight.configure(enabled_override=True, rank=3,
                     directory=str(tmp_path), handlers=False)
    flight.record("enqueue", "g0", op=1, handle=5)
    flight.record("exec_end", "g0", names=["g0"])
    path = flight.dump("unit_test")
    assert path and os.path.exists(path)
    header, events = flight.parse_dump(open(path).read())
    assert header["rank"] == 3
    assert header["reason"] == "unit_test"
    assert header["events"] == 2
    assert events[0]["kind"] == "enqueue"
    assert events[0]["name"] == "g0"
    assert events[0]["op"] == 1
    assert events[1]["kind"] == "exec_end"
    assert events[0]["seq"] < events[1]["seq"]
    # a second dump overwrites (the file is "the last dump")
    flight.record("stall_abort", "g1")
    path2 = flight.dump("again")
    assert path2 == path
    header2, events2 = flight.parse_dump(open(path).read())
    assert header2["reason"] == "again"
    assert len(events2) == 3


# ----------------------------------------------- rendezvous flight routes

@pytest.fixture()
def kv_server():
    from horovod_tpu.runner.http.http_server import KVStoreServer

    srv = KVStoreServer()
    srv.start_server()
    yield srv
    srv.shutdown_server()


def test_clock_route(kv_server):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{kv_server.port}/clock", timeout=5) as r:
        body = json.loads(r.read())
    assert abs(body["time_unix"] - time.time()) < 5.0


def test_dump_ships_to_sink_with_receipt_stamp(kv_server, tmp_path):
    from horovod_tpu.runner.http.http_server import FLIGHT_META_SCOPE

    flight.configure(enabled_override=True, rank=2,
                     sink_addr="127.0.0.1", sink_port=kv_server.port,
                     directory=str(tmp_path), handlers=False)
    flight.record("enqueue", "g0")
    flight.dump("ship_it")
    with urllib.request.urlopen(
            f"http://127.0.0.1:{kv_server.port}/flight/2", timeout=5) as r:
        header, events = flight.parse_dump(r.read().decode())
    assert header["rank"] == 2
    # the /clock probe ran at dump time: offset is near zero locally
    assert abs(header["clock_offset_s"]) < 5.0
    assert len(events) == 1
    meta = json.loads(
        kv_server.store[FLIGHT_META_SCOPE]["2"].decode())
    assert meta["bytes"] > 0
    assert abs(meta["recv_time_unix"] - time.time()) < 5.0
    # and the peer-fetch helper sees it
    got = flight.fetch_peer_dump(2)
    assert got is not None and got[0]["rank"] == 2


# ------------------------------------------------- straggler attribution

def _put_fake_dump(port, rank, enqueues):
    lines = [json.dumps({"flight_header": 1, "rank": rank,
                         "reason": "fake", "time_unix": time.time(),
                         "events": len(enqueues)})]
    for i, name in enumerate(enqueues):
        lines.append(json.dumps({
            "seq": i, "t_mono": float(i), "t_wall": time.time(),
            "kind": "enqueue", "name": name}))
    body = ("\n".join(lines) + "\n").encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/flight/{rank}", data=body,
        method="PUT")
    urllib.request.urlopen(req, timeout=5)


def test_straggler_report_names_lagging_peer(kv_server, tmp_path):
    flight.configure(enabled_override=True, rank=0,
                     sink_addr="127.0.0.1", sink_port=kv_server.port,
                     directory=str(tmp_path), handlers=False)
    # we enqueued g0..g3 twice; peer 1's dump shows g3 only once and
    # peer 2 kept up; peer 3 has no dump at all
    for _ in range(2):
        for n in ("g0", "g1", "g2", "g3"):
            flight.record("enqueue", n)
    _put_fake_dump(kv_server.port, 1,
                   ["g0", "g1", "g2", "g3", "g0", "g1", "g2"])
    _put_fake_dump(kv_server.port, 2,
                   ["g0", "g1", "g2", "g3"] * 2)
    msg = flight.straggler_report(["g2", "g3"], world_size=4, my_rank=0)
    assert "rank 1 has not submitted g3" in msg
    assert "rank 2" not in msg
    assert "[3]" in msg  # no dump from rank 3 is called out
    assert "locally pending: g2, g3" in msg
    # our own dump shipped as a side effect (peers/analyzer see us too)
    assert flight.fetch_peer_dump(0) is not None


def test_straggler_report_without_sink(tmp_path):
    flight.configure(enabled_override=True, rank=0,
                     directory=str(tmp_path), handlers=False)
    flight.record("enqueue", "g0")
    msg = flight.straggler_report(["g0"], world_size=2, my_rank=0)
    assert "no flight sink configured" in msg
    assert "locally pending: g0" in msg


# ------------------------------------------------------------- analyzer

def _write_dump(path, rank, events, offset=0.0):
    with open(path, "w") as f:
        f.write(json.dumps({
            "flight_header": 1, "rank": rank, "reason": "test",
            "time_unix": time.time(), "events": len(events),
            "clock_offset_s": offset}) + "\n")
        for i, ev in enumerate(events):
            ev = dict(ev)
            ev.setdefault("seq", i)
            ev.setdefault("t_mono", float(i))
            ev.setdefault("t_wall", time.time())
            f.write(json.dumps(ev) + "\n")


def test_flight_analyze_names_straggler(tmp_path):
    analyzer = _load_script("flight_analyze")
    # rank 0 enqueued g0,g1 twice and executed the first round; its
    # second round is pending. rank 1 only ever enqueued the first
    # round — it is the straggler for both tensors.
    d0 = str(tmp_path / "flight_rank0.jsonl")
    d1 = str(tmp_path / "flight_rank1.jsonl")
    _write_dump(d0, 0, [
        {"kind": "enqueue", "name": "g0"},
        {"kind": "enqueue", "name": "g1"},
        {"kind": "exec_end", "name": "g0", "names": ["g0", "g1"]},
        {"kind": "enqueue", "name": "g0"},
        {"kind": "enqueue", "name": "g1"},
        {"kind": "stall_abort", "name": "g0"},
    ], offset=0.25)
    _write_dump(d1, 1, [
        {"kind": "enqueue", "name": "g0"},
        {"kind": "enqueue", "name": "g1"},
        {"kind": "exec_end", "name": "g0", "names": ["g0", "g1"]},
    ], offset=-0.25)
    report = analyzer.analyze([analyzer.load_file(d0),
                               analyzer.load_file(d1)])
    assert report["suspected_straggler_ranks"] == [1]
    assert report["stragglers"]["1"] == ["g0", "g1"]
    assert report["ranks"][0]["pending"] == ["g0", "g1"]
    assert report["ranks"][1]["pending"] == []
    # clock offsets applied to the aligned activity stamps
    assert (report["ranks"][0]["last_activity_aligned_unix"]
            != report["ranks"][1]["last_activity_aligned_unix"])
    text = analyzer.render(report)
    assert "SUSPECTED STRAGGLER rank 1" in text
    # CLI entry: exit 0 and a JSON artifact
    out = str(tmp_path / "report.json")
    assert analyzer.main([d0, d1, "--json", out]) == 0
    assert json.load(open(out))["suspected_straggler_ranks"] == [1]


def test_flight_analyze_handles_duplicate_rank_dumps(tmp_path):
    """A rank can appear twice (local file + server fetch): the merge
    must not fall through to comparing header dicts (TypeError) — the
    later duplicate wins."""
    analyzer = _load_script("flight_analyze")
    d0 = str(tmp_path / "a.jsonl")
    d0b = str(tmp_path / "b.jsonl")
    _write_dump(d0, 0, [{"kind": "enqueue", "name": "g0"}])
    _write_dump(d0b, 0, [{"kind": "enqueue", "name": "g0"},
                         {"kind": "enqueue", "name": "g1"}])
    report = analyzer.analyze([analyzer.load_file(d0),
                               analyzer.load_file(d0b)])
    assert report["ranks"][0]["events"] == 2  # later duplicate won


def test_dump_is_nonblocking_when_lock_held(tmp_path):
    """A signal handler re-entering dump() on the main thread must not
    deadlock on the non-reentrant dump lock — it bails instead."""
    flight.configure(enabled_override=True, rank=0,
                     directory=str(tmp_path), handlers=False)
    flight.record("enqueue", "g0")
    assert flight._dump_lock.acquire(blocking=False)
    try:
        assert flight.dump("reentrant") is None
    finally:
        flight._dump_lock.release()
    assert flight.dump("after") is not None


def test_flight_analyze_no_dumps_is_an_error():
    analyzer = _load_script("flight_analyze")
    assert analyzer.main([]) == 1


# ------------------------------------------------ eager runtime events

def test_eager_runtime_emits_flight_events(tmp_path):
    from horovod_tpu.ops.eager_runtime import EagerRuntime

    flight.configure(enabled_override=True, rank=0,
                     directory=str(tmp_path), handlers=False)
    rt = EagerRuntime(0, 1, cycle_ms=1.0, fast_path=False)
    try:
        h = rt.allreduce_async("fr_x", np.ones((8,), np.float32))
        rt.synchronize(h, timeout_s=30.0)
    finally:
        rt.shutdown()
    kinds = {}
    names = set()
    for ev in flight.snapshot():
        kinds[ev[3]] = kinds.get(ev[3], 0) + 1
        names.add(ev[4])
    assert kinds.get("enqueue", 0) >= 1
    assert kinds.get("response", 0) >= 1
    assert kinds.get("exec_begin", 0) >= 1
    assert kinds.get("exec_end", 0) >= 1
    assert "fr_x" in names


def test_fast_path_plan_events(tmp_path):
    from horovod_tpu.ops.eager_runtime import EagerRuntime

    flight.configure(enabled_override=True, rank=0,
                     directory=str(tmp_path), handlers=False)
    rt = EagerRuntime(0, 1, cycle_ms=1.0, fast_path=True,
                      fast_path_warmup=2)
    try:
        for _ in range(6):
            hs = [rt.allreduce_async(f"fp_{i}",
                                     np.ones((4,), np.float32))
                  for i in range(3)]
            for h in hs:
                rt.synchronize(h, timeout_s=30.0)
        assert rt.fast_path_stats()["active"]
        rt.invalidate_plan("unit_test")
    finally:
        rt.shutdown()
    kinds = [ev[3] for ev in flight.snapshot()]
    assert "plan_activate" in kinds
    assert "plan_invalidate" in kinds
    # bypassed enqueues still count as submissions
    fast_enqueues = [
        ev for ev in flight.snapshot()
        if ev[3] == "enqueue" and (ev[5] or {}).get("fast_path")
    ]
    assert fast_enqueues


# -------------------------------------------------------- SIGUSR2 trigger

def test_sigusr2_dumps_on_demand(tmp_path):
    flight.configure(enabled_override=True, rank=0,
                     directory=str(tmp_path), handlers=True)
    flight.record("enqueue", "g0")
    assert flight.dump_count() == 0
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = time.monotonic() + 5.0
    while flight.dump_count() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert flight.dump_count() >= 1
    header, events = flight.parse_dump(
        open(os.path.join(str(tmp_path), "flight_rank0.jsonl")).read())
    assert header["reason"] == "sigusr2"
    assert any(e["kind"] == "signal_dump" for e in events)


def test_sigusr2_chains_preexisting_handler(tmp_path):
    """An application's own SIGUSR2 handler must keep firing after the
    recorder (default ON) installs its dump trigger."""
    fired = []
    prev = signal.signal(signal.SIGUSR2, lambda s, f: fired.append(s))
    # earlier tests may have armed the recorder's handler already;
    # force a fresh install so it captures OUR handler as the previous
    flight._handlers_installed = False
    flight._prev_sigusr2 = None
    try:
        flight.configure(enabled_override=True, rank=0,
                         directory=str(tmp_path), handlers=True)
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fired == [signal.SIGUSR2]  # the app handler still ran
        assert flight.dump_count() >= 1   # and so did the dump
    finally:
        signal.signal(signal.SIGUSR2, prev)
        flight._prev_sigusr2 = None
        flight._handlers_installed = False


# ------------------------------------------------------------ knob wiring

def test_knob_disables_recorder(monkeypatch):
    import horovod_tpu as hvd

    monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "0")
    hvd.init()
    try:
        assert not flight.enabled()
        flight.record("enqueue", "x")
        assert flight.event_count() == 0
    finally:
        hvd.shutdown()


def test_default_on_and_shutdown_disables(monkeypatch, tmp_path):
    import horovod_tpu as hvd

    monkeypatch.setenv("HOROVOD_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_FLIGHT_CAPACITY", "77")
    hvd.init()
    assert flight.enabled()  # black boxes default on
    assert flight.dump_dir() == str(tmp_path)
    hvd.shutdown()
    assert not flight.enabled()  # configure()-driven enable ends with it


# ------------------------------------------- metrics push + aggregation

def test_metrics_push_feeds_aggregated_scrape(kv_server):
    metrics.reset()
    metrics.enable()
    try:
        metrics.registry.counter("t_push_total", "x").inc(5)
        assert metrics.push_once("127.0.0.1", kv_server.port, 1)
        metrics.registry.counter("t_push_total", "x").inc(2)
        metrics.start_metrics_push("127.0.0.1", kv_server.port, 0,
                                   interval_s=30.0)  # immediate first push
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{kv_server.port}/metrics",
                    timeout=5) as r:
                scrape = r.read().decode()
            if 'rank="0"' in scrape:
                break
            time.sleep(0.05)
        assert 't_push_total{rank="1"} 5' in scrape
        assert 't_push_total{rank="0"} 7' in scrape
        # headers dedup to one family block and the merge lints clean
        assert scrape.count("# TYPE t_push_total counter") == 1
        assert metrics.lint_exposition(scrape) == []
    finally:
        metrics.stop_metrics_push()
        metrics.reset()


# ----------------------------------------------------- world-2 e2e gate

@pytest.mark.slow
def test_flight_check_e2e_gate():
    """The acceptance scenario end-to-end (scripts/flight_check.py):
    injected collective delay on rank 1, stall watchdog autopsy naming
    rank 1 + g3, aggregated analyzer report, rank-labeled /metrics."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts",
                                      "flight_check.py")],
        env=env, cwd=_REPO, timeout=150,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    assert proc.returncode == 0, f"flight_check failed:\n{proc.stdout}"
    assert '"ok": true' in proc.stdout
