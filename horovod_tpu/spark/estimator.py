"""Spark Estimator/Model API: fit a model to a DataFrame, get back a
transformer for inference.

Reference: /root/reference/horovod/spark/keras/estimator.py:88
(`KerasEstimator`) and spark/torch/estimator.py (`TorchEstimator`) —
`est.fit(df)` launches distributed Horovod training over the DataFrame
and returns a Model whose `transform(df)` appends predictions.

TPU-native redesign, not a port: the reference serializes Keras graphs,
writes the DataFrame to a Petastorm parquet store, and streams row
groups into per-rank data loaders. JAX models are pytrees and the TPU
input path is host numpy → device shards, so this estimator

  * extracts (features, labels) from the DataFrame once on the driver
    (numpy), and shards rows per rank inside the Spark barrier task —
    the Store/Petastorm machinery is replaced by the framework's own
    data layer (`data.ShardedDataLoader` feeds bigger-than-driver data
    outside Spark);
  * trains with the standard recipe: `hvd.init()` →
    `DistributedOptimizer(optax...)` → per-rank minibatch loop, exactly
    what `spark.run` slots provide;
  * returns a `JaxModel` holding the trained pytree; `transform`
    runs inference partition-by-partition on the executors, and
    `save`/`load` round-trip through `horovod_tpu.checkpoint` (the
    Keras write/read path of the reference maps onto save_model's
    optimizer-spec rehydration).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Sequence

import numpy as np


def _rows_to_matrix(rows, cols: Sequence[str]) -> np.ndarray:
    """Row objects/dicts → float32 matrix over the named columns."""
    return np.asarray(
        [[getattr(r, c) if hasattr(r, c) else r[c] for c in cols]
         for r in rows], dtype=np.float32,
    )


def _require_numpy_df(df, feature_cols: Sequence[str],
                      label_cols: Sequence[str]):
    """DataFrame → (X, Y) float32 numpy (driver-side materialization)."""
    rows = df.collect()
    return _rows_to_matrix(rows, feature_cols), _rows_to_matrix(
        rows, label_cols
    )


def _transform_rdd(df, feature_cols: Sequence[str], out_col: str,
                   predict: Callable[[np.ndarray], np.ndarray]):
    """Shared transform body (reference KerasModel.transform's row UDF):
    map each partition's rows through `predict`, appending `out_col`."""

    def map_partition(rows):
        rows = list(rows)
        if not rows:
            return iter([])
        preds = predict(_rows_to_matrix(rows, feature_cols))
        out = []
        for r, p in zip(rows, preds):
            d = r.asDict() if hasattr(r, "asDict") else dict(r)
            d[out_col] = (
                p.tolist() if getattr(p, "ndim", 0) else float(p)
            )
            out.append(d)
        return iter(out)

    rdd = df.rdd if hasattr(df, "rdd") else df
    return rdd.mapPartitions(map_partition)


def _mse(pred, y):
    import jax.numpy as jnp

    return jnp.mean((pred - y) ** 2)


_LOSSES: Dict[str, Callable] = {"mse": _mse}


def _resolve_model(model):
    """(init_fn(rng, x), apply_fn(params, x)) from a flax-style module
    (.init/.apply) or an (init_fn, apply_fn) pair."""
    if hasattr(model, "init") and hasattr(model, "apply"):
        return (lambda rng, x: model.init(rng, x),
                lambda p, x: model.apply(p, x))
    init_fn, apply_fn = model
    return init_fn, apply_fn


class JaxModel:
    """Trained transformer (reference KerasModel): holds the pytree and
    appends a prediction column."""

    def __init__(self, params, apply_fn, feature_cols: Sequence[str],
                 output_col: str = "prediction", metadata=None,
                 optimizer_spec: Optional[tuple] = None):
        import jax

        self.params = params
        self._apply = apply_fn
        # jit ONCE: transform maps many partitions and each fresh
        # jax.jit wrapper would recompile from an empty cache
        self._jit_apply = jax.jit(apply_fn)
        self.feature_cols = list(feature_cols)
        self.output_col = output_col
        self.metadata = dict(metadata or {})
        self.optimizer_spec = optimizer_spec

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._jit_apply(self.params, x))

    def transform(self, df):
        """Append predictions row-by-row (reference KerasModel.transform
        appends output columns via a row-mapping UDF). Output rows are
        dicts of the original columns plus `output_col`."""
        return _transform_rdd(
            df, self.feature_cols, self.output_col, self.predict
        )

    def save(self, path: str) -> None:
        """Checkpoint params + the optimizer spec the estimator trained
        with, so hvd.load_model(path) can resume training — not just
        this class's load() for inference."""
        from ..checkpoint import save_model

        save_model(path, self.params, metadata=self.metadata,
                   optimizer_spec=self.optimizer_spec)

    @classmethod
    def load(cls, path: str, apply_fn, feature_cols,
             output_col: str = "prediction"):
        """Rebuild from a checkpoint; `apply_fn` is code, not data —
        the caller supplies it like the reference supplies
        custom_objects at load time."""
        from ..checkpoint import load_params

        params, metadata = load_params(path)
        return cls(params, apply_fn, feature_cols, output_col,
                   metadata=metadata)


class JaxEstimator:
    """Fit a JAX/flax model to a Spark DataFrame with distributed
    training (reference KerasEstimator, spark/keras/estimator.py:88).

    `model` is a flax-style module (.init/.apply) or an
    (init_fn, apply_fn) pair; `optimizer_spec` is the serializable
    ("optax_name", kwargs) identity used throughout this framework;
    `loss` is "mse" or a callable (pred, y) -> scalar.
    """

    def __init__(
        self,
        model,
        feature_cols: Sequence[str],
        label_cols: Sequence[str],
        optimizer_spec: tuple = ("adam", {"learning_rate": 1e-3}),
        loss="mse",
        batch_size: int = 32,
        epochs: int = 1,
        num_proc: Optional[int] = None,
        output_col: str = "prediction",
        seed: int = 0,
        verbose: int = 0,
        store=None,
        run_id: Optional[str] = None,
    ):
        from .store import store_or_none

        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.optimizer_spec = optimizer_spec
        self.loss = loss
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.output_col = output_col
        self.seed = seed
        self.verbose = verbose
        # reference estimators persist run artifacts through a Store
        # (spark/common/store.py); a string prefix is accepted directly
        self.store = store_or_none(store)
        self.run_id = run_id or "run"

    def fit(self, df) -> JaxModel:
        from . import run as spark_run

        x, y = _require_numpy_df(df, self.feature_cols, self.label_cols)
        loss_fn = (
            _LOSSES[self.loss] if isinstance(self.loss, str) else self.loss
        )
        init_fn, apply_fn = _resolve_model(self.model)
        spec = self.optimizer_spec
        batch_size, epochs, seed = self.batch_size, self.epochs, self.seed

        def train():
            import os

            import jax
            import jax.numpy as jnp
            import optax

            import horovod_tpu as hvd

            hvd.init()
            # the SLOT's rank shards the data (one shard per Spark
            # barrier task, like the reference's per-rank row groups) —
            # hvd.size() counts devices, which in single-process worlds
            # exceeds the slot count
            rank = int(os.environ.get("HOROVOD_RANK", hvd.rank()))
            size = int(os.environ.get("HOROVOD_SIZE", hvd.size()))
            # rank-sharded rows (the reference reads per-rank Petastorm
            # row groups; here the shard is a strided row slice)
            xs, ys = x[rank::size], y[rank::size]
            params = init_fn(jax.random.PRNGKey(seed), xs[:1])
            name, kwargs = spec
            opt = hvd.DistributedOptimizer(getattr(optax, name)(**kwargs))
            opt_state = opt.init(params)
            params = hvd.broadcast_parameters(params, root_rank=0)

            @jax.jit
            def step(p, s, bx, by):
                def lf(p):
                    return loss_fn(apply_fn(p, bx), by)

                l, g = jax.value_and_grad(lf)(p)
                u, s = opt.update(g, s, p)
                return optax.apply_updates(p, u), s, l

            n = len(xs)
            steps = max(1, n // batch_size)
            for epoch in range(epochs):
                perm = np.random.RandomState(seed + epoch).permutation(n)
                for i in range(steps):
                    idx = perm[i * batch_size:(i + 1) * batch_size]
                    if len(idx) == 0:
                        continue
                    params, opt_state, l = step(
                        params, opt_state, xs[idx], ys[idx]
                    )
            hvd.shutdown()
            if rank == 0:
                return jax.tree_util.tree_map(np.asarray, params)
            return None

        results = spark_run(train, num_proc=self.num_proc,
                            verbose=self.verbose)
        trained = next(r for r in results if r is not None)
        jm = JaxModel(trained, apply_fn, self.feature_cols,
                      self.output_col,
                      metadata={"epochs": self.epochs},
                      optimizer_spec=self.optimizer_spec)
        if self.store is not None:
            import tempfile

            # save_model writes a directory tree; mirror it in bulk
            # under <prefix>/<run_id>/checkpoint/model
            ckpt = self.store.get_checkpoint_path(self.run_id)
            with tempfile.TemporaryDirectory() as tmp:
                local = os.path.join(tmp, "model")
                jm.save(local)
                self.store.upload(local, f"{ckpt}/model")
        return jm


class TorchEstimator:
    """Fit a torch.nn.Module to a Spark DataFrame via this framework's
    torch adapter (reference spark/torch/estimator.py). Same DataFrame
    contract as JaxEstimator; training uses
    horovod_tpu.torch.DistributedOptimizer."""

    def __init__(
        self,
        model,
        feature_cols: Sequence[str],
        label_cols: Sequence[str],
        optimizer_factory: Optional[Callable] = None,
        loss: Optional[Callable] = None,
        batch_size: int = 32,
        epochs: int = 1,
        num_proc: Optional[int] = None,
        output_col: str = "prediction",
        verbose: int = 0,
        store=None,
        run_id: Optional[str] = None,
    ):
        from .store import store_or_none

        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.optimizer_factory = optimizer_factory
        self.loss = loss
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.output_col = output_col
        self.verbose = verbose
        self.store = store_or_none(store)
        self.run_id = run_id or "run"

    def fit(self, df) -> "TorchModel":
        import torch

        from . import run as spark_run

        x, y = _require_numpy_df(df, self.feature_cols, self.label_cols)
        model = self.model
        opt_factory = self.optimizer_factory or (
            lambda params: torch.optim.SGD(params, lr=0.01)
        )
        loss_fn = self.loss or torch.nn.functional.mse_loss
        batch_size, epochs = self.batch_size, self.epochs

        def train():
            import os

            import torch

            import horovod_tpu.torch as thvd

            thvd.init()
            rank = int(os.environ.get("HOROVOD_RANK", thvd.rank()))
            size = int(os.environ.get("HOROVOD_SIZE", thvd.size()))
            xs = torch.from_numpy(x[rank::size])
            ys = torch.from_numpy(y[rank::size])
            opt = thvd.DistributedOptimizer(
                opt_factory(model.parameters()),
                named_parameters=list(model.named_parameters()),
            )
            thvd.broadcast_parameters(model.state_dict(), root_rank=0)
            n = len(xs)
            steps = max(1, n // batch_size)
            for _ in range(epochs):
                perm = torch.randperm(n)
                for i in range(steps):
                    idx = perm[i * batch_size:(i + 1) * batch_size]
                    if len(idx) == 0:
                        continue
                    opt.zero_grad()
                    loss = loss_fn(model(xs[idx]), ys[idx])
                    loss.backward()
                    opt.step()
            thvd.shutdown()
            if rank == 0:
                return {
                    k: v.detach().cpu().numpy()
                    for k, v in model.state_dict().items()
                }
            return None

        results = spark_run(train, num_proc=self.num_proc,
                            verbose=self.verbose)
        trained = next(r for r in results if r is not None)
        tm = TorchModel(model, trained, self.feature_cols,
                        self.output_col)
        if self.store is not None:
            import io

            buf = io.BytesIO()
            np.savez(buf, **trained)
            ckpt = self.store.get_checkpoint_path(self.run_id)
            self.store.write(f"{ckpt}/model.npz", buf.getvalue())
        return tm


class TorchModel:
    def __init__(self, module, state_dict: Dict[str, np.ndarray],
                 feature_cols: Sequence[str],
                 output_col: str = "prediction"):
        import copy

        import torch

        # own copy: flipping the CALLER's module to eval and overwriting
        # its weights would silently corrupt their continued training
        self.module = copy.deepcopy(module)
        self.module.load_state_dict(
            {k: torch.from_numpy(np.asarray(v))
             for k, v in state_dict.items()}
        )
        self.module.eval()
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    def predict(self, x: np.ndarray) -> np.ndarray:
        import torch

        with torch.no_grad():
            return self.module(torch.from_numpy(
                np.asarray(x, dtype=np.float32)
            )).numpy()

    def transform(self, df):
        return _transform_rdd(
            df, self.feature_cols, self.output_col, self.predict
        )
