"""Spark Estimator/Model API: fit a model to a DataFrame, get back a
transformer for inference.

Reference: /root/reference/horovod/spark/keras/estimator.py:88
(`KerasEstimator`) and spark/torch/estimator.py (`TorchEstimator`) —
`est.fit(df)` launches distributed Horovod training over the DataFrame
and returns a Model whose `transform(df)` appends predictions.

TPU-native redesign, not a port: the reference serializes Keras graphs,
writes the DataFrame to a Petastorm parquet store, and streams row
groups into per-rank data loaders. JAX models are pytrees and the TPU
input path is host numpy → device shards, so this estimator

  * materializes the DataFrame into rank-shardable npz part files
    through the Store ON THE EXECUTORS (prepare_data — the analog of
    the reference's Petastorm parquet write, spark/common/util.py);
    each rank reads only its own share of parts, so dataset size is
    bounded by the Store, never driver RAM;
  * trains with the standard recipe: `hvd.init()` →
    `DistributedOptimizer(optax...)` → per-rank minibatch loop, exactly
    what `spark.run` slots provide;
  * returns a `JaxModel` holding the trained pytree; `transform`
    runs inference partition-by-partition on the executors, and
    `save`/`load` round-trip through `horovod_tpu.checkpoint` (the
    Keras write/read path of the reference maps onto save_model's
    optimizer-spec rehydration).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Sequence

import numpy as np


def _rows_to_matrix(rows, cols: Sequence[str]) -> np.ndarray:
    """Row objects/dicts → float32 matrix over the named columns."""
    return np.asarray(
        [[getattr(r, c) if hasattr(r, c) else r[c] for c in cols]
         for r in rows], dtype=np.float32,
    )


def prepare_data(df, store, run_id: str, feature_cols: Sequence[str],
                 label_cols: Sequence[str], validation: float = 0.0,
                 seed: int = 0) -> Sequence[str]:
    """Materialize the DataFrame into rank-shardable part files through
    the Store — ON THE EXECUTORS, partition by partition. The driver
    only ever sees (partition index, row count) pairs, so dataset size
    is bounded by the Store, not driver RAM (reference
    spark/common/util.py prepare_data + store.py:167's per-rank
    row-group layout; npz parts instead of Petastorm parquet — the TPU
    input path is host numpy → device shards).

    Each part carries its own train/validation split (a deterministic
    per-row Bernoulli(validation) mask seeded by `seed` + partition
    index), mirroring the reference's validation-column split. Returns
    the part file names (relative to ``store.get_data_path(run_id)``),
    sorted.
    """
    import io

    if not 0.0 <= validation < 1.0:
        raise ValueError(
            f"validation must be in [0, 1), got {validation}")
    prefix = store.prefix_path
    data_path = store.get_data_path(run_id)
    fcols, lcols = list(feature_cols), list(label_cols)

    def write_partition(idx, rows):
        from .store import Store

        rows = list(rows)
        if not rows:
            return iter([])
        x = _rows_to_matrix(rows, fcols)
        y = _rows_to_matrix(rows, lcols)
        n = len(x)
        if validation > 0.0:
            # fraction-exact (in expectation) deterministic mask: a
            # stride of round(1/validation) caps the holdout at 50% and
            # quantizes it (0.9 → 50%, 0.3 → 33%) — ADVICE r4 #4
            rng = np.random.RandomState(seed + idx)
            u = rng.random_sample(n)
            val_mask = u < validation
            if val_mask.all():
                # tiny partition, unlucky draw: keep >= 1 training row
                # (the old stride scheme guaranteed this for n >= 2)
                val_mask[int(np.argmax(u))] = False
        else:
            val_mask = np.zeros(n, dtype=bool)
        buf = io.BytesIO()
        np.savez(buf, x=x[~val_mask], y=y[~val_mask],
                 vx=x[val_mask], vy=y[val_mask])
        st = Store.create(prefix)
        name = f"part-{idx:05d}.npz"
        st.write(f"{data_path}/{name}", buf.getvalue())
        return iter([(idx, n)])

    rdd = df.rdd if hasattr(df, "rdd") else df
    parts = rdd.mapPartitionsWithIndex(write_partition).collect()
    if not parts:
        raise ValueError(
            "prepare_data: the DataFrame produced no rows — nothing to "
            "train on")
    return [f"part-{idx:05d}.npz" for idx, _ in sorted(parts)]


def _read_shard(prefix: str, data_path: str, part_names: Sequence[str],
                rank: int, size: int, n_features: int = 1,
                n_labels: int = 1):
    """Load THIS rank's share of the materialized parts (the reference
    assigns per-rank row groups). With >= `size` parts, files are
    round-robined by index; with fewer parts than ranks, each rank
    reads exactly one file and takes a strided row slice of it — either
    way every row belongs to exactly one rank and no rank reads the
    whole dataset. Returns (x, y, vx, vy, n_rows_touched)."""
    import io

    from .store import Store

    st = Store.create(prefix)
    nparts = len(part_names)
    if nparts >= size:
        mine = [(n, 0, 1) for i, n in enumerate(part_names)
                if i % size == rank]
    else:
        # ranks r, r+nparts, ... share part (r % nparts); the stride is
        # how many ranks actually landed on THIS part (the last parts
        # may carry one fewer when nparts does not divide size)
        p = rank % nparts
        sharing = len(range(p, size, nparts))
        mine = [(part_names[p], rank // nparts, sharing)]
    xs, ys, vxs, vys = [], [], [], []
    touched = 0
    for name, sub, stride in mine:
        with np.load(io.BytesIO(st.read(f"{data_path}/{name}"))) as z:
            x, y = z["x"][sub::stride], z["y"][sub::stride]
            vx, vy = z["vx"][sub::stride], z["vy"][sub::stride]
        xs.append(x); ys.append(y); vxs.append(vx); vys.append(vy)
        touched += len(x) + len(vx)
    if not xs or sum(len(a) for a in xs) == 0:
        # keep the true column widths: empty-shard ranks still build
        # zero-filled keep-collectives-alive batches from these shapes
        return (np.zeros((0, n_features), np.float32),
                np.zeros((0, n_labels), np.float32),
                np.zeros((0, n_features), np.float32),
                np.zeros((0, n_labels), np.float32), 0)
    return (np.concatenate(xs), np.concatenate(ys),
            np.concatenate(vxs), np.concatenate(vys), touched)


def _predict_batched(apply_fn, params, x, batch_size=4096):
    """Full-shard prediction in bounded chunks: metric evaluation must
    not materialize activations for millions of rows in one device call
    (that would defeat the store-backed memory bound)."""
    if len(x) <= batch_size:
        return np.asarray(apply_fn(params, x))
    return np.concatenate([
        np.asarray(apply_fn(params, x[i:i + batch_size]))
        for i in range(0, len(x), batch_size)
    ])


def _ephemeral_store():
    """store=None convenience: a LocalStore under a temp dir — fine for
    local mode; real clusters pass a shared-filesystem/fsspec store."""
    import tempfile

    from .store import LocalStore

    return LocalStore(tempfile.mkdtemp(prefix="hvd_tpu_estimator_"))


def _transform_rdd(df, feature_cols: Sequence[str], out_col: str,
                   predict: Callable[[np.ndarray], np.ndarray]):
    """Shared transform body (reference KerasModel.transform's row UDF):
    map each partition's rows through `predict`, appending `out_col`."""

    def map_partition(rows):
        rows = list(rows)
        if not rows:
            return iter([])
        preds = predict(_rows_to_matrix(rows, feature_cols))
        out = []
        for r, p in zip(rows, preds):
            d = r.asDict() if hasattr(r, "asDict") else dict(r)
            d[out_col] = (
                p.tolist() if getattr(p, "ndim", 0) else float(p)
            )
            out.append(d)
        return iter(out)

    rdd = df.rdd if hasattr(df, "rdd") else df
    return rdd.mapPartitions(map_partition)


def _mse(pred, y):
    import jax.numpy as jnp

    return jnp.mean((pred - y) ** 2)


_LOSSES: Dict[str, Callable] = {"mse": _mse}


def _resolve_model(model):
    """(init_fn(rng, x), apply_fn(params, x)) from a flax-style module
    (.init/.apply) or an (init_fn, apply_fn) pair."""
    if hasattr(model, "init") and hasattr(model, "apply"):
        return (lambda rng, x: model.init(rng, x),
                lambda p, x: model.apply(p, x))
    init_fn, apply_fn = model
    return init_fn, apply_fn


class JaxModel:
    """Trained transformer (reference KerasModel): holds the pytree and
    appends a prediction column."""

    def __init__(self, params, apply_fn, feature_cols: Sequence[str],
                 output_col: str = "prediction", metadata=None,
                 optimizer_spec: Optional[tuple] = None, history=None):
        import jax

        self.params = params
        # per-epoch training curves from fit(): train_loss, val_loss and
        # train_/val_<metric> lists (reference estimators surface these
        # through the Keras History object)
        self.history = dict(history or {})
        self._apply = apply_fn
        # jit ONCE: transform maps many partitions and each fresh
        # jax.jit wrapper would recompile from an empty cache
        self._jit_apply = jax.jit(apply_fn)
        self.feature_cols = list(feature_cols)
        self.output_col = output_col
        self.metadata = dict(metadata or {})
        self.optimizer_spec = optimizer_spec

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._jit_apply(self.params, x))

    def transform(self, df):
        """Append predictions row-by-row (reference KerasModel.transform
        appends output columns via a row-mapping UDF). Output rows are
        dicts of the original columns plus `output_col`."""
        return _transform_rdd(
            df, self.feature_cols, self.output_col, self.predict
        )

    def save(self, path: str) -> None:
        """Checkpoint params + the optimizer spec the estimator trained
        with, so hvd.load_model(path) can resume training — not just
        this class's load() for inference."""
        from ..checkpoint import save_model

        save_model(path, self.params, metadata=self.metadata,
                   optimizer_spec=self.optimizer_spec)

    @classmethod
    def load(cls, path: str, apply_fn, feature_cols,
             output_col: str = "prediction"):
        """Rebuild from a checkpoint; `apply_fn` is code, not data —
        the caller supplies it like the reference supplies
        custom_objects at load time."""
        from ..checkpoint import load_params

        params, metadata = load_params(path)
        return cls(params, apply_fn, feature_cols, output_col,
                   metadata=metadata)


class JaxEstimator:
    """Fit a JAX/flax model to a Spark DataFrame with distributed
    training (reference KerasEstimator, spark/keras/estimator.py:88).

    `model` is a flax-style module (.init/.apply) or an
    (init_fn, apply_fn) pair; `optimizer_spec` is the serializable
    ("optax_name", kwargs) identity used throughout this framework;
    `loss` is "mse" or a callable (pred, y) -> scalar.
    """

    def __init__(
        self,
        model,
        feature_cols: Sequence[str],
        label_cols: Sequence[str],
        optimizer_spec: tuple = ("adam", {"learning_rate": 1e-3}),
        loss="mse",
        batch_size: int = 32,
        epochs: int = 1,
        num_proc: Optional[int] = None,
        output_col: str = "prediction",
        seed: int = 0,
        verbose: int = 0,
        store=None,
        run_id: Optional[str] = None,
        validation: float = 0.0,
        metrics: Optional[Dict[str, Callable]] = None,
        callbacks: Optional[Sequence] = None,
        restore_best_weights: bool = False,
    ):
        from .store import store_or_none

        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.optimizer_spec = optimizer_spec
        self.loss = loss
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.output_col = output_col
        self.seed = seed
        self.verbose = verbose
        # reference estimators persist run artifacts through a Store
        # (spark/common/store.py); a string prefix is accepted directly
        self.store = store_or_none(store)
        self.run_id = run_id or "run"
        # reference KerasEstimator-style validation split + metric fns
        # (spark/keras/estimator.py): fraction of rows held out per
        # part; metrics = {name: fn(pred, y) -> scalar} evaluated per
        # epoch on train batches and the validation shard
        self.validation = float(validation)
        self.metrics = dict(metrics or {})
        # horovod_tpu.callbacks instances, invoked like the reference
        # KerasEstimator's callbacks param: on_train_begin, per-epoch
        # begin/end (epoch-end receives the epoch's logs, so
        # MetricAverageCallback averages metrics across ranks), per-batch
        # end. They run inside every training slot. EarlyStoppingCallback
        # members end training for every rank in the same epoch.
        self.callbacks = list(callbacks or [])
        # Lightning checkpoint_callback analog (reference
        # spark/lightning/estimator.py): return the epoch with the best
        # monitored loss (val_loss when a validation split exists, else
        # train_loss) instead of the last; with a Store the persisted
        # model is therefore the best checkpoint.
        self.restore_best_weights = bool(restore_best_weights)

    def fit(self, df) -> JaxModel:
        from . import run as spark_run

        store = self.store if self.store is not None else _ephemeral_store()
        part_names = prepare_data(
            df, store, self.run_id, self.feature_cols, self.label_cols,
            validation=self.validation, seed=self.seed)
        prefix = store.prefix_path
        data_path = store.get_data_path(self.run_id)
        loss_fn = (
            _LOSSES[self.loss] if isinstance(self.loss, str) else self.loss
        )
        init_fn, apply_fn = _resolve_model(self.model)
        spec = self.optimizer_spec
        batch_size, epochs, seed = self.batch_size, self.epochs, self.seed
        n_features = len(self.feature_cols)
        n_labels = len(self.label_cols)
        metric_fns = self.metrics
        cbs = self.callbacks
        restore_best = self.restore_best_weights

        def train():
            import os

            import jax
            import optax

            import horovod_tpu as hvd

            hvd.init()
            # the SLOT's rank shards the data (one shard per Spark
            # barrier task, like the reference's per-rank row groups) —
            # hvd.size() counts devices, which in single-process worlds
            # exceeds the slot count
            rank = int(os.environ.get("HOROVOD_RANK", hvd.rank()))
            size = int(os.environ.get("HOROVOD_SIZE", hvd.size()))
            # THIS rank's share of the store-materialized parts; the
            # whole dataset never converges on any single process
            xs, ys, vx, vy, touched = _read_shard(
                prefix, data_path, part_names, rank, size,
                n_features=n_features, n_labels=n_labels)
            params = init_fn(
                jax.random.PRNGKey(seed),
                np.zeros((1, n_features), np.float32))
            name, kwargs = spec
            opt = hvd.DistributedOptimizer(getattr(optax, name)(**kwargs))
            opt_state = opt.init(params)
            params = hvd.broadcast_parameters(params, root_rank=0)

            @jax.jit
            def step(p, s, bx, by, w):
                # w = w_r[i] / mean_r(w[i]) (see `scale` above): keep-
                # alive batches on empty/short shards run the SAME
                # collectives (step-count parity) but their loss is
                # scaled to 0, so they contribute identity gradients to
                # the cross-rank average instead of biasing every rank's
                # update with gradients of zero-filled rows; partial
                # batches are weighted by their valid-sample fraction
                # relative to the other ranks' (ADVICE r4 #3)
                def lf(p):
                    raw = loss_fn(apply_fn(p, bx), by)
                    return raw * w, raw

                (_, raw), g = jax.value_and_grad(lf, has_aux=True)(p)
                u, s = opt.update(g, s, p)
                return optax.apply_updates(p, u), s, raw

            n = len(xs)
            # every rank must run the same number of steps (collectives
            # per step); short shards wrap around their rows
            steps = max(1, -(-n // batch_size)) if n else 1
            steps = int(np.max(np.asarray(
                hvd.allgather(np.asarray([steps], np.int64)))))
            # per-step gradient weights: w_r[i] = fraction of rank r's
            # batch i that is real (un-wrapped) data. The loss is scaled
            # by w_r[i] / mean_r(w[i]) so the allreduce-AVERAGE of the
            # gradients equals the VALID-SAMPLE-weighted mean — scaling
            # by w alone would shrink every update by mean(w) instead of
            # reweighting across ranks (keep-alive batches then
            # contribute exactly identity gradients, ADVICE r4 #3)
            w_local = np.asarray(
                [np.count_nonzero(
                    np.arange(i * batch_size, (i + 1) * batch_size) < n)
                 / batch_size for i in range(steps)], np.float32)
            w_all = np.asarray(hvd.allgather(
                w_local[None, :])).reshape(-1, steps)
            w_mean = w_all.mean(axis=0)
            # steps = max over ranks of ceil(n/batch): the max-achieving
            # rank has w > 0 at every step, so w_mean > 0 always; guard
            # for safety
            scale = np.where(w_mean > 0, w_local / np.maximum(
                w_mean, 1e-12), 0.0).astype(np.float32)
            history = {"train_loss": []}
            if len(vx):
                history["val_loss"] = []
            for mname in metric_fns:
                history[f"train_{mname}"] = []
                if len(vx):
                    history[f"val_{mname}"] = []
            cb_state = None
            best_val = best_params = best_epoch = stopped_epoch = None
            for cb in cbs:
                cb_state = cb.on_train_begin(cb_state)
            for epoch in range(epochs):
                for cb in cbs:
                    cb_state = cb.on_epoch_begin(epoch, cb_state)
                perm = (np.random.RandomState(seed + epoch).permutation(n)
                        if n else np.zeros((0,), np.int64))
                losses = []
                for i in range(steps):
                    if n == 0:
                        bx = np.zeros((batch_size, n_features),
                                      np.float32)
                        by = np.zeros(
                            (batch_size,) + ys.shape[1:], np.float32)
                    else:
                        pos = np.arange(i * batch_size,
                                        (i + 1) * batch_size)
                        idx = np.take(perm, pos % n, mode="wrap")
                        bx, by = xs[idx], ys[idx]
                    params, opt_state, l = step(
                        params, opt_state, bx, by, scale[i])
                    if w_local[i] > 0:
                        losses.append(float(l))
                    for cb in cbs:
                        cb_state = cb.on_batch_end(i, cb_state)
                # cross-rank VALID-SAMPLE-weighted epoch loss, identical
                # on every rank: an empty-shard rank logging a 0.0
                # sentinel would deflate MetricAverageCallback's average
                loss_w = (float(np.dot(losses, w_local[w_local > 0]))
                          if losses else 0.0)
                sums = np.asarray(hvd.allreduce(np.asarray(
                    [loss_w, float(w_local.sum())], np.float32),
                    op=hvd.Sum))
                history["train_loss"].append(
                    float(sums[0]) / max(float(sums[1]), 1e-12))
                pred = None
                if metric_fns and n:
                    pred = _predict_batched(apply_fn, params, xs)
                for mname, fn in metric_fns.items():
                    history[f"train_{mname}"].append(
                        float(fn(pred, ys)) if pred is not None else 0.0)
                if len(vx):
                    vpred = _predict_batched(apply_fn, params, vx)
                    history["val_loss"].append(
                        float(loss_fn(vpred, vy)))
                    for mname, fn in metric_fns.items():
                        history[f"val_{mname}"].append(
                            float(fn(vpred, vy)))
                if cbs:
                    # callbacks may rewrite logs in place (e.g.
                    # MetricAverageCallback's cross-rank average) or add
                    # new keys (Keras-style logs["lr"] = ...)
                    logs = {k: v[-1] for k, v in history.items() if v}
                    for cb in cbs:
                        cb_state = cb.on_epoch_end(epoch, logs, cb_state)
                    for k, v in logs.items():
                        series = history.setdefault(k, [])
                        if len(series) == epoch + 1:
                            series[-1] = v
                        else:
                            series.append(v)
                # best-epoch tracking (Lightning checkpoint_callback
                # analog, spark/lightning/estimator.py): monitor
                # val_loss when a split exists, else the (cross-rank
                # weighted, rank-identical) train_loss
                monitor = "val_loss" if history.get("val_loss") else \
                    "train_loss"
                mval = history[monitor][-1]
                if best_val is None or mval < best_val:
                    best_val, best_epoch = mval, epoch
                    if restore_best and rank == 0:
                        best_params = jax.tree_util.tree_map(
                            np.asarray, params)
                # early stop: OR-reduce the callbacks' verdicts so every
                # rank leaves the collective schedule in the SAME epoch
                # (a per-rank break would deadlock the next allreduce)
                want_stop = any(
                    bool(getattr(cb, "stop_training", False))
                    for cb in cbs)
                agreed = np.asarray(hvd.allreduce(np.asarray(
                    [1.0 if want_stop else 0.0], np.float32),
                    op=hvd.Sum))
                if float(agreed[0]) > 0:
                    stopped_epoch = epoch
                    break
            hvd.shutdown()
            out = {"rank": rank, "rows_touched": int(touched),
                   "history": history, "best_epoch": best_epoch,
                   "stopped_epoch": stopped_epoch}
            if rank == 0:
                out["params"] = (
                    best_params if restore_best and best_params is not None
                    else jax.tree_util.tree_map(np.asarray, params))
            return out

        results = spark_run(train, num_proc=self.num_proc,
                            verbose=self.verbose)
        root = next(r for r in results if r and "params" in r)
        trained = root["params"]
        jm = JaxModel(trained, apply_fn, self.feature_cols,
                      self.output_col,
                      metadata={"epochs": self.epochs,
                                "best_epoch": root.get("best_epoch"),
                                "stopped_epoch": root.get(
                                    "stopped_epoch"),
                                "restored_best": bool(
                                    self.restore_best_weights)},
                      optimizer_spec=self.optimizer_spec,
                      history=root["history"])
        jm.rows_touched_per_rank = {
            r["rank"]: r["rows_touched"] for r in results if r}
        if self.store is not None:
            import tempfile

            # save_model writes a directory tree; mirror it in bulk
            # under <prefix>/<run_id>/checkpoint/model
            ckpt = self.store.get_checkpoint_path(self.run_id)
            with tempfile.TemporaryDirectory() as tmp:
                local = os.path.join(tmp, "model")
                jm.save(local)
                self.store.upload(local, f"{ckpt}/model")
        return jm


class TorchEstimator:
    """Fit a torch.nn.Module to a Spark DataFrame via this framework's
    torch adapter (reference spark/torch/estimator.py). Same DataFrame
    contract as JaxEstimator; training uses
    horovod_tpu.torch.DistributedOptimizer."""

    # Lightning-style hook points (set by LightningEstimator): when
    # non-None, the train loop computes loss via
    # _train_step_fn(model, bx, by, batch_idx) instead of
    # loss(model(bx), by), and validation via _val_step_fn likewise.
    _train_step_fn = None
    _val_step_fn = None

    def __init__(
        self,
        model,
        feature_cols: Sequence[str],
        label_cols: Sequence[str],
        optimizer_factory: Optional[Callable] = None,
        loss: Optional[Callable] = None,
        batch_size: int = 32,
        epochs: int = 1,
        num_proc: Optional[int] = None,
        output_col: str = "prediction",
        verbose: int = 0,
        store=None,
        run_id: Optional[str] = None,
        seed: int = 0,
        validation: float = 0.0,
        metrics: Optional[Dict[str, Callable]] = None,
        callbacks: Optional[Sequence] = None,
        restore_best_weights: bool = False,
    ):
        from .store import store_or_none

        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.optimizer_factory = optimizer_factory
        self.loss = loss
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.output_col = output_col
        self.verbose = verbose
        self.store = store_or_none(store)
        self.run_id = run_id or "run"
        self.seed = seed
        self.validation = float(validation)
        self.metrics = dict(metrics or {})
        # same contract as JaxEstimator.callbacks (runs in every slot)
        self.callbacks = list(callbacks or [])
        # Lightning checkpoint_callback analog: see JaxEstimator
        self.restore_best_weights = bool(restore_best_weights)

    def fit(self, df) -> "TorchModel":
        import torch

        from . import run as spark_run

        store = self.store if self.store is not None else _ephemeral_store()
        part_names = prepare_data(
            df, store, self.run_id, self.feature_cols, self.label_cols,
            validation=self.validation, seed=self.seed)
        prefix = store.prefix_path
        data_path = store.get_data_path(self.run_id)
        model = self.model
        opt_factory = self.optimizer_factory or (
            lambda params: torch.optim.SGD(params, lr=0.01)
        )
        loss_fn = self.loss or torch.nn.functional.mse_loss
        batch_size, epochs, seed = self.batch_size, self.epochs, self.seed
        n_features = len(self.feature_cols)
        n_labels = len(self.label_cols)
        metric_fns = self.metrics
        cbs = self.callbacks
        restore_best = self.restore_best_weights
        train_step_fn = self._train_step_fn
        val_step_fn = self._val_step_fn

        def train():
            import os

            import numpy as np
            import torch

            import horovod_tpu.torch as thvd

            thvd.init()
            rank = int(os.environ.get("HOROVOD_RANK", thvd.rank()))
            size = int(os.environ.get("HOROVOD_SIZE", thvd.size()))
            x_, y_, vx_, vy_, touched = _read_shard(
                prefix, data_path, part_names, rank, size,
                n_features=n_features, n_labels=n_labels)
            xs, ys = torch.from_numpy(x_), torch.from_numpy(y_)
            vx, vy = torch.from_numpy(vx_), torch.from_numpy(vy_)
            opt = thvd.DistributedOptimizer(
                opt_factory(model.parameters()),
                named_parameters=list(model.named_parameters()),
            )
            thvd.broadcast_parameters(model.state_dict(), root_rank=0)
            n = len(xs)
            # every rank must run the same number of steps (each step's
            # gradient allreduce is a collective); short shards wrap
            steps = max(1, -(-n // batch_size)) if n else 1
            steps = int(torch.max(thvd.allgather(
                torch.tensor([steps], dtype=torch.int64))))
            # same keep-alive weighting as the Jax estimator (ADVICE r4
            # #3): scale each batch's loss by w_r[i]/mean_r(w[i]) so
            # zero-filled / wrapped batches contribute identity (or
            # proportionally down-weighted) gradients to the allreduce
            # average instead of full-weight zero-data gradients
            w_local = np.asarray(
                [np.count_nonzero(
                    np.arange(i * batch_size, (i + 1) * batch_size) < n)
                 / batch_size for i in range(steps)], np.float32)
            w_all = thvd.allgather(
                torch.from_numpy(w_local[None, :])).numpy()
            w_mean = w_all.reshape(-1, steps).mean(axis=0)
            scale = np.where(w_mean > 0, w_local / np.maximum(
                w_mean, 1e-12), 0.0).astype(np.float32)
            history = {"train_loss": []}
            if len(vx):
                history["val_loss"] = []
            for mname in metric_fns:
                history[f"train_{mname}"] = []
                if len(vx):
                    history[f"val_{mname}"] = []
            cb_state = None
            best_val = best_params = best_epoch = stopped_epoch = None
            for cb in cbs:
                cb_state = cb.on_train_begin(cb_state)
            for epoch in range(epochs):
                for cb in cbs:
                    cb_state = cb.on_epoch_begin(epoch, cb_state)
                perm = torch.from_numpy(
                    np.random.RandomState(seed + epoch).permutation(
                        max(n, 1)))
                losses = []
                for i in range(steps):
                    idx = perm[
                        torch.arange(i * batch_size,
                                     (i + 1) * batch_size) % max(n, 1)]
                    bx = xs[idx] if n else torch.zeros(
                        (batch_size, xs.shape[-1]))
                    by = ys[idx] if n else torch.zeros(
                        (batch_size, ys.shape[-1]))
                    opt.zero_grad()
                    loss = (train_step_fn(model, bx, by, i)
                            if train_step_fn is not None
                            else loss_fn(model(bx), by))
                    (loss * float(scale[i])).backward()
                    opt.step()
                    if w_local[i] > 0:
                        losses.append(float(loss.detach()))
                    for cb in cbs:
                        cb_state = cb.on_batch_end(i, cb_state)
                # cross-rank VALID-SAMPLE-weighted epoch loss, identical
                # on every rank: an empty-shard rank logging a 0.0
                # sentinel would deflate MetricAverageCallback's average
                loss_w = float(np.dot(
                    [float(v) for v in losses] or [0.0],
                    w_local[w_local > 0] if len(losses) else [0.0]))
                sums = thvd.allreduce(
                    torch.tensor([loss_w, float(w_local.sum())]),
                    op=thvd.Sum)
                history["train_loss"].append(
                    float(sums[0] / max(float(sums[1]), 1e-12)))
                chunk = 4096  # bounded eval: never materialize the
                # whole shard's activations in one call

                def eval_batched(t):
                    with torch.no_grad():
                        return torch.cat([
                            model(t[i:i + chunk])
                            for i in range(0, len(t), chunk)
                        ]) if len(t) else model(t)

                if metric_fns and n:
                    pred = eval_batched(xs)
                    for mname, fn in metric_fns.items():
                        history[f"train_{mname}"].append(
                            float(fn(pred, ys)))
                if len(vx):
                    vpred = (eval_batched(vx)
                             if (metric_fns or val_step_fn is None)
                             else None)
                    if val_step_fn is not None:
                        with torch.no_grad():
                            tot = sum(
                                float(val_step_fn(
                                    model, vx[j:j + chunk],
                                    vy[j:j + chunk], j // chunk))
                                * len(vx[j:j + chunk])
                                for j in range(0, len(vx), chunk))
                        history["val_loss"].append(tot / len(vx))
                    else:
                        history["val_loss"].append(
                            float(loss_fn(vpred, vy)))
                    for mname, fn in metric_fns.items():
                        history[f"val_{mname}"].append(
                            float(fn(vpred, vy)))
                if cbs:
                    logs = {k: v[-1] for k, v in history.items() if v}
                    for cb in cbs:
                        cb_state = cb.on_epoch_end(epoch, logs, cb_state)
                    for k, v in logs.items():
                        series = history.setdefault(k, [])
                        if len(series) == epoch + 1:
                            series[-1] = v
                        else:
                            series.append(v)
                # best-epoch tracking + OR-reduced early stop — same
                # semantics as JaxEstimator (Lightning analog)
                monitor = "val_loss" if history.get("val_loss") else \
                    "train_loss"
                mval = history[monitor][-1]
                if best_val is None or mval < best_val:
                    best_val, best_epoch = mval, epoch
                    if restore_best and rank == 0:
                        best_params = {
                            k: v.detach().cpu().numpy().copy()
                            for k, v in model.state_dict().items()
                        }
                want_stop = any(
                    bool(getattr(cb, "stop_training", False))
                    for cb in cbs)
                agreed = thvd.allreduce(
                    torch.tensor([1.0 if want_stop else 0.0]),
                    op=thvd.Sum)
                if float(agreed[0]) > 0:
                    stopped_epoch = epoch
                    break
            thvd.shutdown()
            out = {"rank": rank, "rows_touched": int(touched),
                   "history": history, "best_epoch": best_epoch,
                   "stopped_epoch": stopped_epoch}
            if rank == 0:
                out["params"] = (
                    best_params
                    if restore_best and best_params is not None
                    else {
                        k: v.detach().cpu().numpy()
                        for k, v in model.state_dict().items()
                    })
            return out

        results = spark_run(train, num_proc=self.num_proc,
                            verbose=self.verbose)
        root = next(r for r in results if r and "params" in r)
        trained = root["params"]
        tm = TorchModel(model, trained, self.feature_cols,
                        self.output_col)
        tm.history = root["history"]
        tm.best_epoch = root.get("best_epoch")
        tm.stopped_epoch = root.get("stopped_epoch")
        tm.rows_touched_per_rank = {
            r["rank"]: r["rows_touched"] for r in results if r}
        if self.store is not None:
            import io

            buf = io.BytesIO()
            np.savez(buf, **trained)
            ckpt = self.store.get_checkpoint_path(self.run_id)
            self.store.write(f"{ckpt}/model.npz", buf.getvalue())
        return tm


class TorchModel:
    def __init__(self, module, state_dict: Dict[str, np.ndarray],
                 feature_cols: Sequence[str],
                 output_col: str = "prediction"):
        import copy

        import torch

        # own copy: flipping the CALLER's module to eval and overwriting
        # its weights would silently corrupt their continued training
        self.module = copy.deepcopy(module)
        self.module.load_state_dict(
            {k: torch.from_numpy(np.asarray(v))
             for k, v in state_dict.items()}
        )
        self.module.eval()
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    def predict(self, x: np.ndarray) -> np.ndarray:
        import torch

        with torch.no_grad():
            return self.module(torch.from_numpy(
                np.asarray(x, dtype=np.float32)
            )).numpy()

    def transform(self, df):
        return _transform_rdd(
            df, self.feature_cols, self.output_col, self.predict
        )


def _lightning_loss(out):
    """training_step/validation_step may return the loss tensor or a
    dict carrying it under "loss" (Lightning contract)."""
    if isinstance(out, dict):
        out = out["loss"]
    return out


def _first_optimizer(cfg):
    """Unwrap configure_optimizers()'s accepted shapes — a single
    optimizer, [optimizers], ([optimizers], [schedulers]), or
    {"optimizer": opt, ...} — down to one optimizer. Multi-optimizer
    setups (GANs) are out of scope here, as in the reference's
    estimator; LR schedulers are not stepped by this train loop, so
    their presence warns rather than being silently dropped."""
    import warnings

    schedulers = None
    if isinstance(cfg, dict):
        schedulers = cfg.get("lr_scheduler")
        cfg = cfg["optimizer"]
    if isinstance(cfg, (list, tuple)):
        if (len(cfg) == 2 and isinstance(cfg[0], (list, tuple))
                and isinstance(cfg[1], (list, tuple))):
            cfg, schedulers = cfg[0], (cfg[1] or None)
        opts = list(cfg) if isinstance(cfg, (list, tuple)) else [cfg]
        if len(opts) != 1:
            raise ValueError(
                "LightningEstimator supports exactly one optimizer; "
                f"configure_optimizers() returned {len(opts)}")
        cfg = opts[0]
    if schedulers:
        warnings.warn(
            "LightningEstimator does not step LR schedulers returned "
            "by configure_optimizers(); training runs at the "
            "optimizer's base LR. Fold the schedule into the optimizer "
            "or train with horovod_tpu.torch directly.",
            stacklevel=3)
    return cfg


class LightningEstimator(TorchEstimator):
    """Fit a Lightning-STYLE module to a Spark DataFrame — the third
    estimator flavor (reference
    /root/reference/horovod/spark/lightning/estimator.py:1).

    The module contract is duck-typed, so real
    ``pytorch_lightning.LightningModule`` subclasses work unchanged and
    no pytorch-lightning install is required:

      * ``training_step((x, y), batch_idx) -> loss`` (or
        ``{"loss": ...}``) — required; the module does its own forward.
      * ``configure_optimizers()`` — required; single-optimizer forms
        (optimizer, [optimizer], ([opts], [scheds]), {"optimizer": ...}).
      * ``validation_step((x, y), batch_idx)`` — optional; drives
        ``val_loss`` history (and early stopping / best-checkpoint
        monitoring) when a validation split exists.
      * ``forward(x)`` — optional; needed by ``transform()`` and
        metric fns.

    Batches arrive as ``(features, labels)`` float tensors, matching
    the reference estimator's (feature_cols, label_cols) DataFrame
    contract. Everything else — store-backed shards, keep-alive
    weighting, metric/early-stop callbacks, ``restore_best_weights``,
    per-epoch ``history`` on the returned model — is shared with
    TorchEstimator.
    """

    def __init__(self, model, feature_cols: Sequence[str],
                 label_cols: Sequence[str], **kwargs):
        for hook in ("training_step", "configure_optimizers"):
            if not callable(getattr(model, hook, None)):
                raise ValueError(
                    f"Lightning-style module must define {hook}(); "
                    "got " + type(model).__name__)
        if "optimizer_factory" in kwargs or "loss" in kwargs:
            raise ValueError(
                "LightningEstimator derives the optimizer from "
                "configure_optimizers() and the loss from "
                "training_step(); don't pass optimizer_factory/loss")
        super().__init__(
            model=model, feature_cols=feature_cols,
            label_cols=label_cols,
            optimizer_factory=lambda params: _first_optimizer(
                model.configure_optimizers()),
            **kwargs)
        self._train_step_fn = (
            lambda m, bx, by, i: _lightning_loss(
                m.training_step((bx, by), i)))
        if callable(getattr(model, "validation_step", None)):
            self._val_step_fn = (
                lambda m, bx, by, i: _lightning_loss(
                    m.validation_step((bx, by), i)))
