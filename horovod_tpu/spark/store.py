"""Storage abstraction for Spark estimators — run artifacts by scheme.

Role parity with the reference's Store family
(/root/reference/horovod/spark/common/store.py: Store.create dispatching
to LocalStore/HDFSStore/S3Store/GCSStore/DBFSLocalStore), trimmed to
what this framework's estimators actually persist: checkpoints, logs and
run metadata. The reference additionally materializes Petastorm training
data through its store; here training data reaches workers through the
estimator's own collect/shard path (spark/estimator.py), so the data
half of the API is intentionally absent rather than stubbed.

Cloud backends (S3/GCS/HDFS/DBFS) dispatch through `fsspec` when it is
installed; the image this framework ships in has no cloud filesystem
libraries, so those schemes raise a clear ImportError at construction
instead of failing deep inside a write.
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional


class Store:
    """Filesystem-like surface the estimators persist through.

    Path layout mirrors the reference (store.py get_checkpoint_path /
    get_logs_path): `<prefix>/<run_id>/{checkpoint,logs}`.
    """

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path.rstrip("/")

    # --- path layout ---

    def get_run_path(self, run_id: str) -> str:
        return f"{self.prefix_path}/{run_id}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/checkpoint"

    def get_logs_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/logs"

    def get_data_path(self, run_id: str) -> str:
        """Materialized training shards (reference AbstractFilesystemStore
        row-group layout, spark/common/store.py:167 — npz parts here)."""
        return f"{self.get_run_path(run_id)}/data"

    # --- filesystem surface (overridden per backend) ---

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def upload(self, local_dir: str, dest: str) -> None:
        """Mirror a local directory tree into the store at `dest` — the
        one bulk operation estimators need after writing a checkpoint."""
        raise NotImplementedError

    # --- factory ---

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Dispatch on the URL scheme (reference store.py Store.create).

        file:// and plain paths → LocalStore; dbfs:/ → LocalStore on the
        /dbfs fuse mount (the reference's DBFSLocalStore does the same
        mapping — fsspec would silently treat the single-slash form as a
        relative local path); any other ``scheme://`` → fsspec, which
        raises a clear ImportError when the scheme's filesystem package
        (s3fs, gcsfs, adlfs, pyarrow for hdfs, ...) is missing."""
        if prefix_path.startswith("dbfs:/"):
            return LocalStore("/dbfs/" + prefix_path[len("dbfs:/"):].lstrip("/"))
        if "://" in prefix_path and not prefix_path.startswith("file://"):
            return FsspecStore(prefix_path)
        return LocalStore(prefix_path)


class LocalStore(Store):
    """Plain local/NFS filesystem (reference LocalStore)."""

    def __init__(self, prefix_path: str):
        if prefix_path.startswith("file://"):
            prefix_path = prefix_path[len("file://"):]
        super().__init__(prefix_path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: readers never see partial writes

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def upload(self, local_dir: str, dest: str) -> None:
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)


class FsspecStore(Store):
    """Cloud-object-store backend over fsspec (covers the reference's
    HDFSStore/S3Store/GCSStore/DBFS rows with one implementation —
    fsspec is the protocol multiplexer those ecosystems standardized on
    after the reference hand-rolled per-scheme clients)."""

    def __init__(self, prefix_path: str):
        try:
            import fsspec
        except ImportError as e:
            scheme = prefix_path.split(":", 1)[0]
            raise ImportError(
                f"store scheme '{scheme}://' needs the fsspec package "
                f"(plus its {scheme} filesystem implementation); install "
                "it or use a LocalStore prefix"
            ) from e
        super().__init__(prefix_path)
        self._fs, _ = fsspec.core.url_to_fs(prefix_path)

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def read(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        # mirror LocalStore's contract: parents are created on write.
        # Guarded — flat object stores may not implement makedirs, and
        # there it is also unnecessary (ADVICE r3).
        import posixpath

        parent = posixpath.dirname(path)
        if parent:
            try:
                self._fs.makedirs(parent, exist_ok=True)
            except (NotImplementedError, OSError):
                pass
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        # mirror LocalStore: removing an absent path is a no-op
        if self._fs.exists(path):
            self._fs.rm(path, recursive=True)

    def listdir(self, path: str) -> List[str]:
        # fs.ls returns full paths; LocalStore's contract is basenames
        import posixpath

        return sorted(
            posixpath.basename(p.rstrip("/"))
            for p in self._fs.ls(path, detail=False)
        )

    def upload(self, local_dir: str, dest: str) -> None:
        self._fs.put(local_dir, dest, recursive=True)


def store_or_none(store) -> Optional[Store]:
    """Estimator-ctor convenience: accept a Store, a prefix string, or
    None."""
    if store is None:
        return None
    return store if isinstance(store, Store) else Store.create(str(store))
