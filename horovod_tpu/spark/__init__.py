"""Spark integration: run horovod_tpu training inside Spark tasks.

Reference: /root/reference/horovod/spark/runner.py:200 (`horovod.spark.run`)
— Spark barrier tasks become Horovod slots; the driver collects task host
info, assigns ranks, and results return through Spark. This adapter keeps
that shape: one Spark barrier task per slot, slot env injected via the
same launcher protocol (exec_run.slot_env), results collected from the
tasks. Estimator APIs (KerasEstimator/TorchEstimator over Petastorm
stores, reference spark/keras/estimator.py) are out of scope for the TPU
build: on TPU, data feeding is jax-native (data/ShardedDataLoader).

Import is gated: pyspark is an optional dependency.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark (pip install pyspark); "
            "for local multi-process runs use horovod_tpu.runner.run()"
        ) from e


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    extra_env: Optional[dict] = None,
    verbose: int = 1,
) -> List[Any]:
    """Run `fn` on `num_proc` Spark barrier tasks as horovod_tpu slots
    (reference spark/runner.py:200).

    Each task sets the slot env (HOROVOD_RANK/..., coordination-service
    address published by rank 0 through the Spark barrier) and calls `fn`.
    Returns the per-rank results.
    """
    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = int(sc.defaultParallelism)
    kwargs = kwargs or {}
    env = dict(extra_env or {})

    def task(it):
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()
        size = len(infos)
        hosts = [info.address.split(":")[0] for info in infos]
        coordinator = hosts[0]
        # local/cross ranks from task host placement (reference
        # spark/driver/driver_service.py computes the same from task info)
        my_host = hosts[rank]
        local_rank = hosts[:rank].count(my_host)
        local_size = hosts.count(my_host)
        host_order = list(dict.fromkeys(hosts))
        cross_rank = host_order.index(my_host)
        cross_size = len(host_order)
        os.environ.update(env)
        for k, v in {
            "HOROVOD_RANK": rank, "HOROVOD_SIZE": size,
            "HOROVOD_LOCAL_RANK": local_rank,
            "HOROVOD_LOCAL_SIZE": local_size,
            "HOROVOD_CROSS_RANK": cross_rank,
            "HOROVOD_CROSS_SIZE": cross_size,
            "HVD_TPU_RANK": rank, "HVD_TPU_SIZE": size,
            "HVD_TPU_PROCESS_ID": rank, "HVD_TPU_NUM_PROCESSES": size,
            "HVD_TPU_COORDINATOR_ADDRESS": f"{coordinator}:9099",
        }.items():
            os.environ[k] = str(v)
        ctx.barrier()
        yield (rank, fn(*args, **kwargs))

    rdd = sc.parallelize(range(num_proc), num_proc).barrier()
    results = rdd.mapPartitions(task).collect()
    return [r for _, r in sorted(results)]


def run_elastic(*a, **kw):
    raise NotImplementedError(
        "elastic Spark jobs: use hvdrun --host-discovery-script with a "
        "script that queries the Spark cluster (reference "
        "spark/runner.py:312 maps onto the elastic driver here)"
    )
