"""Spark integration: run horovod_tpu training inside Spark tasks.

Reference: /root/reference/horovod/spark/runner.py:200 (`horovod.spark.run`)
— Spark barrier tasks become Horovod slots; the driver collects task host
info, assigns ranks, and results return through Spark. This adapter keeps
that shape: one Spark barrier task per slot, slot env injected via the
same launcher protocol (exec_run.slot_env), results collected from the
tasks. Estimator APIs live in .estimator (JaxEstimator/TorchEstimator —
the reference's KerasEstimator/TorchEstimator re-designed without the
Petastorm store: on TPU, data feeding is jax-native numpy shards;
data/ShardedDataLoader covers bigger-than-driver datasets outside Spark).

Import is gated: pyspark is an optional dependency.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark (pip install pyspark); "
            "for local multi-process runs use horovod_tpu.runner.run()"
        ) from e


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    extra_env: Optional[dict] = None,
    verbose: int = 1,
) -> List[Any]:
    """Run `fn` on `num_proc` Spark barrier tasks as horovod_tpu slots
    (reference spark/runner.py:200).

    Each task sets the slot env (HOROVOD_RANK/..., coordination-service
    address published by rank 0 through the Spark barrier) and calls `fn`.
    Returns the per-rank results.
    """
    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = int(sc.defaultParallelism)
    kwargs = kwargs or {}
    env = dict(extra_env or {})

    def task(it):
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()
        size = len(infos)
        hosts = [info.address.split(":")[0] for info in infos]
        coordinator = hosts[0]
        # local/cross ranks from task host placement (reference
        # spark/driver/driver_service.py computes the same from task info)
        my_host = hosts[rank]
        local_rank = hosts[:rank].count(my_host)
        local_size = hosts.count(my_host)
        host_order = list(dict.fromkeys(hosts))
        cross_rank = host_order.index(my_host)
        cross_size = len(host_order)
        os.environ.update(env)
        for k, v in {
            "HOROVOD_RANK": rank, "HOROVOD_SIZE": size,
            "HOROVOD_LOCAL_RANK": local_rank,
            "HOROVOD_LOCAL_SIZE": local_size,
            "HOROVOD_CROSS_RANK": cross_rank,
            "HOROVOD_CROSS_SIZE": cross_size,
            "HVD_TPU_RANK": rank, "HVD_TPU_SIZE": size,
            "HVD_TPU_PROCESS_ID": rank, "HVD_TPU_NUM_PROCESSES": size,
            "HVD_TPU_COORDINATOR_ADDRESS": f"{coordinator}:9099",
        }.items():
            os.environ[k] = str(v)
        ctx.barrier()
        yield (rank, fn(*args, **kwargs))

    rdd = sc.parallelize(range(num_proc), num_proc).barrier()
    results = rdd.mapPartitions(task).collect()
    return [r for _, r in sorted(results)]


def _cluster_parallelism(sc) -> int:
    """Current schedulable slots reported by the Spark cluster."""
    return max(1, int(sc.defaultParallelism))


def run_elastic(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    reset_limit: int = 0,
    elastic_timeout_s: float = 600.0,
    extra_env: Optional[dict] = None,
    verbose: int = 1,
) -> List[Any]:
    """Elastic training over a dynamic Spark cluster (reference
    spark/runner.py:312 run_elastic).

    The respawn-round model of this framework's elastic driver, at the
    Spark level: each round is one barrier job sized to the slots the
    cluster currently offers (clamped to [min_np, max_np]); a failed
    round — lost executors, preempted nodes — re-sizes and re-runs. `fn`
    should follow the elastic-state recipe (hvd.elastic.TpuState + commit)
    so resumed rounds continue from committed state; Spark's own task
    blacklisting keeps failing executors out of later rounds.
    """
    import time as _time

    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = _cluster_parallelism(sc)
    min_np = min_np or 1
    max_np = max_np or num_proc
    kwargs = kwargs or {}

    def _wait_for_min_slots() -> int:
        """Block until the cluster offers >= min_np schedulable slots
        (the driver-level wait_for_available_slots analog,
        runner/elastic/driver.py) — submitting a barrier job wider than
        the cluster fails at scheduling, which must read as "wait for
        recovery", never as a deterministic failure."""
        wait_start = _time.monotonic()
        while True:
            available = _cluster_parallelism(sc)
            if available >= min_np:
                return available
            if _time.monotonic() - wait_start > elastic_timeout_s:
                raise RuntimeError(
                    f"cluster offered {available} < min_np={min_np} "
                    f"slots for {elastic_timeout_s}s"
                )
            if verbose:
                print(
                    f"horovod_tpu.spark: waiting for >= {min_np} slots "
                    f"(cluster offers {available})",
                    flush=True,
                )
            _time.sleep(1.0)

    resets = 0
    fast_failures = 0
    current = max(min_np, min(num_proc, max_np))
    while True:
        round_start = _time.monotonic()
        try:
            return run(
                fn, args=args, kwargs=kwargs, num_proc=current,
                extra_env=extra_env, verbose=verbose,
            )
        except Exception as e:
            resets += 1
            if reset_limit and resets >= reset_limit:
                raise RuntimeError(
                    f"elastic Spark job failed after {resets} resets"
                ) from e
            available = _wait_for_min_slots()
            # A round that dies immediately is a deterministic failure
            # (user bug, broken config), not an executor loss — elastic
            # retries cannot fix it. Three in a row terminates even with
            # an unlimited reset_limit, so a TypeError in fn can't
            # resubmit barrier jobs forever. Only rounds the cluster
            # could actually schedule count: if it shrank below what we
            # submitted, the fast death was a scheduling/loss artifact.
            if (_time.monotonic() - round_start < 5.0
                    and available >= current):
                fast_failures += 1
                if fast_failures >= 3:
                    raise RuntimeError(
                        "elastic Spark job failed 3 consecutive rounds "
                        "within seconds — the failure looks "
                        "deterministic, not an executor loss"
                    ) from e
            else:
                fast_failures = 0
            current = max(min_np, min(available, max_np))
            if verbose:
                print(
                    f"horovod_tpu.spark: round failed ({e}); retrying "
                    f"with {current} slots",
                    flush=True,
                )
            _time.sleep(1.0)  # backoff before resubmitting the round


# Estimator API (reference spark/keras/estimator.py, spark/torch/
# estimator.py): imported at the bottom — estimator.py's fit() calls
# back into this module's run().
from .estimator import (  # noqa: E402,F401
    JaxEstimator,
    JaxModel,
    LightningEstimator,
    TorchEstimator,
    TorchModel,
)
from .store import (  # noqa: E402,F401
    FsspecStore,
    LocalStore,
    Store,
)
