"""JAX version-compatibility shims.

The codebase targets the modern `jax.shard_map` API (top-level export,
``check_vma=``, ``axis_names=``). Older installs (<= 0.4.x) only ship
`jax.experimental.shard_map.shard_map` with ``check_rep=`` and express
partially-manual meshes through ``auto=`` (the complement of
``axis_names``). Every shard_map call in the library and tests routes
through this wrapper so one process can run against either API.
"""

from __future__ import annotations

from typing import Optional


def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
              check_vma: bool = True, axis_names: Optional[frozenset] = None,
              legacy_submesh: bool = False):
    """`jax.shard_map` with the modern signature on any supported jax.

    ``check_vma`` maps to the legacy ``check_rep``; ``axis_names`` (the
    axes the body is manual over) maps to legacy ``auto`` (the axes it
    is NOT manual over). ``legacy_submesh`` opts a call site into the
    legacy sub-mesh fallback below — only valid when the ENCLOSING jit
    never shards anything over the non-manual axes (a shard_map bound to
    a sub-mesh conflicts with full-mesh-sharded jit arguments).
    """
    import jax

    if hasattr(jax, "shard_map"):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, **kw)

    from jax.experimental.shard_map import shard_map as _shard_map

    # axis_names is deliberately NOT mapped to legacy ``auto``: the 0.4.x
    # partial-manual lowering emits a PartitionId instruction the SPMD
    # partitioner rejects whenever the body uses axis_index (pipeline
    # schedules, ring attention). And running fully manual over the FULL
    # mesh is not safe either: 0.4.x jit miscompiles a fully-manual
    # region whose mesh carries axes the specs never name (the pipeline
    # on a (pp, dp) mesh computes wrong logits under jit; exact on a
    # pp-only mesh and exact un-jitted). So when the call site opted in
    # (legacy_submesh) and the in/out specs reference only the declared
    # manual axes, run fully manual on the SUB-MESH of exactly those
    # axes (coordinate 0 on the rest) — the idle axes carried replicated
    # data anyway, so dropping their replicas is numerically identical.
    if legacy_submesh and axis_names is not None and mesh is not None:
        if _spec_axes(in_specs) | _spec_axes(out_specs) <= set(axis_names):
            mesh = _submesh(mesh, axis_names)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def _submesh(mesh, axis_names):
    """`mesh` restricted to exactly `axis_names` (coordinate 0 on every
    other axis); `mesh` itself when nothing is dropped."""
    unused = [a for a in mesh.axis_names if a not in axis_names]
    if not unused:
        return mesh
    from jax.sharding import Mesh

    take = tuple(
        slice(None) if a in axis_names else 0
        for a in mesh.axis_names
    )
    return Mesh(
        mesh.devices[take],
        tuple(a for a in mesh.axis_names if a in axis_names),
    )


def placement_mesh(mesh, axis_names=frozenset({"pp"})):
    """The mesh jit arguments feeding a ``legacy_submesh`` shard_map
    should be committed to (``jax.device_put``). Modern jax: ``mesh``
    itself. Legacy jax: the sub-mesh of exactly ``axis_names`` — the
    fallback runs the shard_map there, and jit rejects arguments
    committed to a different device set than an inner shard_map's.
    Callers must drop the absent axes from their PartitionSpecs (e.g.
    ``P("dp") if "dp" in pmesh.axis_names else P()``)."""
    import jax

    if hasattr(jax, "shard_map"):
        return mesh
    return _submesh(mesh, axis_names)


def _spec_axes(specs) -> set:
    """Mesh axis names referenced anywhere in a specs pytree."""
    import jax
    from jax.sharding import PartitionSpec

    names: set = set()
    for s in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec)):
        if not isinstance(s, PartitionSpec):
            continue
        for part in s:
            if part is None:
                continue
            if isinstance(part, (tuple, list)):
                names.update(part)
            else:
                names.add(part)
    return names
