"""Opt-in local-SGD outer loop: pod-local steps, periodic DCN averaging.

``HOROVOD_MULTIPOD_SYNC`` selects the cross-pod sync discipline:

* ``sync`` (default) — every step's gradient reduction spans the whole
  world, exactly today's SPMD path. Single-pod jobs and
  ``localK`` with K<=1 resolve to this **by construction**: the plain
  code path runs, so parity with it is bitwise, not approximate (the
  K=1 guarantee scripts/multipod_check.py asserts).
* ``localK`` (e.g. ``local8``) — each pod runs K steps with gradient
  reductions confined to its own ICI domain (the inner groups of the
  pod topology), and every K-th step the PARAMETERS are averaged
  cross-pod over the DCN outer groups, optionally on the compressed
  wire (the int8 quantize→gather→dequant-accumulate leg
  ops/hierarchical.py already runs for hierarchical allreduce), with
  an outer momentum in the SlowMo/Lookahead family applied to the
  averaged step.

Numerics: for plain SGD the K=1 *mathematical* equivalence is exact
(mean-of-pod-means = global mean); for stateful optimizers and K>1 the
pods genuinely diverge between syncs — that is the latency tolerance
being bought. The convergence envelope versus the sync baseline is
measured, not assumed: ``scripts/multipod_check.py`` trains both on
the simulated 4-pod world and gates the final-loss ratio
(docs/multipod.md documents the envelope and its caveats).

Outer update (per leaf, at each sync):

    delta  = cross_pod_mean(params - anchor)
    v      = outer_momentum * v + delta
    params = anchor + outer_lr * v;  anchor = params

With ``outer_momentum=0`` and ``outer_lr=1`` this is plain parameter
averaging (anchors are identical across pods after every sync, so
``mean(p - a) = mean(p) - a``); the momentum term recovers part of the
information K local steps accumulate in divergent directions (SlowMo,
PAPERS.md lineage). What crosses DCN is the pod's **K-step delta from
the anchor**, not the raw parameters — the payload the int8 wire
quantizes accurately (deltas are small and zero-centered; quantizing
raw weights would put the block-scale noise on the full parameter
magnitude).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional, Tuple

from ..core.exceptions import HorovodInternalError
from .topology import PodTopology

_SYNC_RE = re.compile(r"^local\s*(\d+)$")


def parse_sync_mode(spec: str) -> Tuple[str, int]:
    """``HOROVOD_MULTIPOD_SYNC`` → ("sync", 1) or ("local", K).

    ``localK`` with K<=1 normalizes to ("sync", 1): one local step
    between syncs IS the synchronous discipline, and routing it through
    the plain path is what makes the K=1 parity guarantee bitwise."""
    s = (spec or "sync").strip().lower()
    if s in ("", "sync"):
        return "sync", 1
    m = _SYNC_RE.match(s)
    if not m:
        raise HorovodInternalError(
            f"HOROVOD_MULTIPOD_SYNC={spec!r}: expected 'sync' or "
            f"'localK' (e.g. local8)")
    k = int(m.group(1))
    if k <= 1:
        return "sync", 1
    return "local", k


def local_sgd_active(topology: Optional[PodTopology],
                     sync_spec: str) -> bool:
    """Whether the localK outer loop actually engages: needs >1 pod
    AND a localK spec with K>1. Everything else takes the plain
    path."""
    if topology is None or not topology.multi_pod:
        return False
    mode, _k = parse_sync_mode(sync_spec)
    return mode == "local"


@dataclasses.dataclass
class OuterState:
    """Per-leaf outer-loop state, a pytree of the params' structure:
    ``anchor`` is the last synchronized point, ``velocity`` the outer
    momentum buffer, ``residual`` the int8 error-feedback carry (f32,
    per leaf; None on uncompressed/non-EF wires). Registered as a JAX
    pytree so it carries through jit/lax.cond like optimizer state."""

    anchor: Any
    velocity: Any
    residual: Any = None


def _register_outer_state() -> None:
    import jax

    jax.tree_util.register_pytree_node(
        OuterState,
        lambda s: ((s.anchor, s.velocity, s.residual), None),
        lambda _aux, children: OuterState(*children),
    )


_register_outer_state()


class LocalSGD:
    """The outer loop over one :class:`PodTopology`.

    All array methods are traceable: they run inside the existing
    jitted shard_map step over the flat ``axis`` (default ``"hvd"``),
    addressing pods via axis_index_groups — no second mesh, no new
    lowering path. ``wire`` is an optional
    :class:`~horovod_tpu.optim.compression.WireSpec`; the DCN leg then
    moves the compressed payload exactly as the hierarchical outer leg
    does."""

    def __init__(self, topology: PodTopology, k: int,
                 outer_lr: float = 1.0, outer_momentum: float = 0.0,
                 wire=None, axis: str = "hvd"):
        if k < 2:
            raise HorovodInternalError(
                "LocalSGD requires K >= 2; K<=1 must take the plain "
                "synchronous path (parse_sync_mode normalizes this)")
        if not topology.multi_pod:
            raise HorovodInternalError(
                "LocalSGD over a single pod is the plain path; do not "
                "construct the outer loop")
        self.topology = topology
        self.k = int(k)
        self.outer_lr = float(outer_lr)
        self.outer_momentum = float(outer_momentum)
        self.wire = wire
        self.axis = axis
        self._inner = topology.inner_groups()
        self._outer = topology.outer_groups()

    # -- inner (pod-local) leg ---------------------------------------------

    def inner_mean(self, x):
        """Pod-local mean of ``x`` — the gradient reduction of a local
        step, confined to the ICI domain."""
        from jax import lax

        return lax.psum(
            x, self.axis, axis_index_groups=self._inner,
        ) / self.topology.pod_size

    def inner_mean_tree(self, tree):
        import jax

        return jax.tree_util.tree_map(self.inner_mean, tree)

    # -- outer (cross-pod, DCN) leg ----------------------------------------

    def cross_pod_mean(self, x, residual=None):
        """Mean of ``x`` across pods at equal pod-local offset, over
        the (optionally compressed) DCN leg. With ``residual`` (int8
        error feedback, f32 of x's shape) the quantization error of
        THIS sync is folded into the payload of the NEXT one, and the
        call returns ``(mean, new_residual)``."""
        from jax import lax

        n = self.topology.n_pods
        if self.wire is None:
            return lax.psum(
                x, self.axis, axis_index_groups=self._outer) / n
        from ..ops.hierarchical import _outer_wire_sum

        if residual is None:
            return _outer_wire_sum(
                x, self.axis, self._outer, n, self.wire, None) / n
        y, new_res = _outer_wire_sum(
            x, self.axis, self._outer, n, self.wire, residual)
        return y / n, new_res

    def _plain_cross_pod_mean(self, x):
        """Uncompressed cross-pod mean — for payloads the int8 wire
        would bias (optimizer second moments are strictly positive,
        not zero-centered; block scales there inject a systematic
        error the delta payload does not see)."""
        from jax import lax

        return lax.psum(
            x, self.axis, axis_index_groups=self._outer,
        ) / self.topology.n_pods

    @property
    def carries_residual(self) -> bool:
        """Whether outer syncs thread int8 error feedback: requires an
        int8 wire with ``error_feedback`` set. Before PR 17 the
        per-sync residual was computed and dropped; now it rides in
        :class:`OuterState` so quantization error cancels across
        syncs instead of compounding."""
        return (self.wire is not None
                and getattr(self.wire, "kind", None) == "int8"
                and bool(getattr(self.wire, "error_feedback", False)))

    def should_sync(self, step: int) -> bool:
        """Host-side cadence check: sync after steps K-1, 2K-1, ...
        (i.e. every K-th completed local step)."""
        return (int(step) + 1) % self.k == 0

    def init_outer(self, params) -> OuterState:
        import jax
        import jax.numpy as jnp

        residual = None
        if self.carries_residual:
            residual = jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
        return OuterState(
            anchor=jax.tree_util.tree_map(jnp.asarray, params),
            velocity=jax.tree_util.tree_map(jnp.zeros_like, params),
            residual=residual,
        )

    def outer_sync(self, params, state: OuterState,
                   ) -> Tuple[Any, OuterState]:
        """One cross-pod synchronization (traceable): average the
        K-step anchor deltas over DCN (the well-conditioned payload
        for the quantized wire — module docstring), apply outer
        momentum, re-anchor. With an error-feedback wire the carried
        residual joins the delta payload and the fresh quantization
        error replaces it in the returned state. Plain per-leaf maps
        — no tuple-valued leaves in any single map, so
        tuple/namedtuple-structured params pytrees are safe (the
        residual pass works on flattened leaf lists for the same
        reason)."""
        import jax

        tree_map = jax.tree_util.tree_map
        new_res = state.residual
        if state.residual is not None:
            leaves_p, treedef = jax.tree_util.tree_flatten(params)
            leaves_a = treedef.flatten_up_to(state.anchor)
            leaves_r = treedef.flatten_up_to(state.residual)
            pairs = [
                self.cross_pod_mean(p - a, r)
                for p, a, r in zip(leaves_p, leaves_a, leaves_r)
            ]
            mean_delta = treedef.unflatten([y for y, _ in pairs])
            new_res = treedef.unflatten([r for _, r in pairs])
        else:
            mean_delta = tree_map(
                lambda p, a: self.cross_pod_mean(p - a),
                params, state.anchor)
        new_vel = tree_map(
            lambda v, d: self.outer_momentum * v + d,
            state.velocity, mean_delta)
        new_params = tree_map(
            lambda a, v: a + self.outer_lr * v,
            state.anchor, new_vel)
        return new_params, OuterState(anchor=new_params,
                                      velocity=new_vel,
                                      residual=new_res)

    def merge_optimizer_state(self, opt_state):
        """Cross-pod MERGE of pod-local optimizer moments at a sync
        point — the alternative to resetting them (which discards the
        curvature estimate K steps built) or leaving them divergent
        (which fights the freshly-averaged params).

        Any state node exposing ``mu``/``nu`` (optax's ScaleByAdamState
        shape, duck-typed) gets both moments replaced by their
        uncompressed cross-pod means: averaged ``nu`` is each pod's
        second-moment estimate of the SAME post-sync iterate, and
        averaged ``mu`` is consistent with the averaged anchor delta
        the params just took. ``count`` (and every other field/leaf)
        is untouched — pods step in lockstep so counts already agree.
        The int8 wire is deliberately NOT used here (see
        ``_plain_cross_pod_mean``). K=1 never constructs LocalSGD, so
        the synchronous path cannot reach this."""
        import jax

        def _is_moments(node) -> bool:
            return (hasattr(node, "mu") and hasattr(node, "nu")
                    and hasattr(node, "_replace"))

        def _merge(node):
            if not _is_moments(node):
                return node
            mean_tree = lambda t: jax.tree_util.tree_map(
                self._plain_cross_pod_mean, t)
            return node._replace(mu=mean_tree(node.mu),
                                 nu=mean_tree(node.nu))

        return jax.tree_util.tree_map(
            _merge, opt_state, is_leaf=_is_moments)

    def maybe_outer_sync(self, params, state: OuterState, step,
                         ) -> Tuple[Any, OuterState]:
        """Traced-cadence form for fully-jitted loops: ``step`` may be
        a traced scalar; a lax.cond selects sync vs pass-through."""
        import jax
        from jax import lax

        do = (step + 1) % self.k == 0

        def _sync(operand):
            p, s = operand
            return self.outer_sync(p, s)

        def _skip(operand):
            return operand

        return lax.cond(do, _sync, _skip, (params, state))


def from_knobs(topology: Optional[PodTopology] = None,
               knobs=None, wire=None, axis: str = "hvd",
               ) -> Optional[LocalSGD]:
    """Build the outer loop from the knob snapshot, or None when the
    plain synchronous path applies (single pod, sync mode, or K<=1) —
    callers branch on None exactly once, at step-build time."""
    from .topology import pod_topology

    if knobs is None:
        from ..core.state import global_state

        knobs = global_state().knobs
    topo = topology if topology is not None else pod_topology()
    spec = str(getattr(knobs, "multipod_sync", "sync") or "sync")
    if not local_sgd_active(topo, spec):
        return None
    _mode, k = parse_sync_mode(spec)
    return LocalSGD(
        topo, k,
        outer_lr=float(getattr(knobs, "multipod_outer_lr", 1.0)),
        outer_momentum=float(
            getattr(knobs, "multipod_outer_momentum", 0.0)),
        wire=wire, axis=axis,
    )
