"""Pod topology: the pod-aware view of the world every layer shares.

A **pod** is one ICI domain — the rank block whose collectives stay on
the fast torus — and the unit the federation scales by: per-pod relay
servers (relay.py), per-pod local-SGD groups (localsgd.py), per-pod
metric rollups (scripts/metrics_summary.py). This module derives one
:class:`PodTopology` from, in priority order,

1. explicit knobs/env (``HOROVOD_MULTIPOD_PODS`` +
   ``HOROVOD_MULTIPOD_POD_ID``; the launcher exports both per host),
2. a factored mesh (an outer ``dcn`` axis names the pod level,
   parallel/mesh.py),
3. the flat world + ``HOROVOD_MULTIPOD_PODS`` (contiguous rank blocks,
   the launcher's rank model: local ranks contiguous, hosts/pods the
   outer level — the same block convention ops/hierarchical.py uses,
   so the localsgd outer groups and the hierarchical outer leg always
   agree on who is cross-pod).

Rank blocks are contiguous: pod ``p`` of ``n_pods`` over ``world``
ranks owns ``[p*world/n_pods, (p+1)*world/n_pods)``. ``world %
n_pods != 0`` is a configuration error (a lopsided pod would make the
outer averaging groups ragged — XLA replica groups must be uniform).

Integration with core/process_sets.py: :meth:`PodTopology.process_set`
registers (or reuses) the pod's member ranks as a ProcessSet, so
pod-scoped collectives ride the existing set machinery — SPMD
axis_index_groups and the eager sub-mesh form both come for free.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Sequence, Tuple

from ..core.exceptions import HorovodInternalError


def _env_first(*names: str) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return None


@dataclasses.dataclass(frozen=True)
class PodTopology:
    """The federation's shape: which pod this process is in, who else
    is, and how far away the other pods are.

    ``dcn_hops`` is the worst-case DCN hop count between any two pods
    (1 = every pod pair is one switch hop apart — the flat-fabric
    default; the scaling projection's DCN tier consumes it as a latency
    multiplier)."""

    n_pods: int
    pod_id: int
    world: int
    dcn_hops: int = 1

    def __post_init__(self):
        if self.n_pods < 1:
            raise HorovodInternalError(
                f"n_pods must be >= 1, got {self.n_pods}")
        if self.world % self.n_pods:
            raise HorovodInternalError(
                f"world size {self.world} is not divisible by "
                f"{self.n_pods} pods (pods are uniform rank blocks)")
        if not 0 <= self.pod_id < self.n_pods:
            raise HorovodInternalError(
                f"pod_id {self.pod_id} out of range for "
                f"{self.n_pods} pods")

    # -- shape queries ------------------------------------------------------

    @property
    def pod_size(self) -> int:
        return self.world // self.n_pods

    @property
    def multi_pod(self) -> bool:
        return self.n_pods > 1

    def members(self, pod_id: Optional[int] = None) -> List[int]:
        """Global ranks of ``pod_id`` (default: this pod)."""
        p = self.pod_id if pod_id is None else int(pod_id)
        k = self.pod_size
        return list(range(p * k, (p + 1) * k))

    def pod_of_rank(self, rank: int) -> int:
        if not 0 <= rank < self.world:
            raise HorovodInternalError(
                f"rank {rank} out of range for world {self.world}")
        return rank // self.pod_size

    def pod_label(self, pod_id: Optional[int] = None) -> str:
        """The string label telemetry carries (``pod="<label>"`` on the
        aggregated exposition, the ``pod`` field of step records)."""
        return f"pod{self.pod_id if pod_id is None else int(pod_id)}"

    # -- collective group forms --------------------------------------------

    def inner_groups(self) -> List[List[int]]:
        """axis_index_groups for pod-LOCAL collectives: one group per
        pod (the contiguous blocks — ops/hierarchical._block_groups'
        inner form)."""
        k = self.pod_size
        return [list(range(p * k, (p + 1) * k))
                for p in range(self.n_pods)]

    def outer_groups(self) -> List[List[int]]:
        """axis_index_groups for CROSS-pod collectives: the strided
        groups joining equal pod-local offsets across pods — the DCN
        leg's communicators."""
        k = self.pod_size
        return [[off + p * k for p in range(self.n_pods)]
                for off in range(k)]

    # -- process-set integration -------------------------------------------

    def process_set(self):
        """This pod's member ranks as a registered ProcessSet (created
        on first use, reused afterwards) — pod-scoped collectives get
        the SPMD axis_index_groups and eager sub-mesh forms through the
        existing set machinery. Requires an initialized runtime."""
        from ..core import process_sets

        return process_sets.add_or_get_process_set(self.members())

    def __str__(self) -> str:
        return (f"PodTopology({self.n_pods} pods x {self.pod_size} "
                f"ranks, this={self.pod_label()}, "
                f"dcn_hops={self.dcn_hops})")


# ---------------------------------------------------------------------------
# derivation
# ---------------------------------------------------------------------------

def pod_topology_from_env(world: Optional[int] = None,
                          rank: Optional[int] = None,
                          ) -> Optional[PodTopology]:
    """Build the topology from launcher env alone (no jax, no init):
    ``HOROVOD_MULTIPOD_PODS`` (``HVD_TPU_`` prefix wins, as for every
    knob) names the pod count; ``HOROVOD_MULTIPOD_POD_ID`` pins this
    host's pod, defaulting to ``rank // pod_size`` when a rank env is
    visible. Returns None when no multipod env is set — the single-pod
    world stays knob-free."""
    raw = _env_first("HVD_TPU_MULTIPOD_PODS", "HOROVOD_MULTIPOD_PODS")
    if not raw:
        return None
    try:
        n_pods = int(raw)
    except ValueError:
        return None
    if n_pods <= 0:
        return None
    if world is None:
        w = _env_first("HVD_TPU_SIZE", "HOROVOD_SIZE")
        world = int(w) if w else n_pods
    if rank is None:
        r = _env_first("HVD_TPU_RANK", "HOROVOD_RANK")
        rank = int(r) if r else 0
    pod_raw = _env_first(
        "HVD_TPU_MULTIPOD_POD_ID", "HOROVOD_MULTIPOD_POD_ID")
    if pod_raw is not None:
        pod_id = int(pod_raw)
    else:
        pod_id = rank // max(world // n_pods, 1)
    hops_raw = _env_first(
        "HVD_TPU_MULTIPOD_DCN_HOPS", "HOROVOD_MULTIPOD_DCN_HOPS")
    dcn_hops = int(hops_raw) if hops_raw else 1
    return PodTopology(n_pods=n_pods, pod_id=pod_id, world=world,
                       dcn_hops=dcn_hops)


def pod_topology() -> Optional[PodTopology]:
    """The initialized runtime's topology: knobs first, then a factored
    mesh's ``dcn`` axis, else None (single pod, no federation).

    Mesh derivation: a mesh carrying a ``dcn`` axis IS a multipod
    declaration — the axis extent is the pod count and the pod id is
    this process's coordinate along it (single-controller SPMD sees
    every pod, so the coordinate defaults to 0 unless the env pins
    it)."""
    from ..core.state import global_state

    st = global_state()
    if not st.initialized:
        return pod_topology_from_env()
    world = 1
    if st.mesh is not None:
        import numpy as np

        world = int(np.asarray(st.mesh.devices).size)
    n_pods = int(getattr(st.knobs, "multipod_pods", 0) or 0)
    if n_pods > 1:
        from ..core import basics

        try:
            rank = basics.rank()
        except Exception:
            rank = 0
        env = pod_topology_from_env(world=world, rank=rank)
        if env is not None and env.n_pods == n_pods:
            return env
        return PodTopology(
            n_pods=n_pods,
            pod_id=rank // max(world // n_pods, 1),
            world=world,
            dcn_hops=int(getattr(st.knobs, "multipod_dcn_hops", 1) or 1),
        )
    if st.mesh is not None and "dcn" in getattr(st.mesh, "axis_names", ()):
        sizes = dict(zip(st.mesh.axis_names, st.mesh.devices.shape))
        n = int(sizes["dcn"])
        if n > 1:
            env = pod_topology_from_env(world=world)
            pod_id = env.pod_id if env is not None and env.n_pods == n \
                else 0
            return PodTopology(n_pods=n, pod_id=pod_id, world=world)
    return pod_topology_from_env(world=world)


def pod_block_groups(world: int, n_pods: int,
                     ) -> Tuple[List[List[int]], List[List[int]]]:
    """(inner, outer) axis_index_groups for ``n_pods`` contiguous rank
    blocks — the standalone form check scripts use without a live
    topology. Inner = pod-local, outer = cross-pod strided."""
    topo = PodTopology(n_pods=n_pods, pod_id=0, world=world)
    return topo.inner_groups(), topo.outer_groups()
