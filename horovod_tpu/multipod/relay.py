"""Per-pod control-plane relay: O(pods) root fan-in instead of O(hosts).

The root rendezvous/KV server is hammered by four push families —
metric expositions, flight dumps, replication manifests/store
registrations, and serving registrations — each arriving as its own
HTTP PUT from every host. At hundreds of hosts the root's accept queue
and handler threads become the cluster's single point of contention
(ROADMAP item 5; the MPI characterization work, PAPERS.md 1810.11112,
finds control-plane fan-in breaks before wire bandwidth).

A :class:`PodRelayServer` is a KVStoreServer (the exact scope/key HTTP
surface workers already speak — no client changes beyond pointing
``HVD_TPU_RELAY_ADDR``/``PORT`` at the relay) that

* accepts its pod's pushes locally (a worker's PUT returns as soon as
  the relay stored it — pod-local RTT, not cross-DCN),
* **coalesces** them by (scope, key) — KV semantics are last-write-
  wins, so a metrics exposition superseded before the flush never
  crosses DCN at all,
* forwards one batched ``PUT /relay_batch/<pod_id>`` to the root per
  flush interval under the shared control-plane RetryPolicy
  (full-jitter backoff — utils/retry.py — so relays recovering from a
  root failover don't stampede it), and
* rewrites ``metrics_push`` keys from ``<rank>`` to
  ``<rank>@<pod_label>`` so the root's aggregated ``/metrics`` can
  label every series with its pod (utils/metrics.exposition).

Root-state handoff rides the PR 7 failover path unchanged: the root is
a KVStoreServer with ``state_path``, so a restarted root rebinds the
same port and the relays' forward retry ladder reconnects without any
relay-side state loss (pending entries are re-merged on failure, never
dropped). ``scripts/multipod_check.py`` gates all of this.

GETs are NOT proxied: reads (recovery-ladder fetches, poll-waits) go
to the root directly — they are rare, pull-shaped, and need the
cluster-global view only the root has. The relay exists for the hot
push fan-in.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..health.fleet import HEALTH_SCOPE as _HEALTH_SCOPE
from ..runner.http.http_server import RELAY_BATCH_PATH, KVStoreServer
from ..utils import faults as _faults
from ..utils import retry as _retry
from ..utils.metrics import METRICS_PUSH_SCOPE

LOG = logging.getLogger("horovod_tpu.multipod")

_TIMEOUT_S = 5.0

#: env pair a pod's workers read to find their relay (the launcher
#: exports them per host; scripts/tests set them directly). When unset,
#: every push path falls back to the root rendezvous address — the
#: single-pod world is exactly the pre-federation one.
RELAY_ADDR_ENVS = ("HVD_TPU_RELAY_ADDR", "HOROVOD_RELAY_ADDR")
RELAY_PORT_ENVS = ("HVD_TPU_RELAY_PORT", "HOROVOD_RELAY_PORT")


def relay_endpoint_from_env() -> Optional[Tuple[str, int]]:
    """This pod's relay (addr, port), or None when no relay is
    configured."""
    addr = next((os.environ[n] for n in RELAY_ADDR_ENVS
                 if os.environ.get(n)), None)
    port = next((os.environ[n] for n in RELAY_PORT_ENVS
                 if os.environ.get(n)), None)
    if not addr or not port:
        return None
    try:
        return addr, int(port)
    except ValueError:
        return None


def push_endpoint(root: Optional[Tuple[str, int]] = None,
                  ) -> Optional[Tuple[str, int]]:
    """Where control-plane PUSHES go: the pod relay when one is
    configured, else ``root`` (or the env-published rendezvous
    address). The one routing decision utils/metrics.py,
    elastic/replication.py, utils/flight.py and serving/replica_set.py
    all share."""
    relay = relay_endpoint_from_env()
    if relay is not None:
        return relay
    if root is not None:
        return root
    addr = (os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
            or os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR"))
    port = (os.environ.get("HVD_TPU_RENDEZVOUS_PORT")
            or os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT"))
    if not addr or not port:
        return None
    try:
        return addr, int(port)
    except ValueError:
        return None


class PodRelayServer(KVStoreServer):
    """One pod's control-plane aggregation point.

    Parameters: ``pod_label`` names the pod on forwarded telemetry
    (PodTopology.pod_label()); ``root`` is the rendezvous server's
    (addr, port); ``flush_interval_s`` is the fixed forward cadence —
    at most one upward PUT per interval, and at most one interval of
    staleness per relayed record; ``forward_scopes``
    restricts forwarding to the named scopes (None = forward every
    scope — flight dumps, manifests, registrations and all)."""

    def __init__(self, pod_label: str,
                 root: Union[Tuple[str, int],
                             Sequence[Tuple[str, int]]],
                 port: int = 0, flush_interval_s: float = 1.0,
                 forward_scopes: Optional[List[str]] = None,
                 state_path: Optional[str] = None,
                 policy: Optional[_retry.RetryPolicy] = None):
        super().__init__(port=port, state_path=state_path)
        self.pod_label = pod_label
        # ``root`` accepts one (addr, port) — today's single root,
        # unchanged — or the full sharded root set in replica-id order
        # (docs/control_plane.md). With >1 root the relay fetches the
        # shard map and splits each flush by owner; roots[0] stays the
        # fallback target while no map is available.
        if root and isinstance(root[0], (tuple, list)):
            self.roots = [(str(a), int(p)) for a, p in root]
        else:
            self.roots = [(str(root[0]), int(root[1]))]
        self.root = self.roots[0]
        self._shard_client = None
        if len(self.roots) > 1:
            from ..runner.http.http_client import ShardClient
            self._shard_client = ShardClient(self.roots)
        self.reroutes = 0
        self.flush_interval_s = float(flush_interval_s)
        self.forward_scopes = (
            set(forward_scopes) if forward_scopes is not None else None)
        self._policy = policy or _retry.RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            jitter="full")
        self._outage = _retry.Outage(
            LOG, f"relay {pod_label} forward to the root server")
        self._pending: Dict[Tuple[str, str], bytes] = {}
        self._pending_lock = threading.Lock()
        self._stop = threading.Event()
        self._forwarder: Optional[threading.Thread] = None
        self.forwarded_batches = 0
        self.forwarded_entries = 0
        self.set_mutation_hook(self._observe)

    # -- ingest -------------------------------------------------------------

    def _observe(self, scope: str, key: str, value: bytes) -> None:
        if self.forward_scopes is not None \
                and scope not in self.forward_scopes:
            return
        if scope in (METRICS_PUSH_SCOPE, _HEALTH_SCOPE) \
                and "@" not in key:
            # pod-label the rank key so the root's aggregated /metrics
            # emits rank="<r>",pod="<label>" series — and the root's
            # /health verdict names ranks per pod (docs/multipod.md)
            key = f"{key}@{self.pod_label}"
        with self._pending_lock:
            self._pending[(scope, key)] = value

    # -- forward ------------------------------------------------------------

    def _take_pending(self) -> List[Tuple[str, str, bytes]]:
        with self._pending_lock:
            batch = [(s, k, v) for (s, k), v in self._pending.items()]
            self._pending.clear()
        return batch

    def _restore_pending(self,
                         batch: List[Tuple[str, str, bytes]]) -> None:
        """A failed forward re-merges its entries — newer pod-local
        writes of the same (scope, key) win, so nothing is lost and
        nothing stale overwrites fresh."""
        with self._pending_lock:
            for scope, key, value in batch:
                self._pending.setdefault((scope, key), value)

    def _owner_targets(
            self, batch: List[Tuple[str, str, bytes]],
    ) -> Dict[Tuple[str, int], List[Tuple[str, str, bytes]]]:
        """Group a flush by the root that owns each entry. One group at
        ``roots[0]`` when unsharded or while no shard map is reachable
        (the single-root path, bit-for-bit)."""
        if self._shard_client is None:
            return {self.root: list(batch)}
        try:
            m = self._shard_client.shard_map()
        except Exception:
            m = None
        if m is None or m is False:
            return {self.root: list(batch)}
        groups: Dict[Tuple[str, int],
                     List[Tuple[str, str, bytes]]] = {}
        for s, k, v in batch:
            target = m.addr_of(m.owner_of(s, k))
            groups.setdefault(target, []).append((s, k, v))
        return groups

    def flush_once(self) -> int:
        """Forward everything pending, batched per shard owner (ONE
        PUT total in the single-root world). Returns the entry count
        forwarded. Raises nothing: failed groups re-merge and count on
        the outage tracker; entries a replica rejects as misrouted
        (stale map during a takeover) re-merge too and the next flush
        lands them on the new owner."""
        batch = self._take_pending()
        if not batch:
            return 0
        groups = self._owner_targets(batch)
        sent = 0
        rejected_any = False
        failed: Optional[Exception] = None
        for (addr, port), ents in groups.items():
            # JSON + base64, matching http_server.decode_relay_batch
            # (the root refuses to unpickle network input)
            body = json.dumps([
                {"scope": s, "key": k,
                 "value_b64": base64.b64encode(v).decode()}
                for s, k, v in ents
            ]).encode()

            def _do() -> bytes:
                req = urllib.request.Request(
                    f"http://{addr}:{port}/{RELAY_BATCH_PATH}/"
                    f"{self.pod_label}",
                    data=body, method="PUT",
                )
                with urllib.request.urlopen(
                        req, timeout=_TIMEOUT_S) as resp:
                    return resp.read()

            try:
                raw = self._policy.call(_do, point="relay.forward")
            except Exception as e:
                self._restore_pending(ents)
                failed = e
                continue
            sent += len(ents)
            # a sharded replica answers JSON with per-entry rejects
            # (owner moved under us); an unsharded root answers b"ok"
            try:
                resp_obj = json.loads(raw)
            except Exception:
                resp_obj = None
            if isinstance(resp_obj, dict) and resp_obj.get("rejected"):
                rej = resp_obj["rejected"]
                by_key = {(s, k): v for s, k, v in ents}
                requeue = [
                    (r["scope"], r["key"],
                     by_key[(r["scope"], r["key"])])
                    for r in rej
                    if (r["scope"], r["key"]) in by_key
                ]
                self._restore_pending(requeue)
                self.reroutes += len(requeue)
                sent -= len(requeue)
                rejected_any = True
        if rejected_any and self._shard_client is not None:
            try:
                self._shard_client.refresh_map()
            except Exception:
                pass
        if failed is not None:
            self._outage.failure(failed)
            if self._shard_client is not None:
                # a dead owner also means the map likely moved
                try:
                    self._shard_client.refresh_map()
                except Exception:
                    pass
        else:
            self._outage.success()
        if sent:
            self.forwarded_batches += 1
            self.forwarded_entries += sent
        return sent

    def _forward_loop(self) -> None:
        # fixed cadence: ONE upward PUT per interval regardless of the
        # pod's arrival pattern (a per-record wake would let steady
        # traffic degrade the relay into a per-arrival forwarder and
        # erode the O(pods) fan-in contract). Worst-case record
        # staleness = one interval; an empty interval costs nothing
        # (flush_once returns before any network on empty pending).
        while not self._stop.wait(self.flush_interval_s):
            # launcher-supervised kill point: a ``relay.proc:kill``
            # fault spec (utils/faults.py) takes the whole relay
            # process down here — the deterministic crash the
            # supervisor's backoff-restart is tested against
            # (scripts/multipod_check.py)
            try:
                _faults.inject("relay.proc", pod=self.pod_label)
            except _faults.InjectedFault:
                LOG.warning("relay %s: injected fault in forwarder",
                            self.pod_label)
            self.flush_once()
        self.flush_once()  # final drain: clean shutdowns lose nothing

    # -- lifecycle ----------------------------------------------------------

    def start_server(self) -> int:
        port = super().start_server()
        if self._forwarder is None:
            self._stop.clear()
            self._forwarder = threading.Thread(
                target=self._forward_loop, daemon=True,
                name=f"relay-{self.pod_label}")
            self._forwarder.start()
        return port

    def shutdown_server(self) -> None:
        self._stop.set()
        if self._forwarder is not None:
            self._forwarder.join(timeout=10)
            self._forwarder = None
        super().shutdown_server()

    def stats(self) -> Dict[str, int]:
        with self._pending_lock:
            pending = len(self._pending)
        return {
            "forwarded_batches": self.forwarded_batches,
            "forwarded_entries": self.forwarded_entries,
            "pending": pending,
            "received_requests": self.request_count,
            "reroutes": self.reroutes,
        }


def relay_main(argv: Optional[List[str]] = None) -> int:
    """Process entry point for one launcher-supervised pod relay
    (``python -m horovod_tpu.multipod.relay``). runner/launch.py spawns
    one per pod, exports its address to the pod's workers, and restarts
    it under backoff on crash; after a restart the relay re-fetches the
    shard map, so its next batched PUT lands on the post-takeover
    owners. Fault specs arm from the environment (utils/faults.py), so
    ``relay.proc:kill`` rounds kill the real process from inside its
    own forward loop."""
    import argparse

    from ..runner.http.ring import parse_root_addrs

    p = argparse.ArgumentParser(prog="pod-relay")
    p.add_argument("--pod-label", required=True)
    p.add_argument("--roots", required=True,
                   help="comma-separated addr:port (the root set; one "
                        "entry = plain single root)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--flush-interval", type=float, default=1.0)
    p.add_argument("--state-path", default=None)
    args = p.parse_args(argv)
    roots = parse_root_addrs(args.roots)
    srv = PodRelayServer(
        args.pod_label,
        roots if len(roots) > 1 else roots[0],
        port=args.port,
        flush_interval_s=args.flush_interval,
        state_path=args.state_path)
    port = srv.start_server()
    LOG.info("pod relay %s serving on port %d (roots: %s)",
             args.pod_label, port, args.roots)
    try:
        while True:
            import time as _time
            _time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.shutdown_server()
    return 0


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    raise SystemExit(relay_main())
