"""Shared direct-vs-relayed control-plane fan-in harness.

One implementation of the "simulated fleet pushing expositions"
measurement both `scripts/multipod_check.py` (the gate) and
`scripts/control_plane_scaling.py --pods` (the bench) consume —
threads simulate hosts on this box, pods are relay servers, the
scoreboard is the root KVStoreServer's request count.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from typing import Dict, List, Tuple

from ..runner.http.http_server import KVStoreServer, ShardReplica
from ..utils.metrics import METRICS_PUSH_SCOPE
from .relay import PodRelayServer


def put_with_retry(addr: str, port: int, path: str, body: bytes,
                   attempts: int = 5) -> None:
    """One PUT with a small retry ladder: a contended 1-core server
    resets some connections under a burst, and losing pushes would
    flatter the direct-mode request count."""
    req = urllib.request.Request(
        f"http://{addr}:{port}/{path}", data=body, method="PUT")
    for attempt in range(attempts):
        try:
            with urllib.request.urlopen(req, timeout=5.0):
                return
        except OSError:
            if attempt == attempts - 1:
                raise
            time.sleep(0.02 * (attempt + 1))


def _exposition_body(i: int) -> bytes:
    return (
        "# HELP hvd_steps_total steps\n"
        "# TYPE hvd_steps_total counter\n"
        f"hvd_steps_total {i + 1}\n"
    ).encode()


def _fleet_push(targets: List[Tuple[str, int]], n_pods: int,
                hosts_per_pod: int, pushes_per_host: int) -> float:
    """Every simulated host pushes its expositions at its pod's
    target; returns the fleet's push wall time."""
    def host(pod: int, h: int) -> None:
        rank = pod * hosts_per_pod + h
        addr, port = targets[pod]
        for i in range(pushes_per_host):
            put_with_retry(
                addr, port, f"{METRICS_PUSH_SCOPE}/{rank}",
                _exposition_body(i))

    threads = [
        threading.Thread(target=host, args=(p, h))
        for p in range(n_pods) for h in range(hosts_per_pod)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0


def measure_fanin(n_pods: int, hosts_per_pod: int,
                  pushes_per_host: int = 10,
                  flush_interval_s: float = 0.05,
                  settle_timeout_s: float = 20.0) -> Dict:
    """Run the fleet twice — direct to the root, then through per-pod
    relays — and return the raw scoreboard: root request counts, push
    wall times, per-pod relay stats, and the root's pushed
    metrics_push scope after the relayed run (for exposition checks).
    """
    # direct mode: every host hits the root
    root = KVStoreServer()
    rport = root.start_server()
    direct_s = _fleet_push([("127.0.0.1", rport)] * n_pods, n_pods,
                           hosts_per_pod, pushes_per_host)
    direct_requests = root.request_count
    root.shutdown_server()

    # relayed mode: hosts hit their pod relay, relays batch upward
    root = KVStoreServer()
    rport = root.start_server()
    relays = [
        PodRelayServer(f"pod{p}", ("127.0.0.1", rport),
                       flush_interval_s=flush_interval_s)
        for p in range(n_pods)
    ]
    targets = [("127.0.0.1", r.start_server()) for r in relays]
    relayed_s = _fleet_push(targets, n_pods, hosts_per_pod,
                            pushes_per_host)
    deadline = time.time() + settle_timeout_s
    want = n_pods * hosts_per_pod
    while time.time() < deadline:
        with root.lock:
            if len(root.store.get(METRICS_PUSH_SCOPE, {})) >= want:
                break
        time.sleep(0.05)
    relayed_requests = root.request_count
    with root.lock:
        pushed = dict(root.store.get(METRICS_PUSH_SCOPE, {}))
    per_pod = [dict(pod=f"pod{p}", **relays[p].stats())
               for p in range(n_pods)]
    for r in relays:
        r.shutdown_server()
    root.shutdown_server()

    return {
        "pods": n_pods,
        "hosts": n_pods * hosts_per_pod,
        "pushes_per_host": pushes_per_host,
        "direct": {
            "root_requests": direct_requests,
            "push_wall_s": round(direct_s, 3),
        },
        "relayed": {
            "root_requests": relayed_requests,
            "push_wall_s": round(relayed_s, 3),
            "per_pod_relays": per_pod,
        },
        "root_request_reduction_x": round(
            direct_requests / max(relayed_requests, 1), 2),
        "pod_fanin_factor": hosts_per_pod,
        "pushed": pushed,
    }


def _free_ports(n: int) -> List[int]:
    """n distinct free TCP ports, all reserved before any is used, so
    a replica tier's roots list can be fixed up front (replica id =
    index, the HOROVOD_ROOT_ADDRS contract)."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def measure_shard_balance(n_replicas: int, n_hosts: int,
                          pushes_per_host: int = 1) -> Dict:
    """Sharded-root load-spread measurement: an in-process tier of
    ``n_replicas`` ShardReplicas, ``n_hosts`` simulated hosts each
    pushing ``pushes_per_host`` expositions through a shard-routing
    client. The scoreboard is each replica's request count — with a
    healthy ring every replica serves ≈ total/N (consistent hashing's
    whole point; `scripts/control_plane_scaling.py --root-replicas`
    renders the rows)."""
    from ..runner.http.http_client import ShardClient

    roots = [("127.0.0.1", p) for p in _free_ports(n_replicas)]
    reps = [
        ShardReplica(i, roots, auto_heartbeat=False)
        for i in range(n_replicas)
    ]
    for r in reps:
        r.start_server()
    client = ShardClient(roots)
    client.shard_map()  # fetch once, outside the timed region
    errors: List[str] = []

    def host(rank: int) -> None:
        for i in range(pushes_per_host):
            try:
                client.put(METRICS_PUSH_SCOPE, str(rank),
                           _exposition_body(i))
            except Exception as e:  # surface, don't crash the thread
                errors.append(f"rank {rank}: {e}")
                return

    threads = [threading.Thread(target=host, args=(rank,))
               for rank in range(n_hosts)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    per_replica = [r.request_count for r in reps]
    seen = set()
    copies = 0
    for r in reps:
        with r.lock:
            keys = list(r.store.get(METRICS_PUSH_SCOPE, {}))
        seen.update(keys)
        copies += len(keys)
    for r in reps:
        r.shutdown_server()
    total = sum(per_replica)
    mean = total / max(n_replicas, 1)
    return {
        "root_replicas": n_replicas,
        "hosts": n_hosts,
        "pushes_per_host": pushes_per_host,
        "per_replica_requests": per_replica,
        "total_requests": total,
        "balance_max_over_mean": round(
            max(per_replica) / mean, 3) if mean else 0.0,
        "stored_keys": len(seen),
        # owner + ring-backup copies: ≈ 2× keys with N ≥ 2 replicas
        # (the write-through replication the takeover guarantee rides)
        "stored_copies": copies,
        "push_wall_s": round(wall_s, 3),
        "client_redirects": client.redirects,
        "errors": errors[:5],
        "n_errors": len(errors),
    }
