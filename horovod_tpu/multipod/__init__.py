"""Multi-pod federation: pod-sharded control plane + DCN outer loop.

Everything below this package assumed one pod-slice and one rendezvous
KV server. This subsystem scales both stories to N pods
(docs/multipod.md):

* :mod:`~horovod_tpu.multipod.topology` — the pod descriptor
  (pod_id, member ranks, DCN hop count) derived from env/mesh, the
  pod-aware view every other layer consumes, integrated with
  `core/process_sets.py`;
* :mod:`~horovod_tpu.multipod.relay` — per-pod relay servers that
  batch and forward pod-local control-plane pushes (metrics, flight
  dumps, replication manifests, serving registrations) to the root
  rendezvous server, so the root sees O(pods) connections instead of
  O(hosts);
* :mod:`~horovod_tpu.multipod.localsgd` — the opt-in local-SGD outer
  loop (``HOROVOD_MULTIPOD_SYNC=localK``): each pod runs K local steps
  on the existing SPMD path and periodically averages parameters
  cross-pod over the quantized DCN leg, with outer momentum and a
  bitwise-parity guarantee at K=1 versus the plain path.
"""

from .localsgd import (  # noqa: F401
    LocalSGD,
    OuterState,
    local_sgd_active,
    parse_sync_mode,
)
from .relay import (  # noqa: F401
    PodRelayServer,
    push_endpoint,
    relay_endpoint_from_env,
)
from .topology import (  # noqa: F401
    PodTopology,
    pod_topology,
    pod_topology_from_env,
)
