"""Model-FLOPs-utilization accounting.

The reference reports raw images/sec (docs/benchmarks.rst:40); on TPU the
meaningful denominator is the chip's peak matmul throughput, so benchmarks
here also report MFU = achieved model FLOP/s / peak bf16 FLOP/s. Peak
numbers are the published per-chip bf16 figures for each TPU generation.
"""

from __future__ import annotations

import os

# published peak bf16 TFLOP/s per chip
_PEAK_TFLOPS = {
    "v3": 123.0,
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}


def peak_flops_per_chip(default_gen: str = "v5e") -> float:
    """Peak bf16 FLOP/s for the chip we're running on. Generation comes
    from the PALLAS_AXON_TPU_GEN env (the harness sets it) or the device
    kind string; CPU test worlds fall back to `default_gen` so MFU stays
    a comparable ratio rather than a meaningless number."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").strip().lower()
    if gen not in _PEAK_TFLOPS:
        try:
            import jax

            # device_kind strings: "TPU v3", "TPU v4", "TPU v5 lite",
            # "TPU v5p", "TPU v6 lite" — lite suffix marks the e variants
            kind = jax.devices()[0].device_kind.lower()
            for version in ("v6", "v5", "v4", "v3"):
                if version in kind:
                    if version in ("v5", "v6"):
                        gen = (
                            version + "e" if "lit" in kind else version + "p"
                        )
                    else:
                        gen = version
                    break
        except Exception:
            pass
    if gen not in _PEAK_TFLOPS:
        gen = default_gen
    return _PEAK_TFLOPS[gen] * 1e12


def transformer_train_flops(n_params: int, tokens: int) -> float:
    """Training FLOPs for a dense transformer: the standard 6·N·D
    estimate (fwd 2ND + bwd 4ND), N = non-embedding ≈ total params for
    the sizes benchmarked here."""
    return 6.0 * float(n_params) * float(tokens)


# per-image forward multiply-accumulates at each model's native
# resolution (published GMAC counts: torchvision/ptflops tables); one
# MAC = 2 FLOPs on the MXU, matching the transformer 6·N·D convention
_CNN_FWD_MACS = {
    "resnet50": (4.1e9, 224),
    "resnet101": (7.8e9, 224),
    "resnet152": (11.5e9, 224),
    "inception3": (5.7e9, 299),
    "vgg16": (15.5e9, 224),
}


def cnn_train_flops(model: str, images: int, image_size: int) -> float:
    """Training FLOPs (fwd MACs ×2 FLOPs/MAC ×3 for fwd+bwd) for the
    synthetic-benchmark CNN family, scaled from each model's native
    resolution."""
    macs, native = _CNN_FWD_MACS[model]
    return 3.0 * 2.0 * macs * (image_size / native) ** 2 * float(images)


def resnet50_train_flops(images: int, image_size: int = 224) -> float:
    """Deprecated alias for ``cnn_train_flops("resnet50", ...)``; kept
    for callers of the pre-r3 helper. Note the accounting change: since
    r3 a MAC counts 2 FLOPs (earlier rounds counted 1), so values are 2x
    the pre-r3 helper's."""
    return cnn_train_flops("resnet50", images, image_size)


def count_params(tree) -> int:
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(tree))
