from . import flight, logging, metrics, mfu, timeline  # noqa: F401
