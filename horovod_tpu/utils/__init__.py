from . import logging, metrics, timeline  # noqa: F401
