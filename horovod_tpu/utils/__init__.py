from . import flight, logging, metrics, timeline  # noqa: F401
