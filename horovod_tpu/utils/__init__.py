from . import logging, timeline  # noqa: F401
