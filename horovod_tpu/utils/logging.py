"""Leveled, rank-prefixed logging.

Reference: /root/reference/horovod/common/logging.{cc,h} — C++ macro logger
with levels TRACE/DEBUG/INFO/WARNING/ERROR/FATAL, env-configured via
HOROVOD_LOG_LEVEL and HOROVOD_LOG_HIDE_TIME. Python logging is the natural
host here; the C++ native runtime (horovod_tpu/_native) has its own
mirror-image logger for the background thread.

Multi-rank attribution: with ``HOROVOD_LOG_RANK=1`` (or the
``rank_prefix`` argument, wired through worker init in core/basics.py)
every line carries a ``[rank N]`` prefix resolved from the launcher's
``HOROVOD_RANK`` env — no jax import, so the prefix is correct from the
first line of a spawned worker, before (or without) jax initializing.
Interleaved stderr from a multi-rank launch is then attributable by
grep alone.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG,  # python has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

LOGGER = logging.getLogger("horovod_tpu")


def _env_rank() -> int:
    """The launcher-assigned rank, or -1 outside a launched worker."""
    for key in ("HVD_TPU_RANK", "HOROVOD_RANK"):
        v = os.environ.get(key)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return -1


class _RankFilter(logging.Filter):
    """Stamps ``record.hvd_rank``: launcher env first (cheap, correct
    pre-jax), jax.process_index() as the fallback for worlds started
    without the launcher. The resolved value is cached — the per-record
    jax import this used to do was measurable noise on chatty levels."""

    def __init__(self) -> None:
        super().__init__()
        self._rank = _env_rank()

    def filter(self, record: logging.LogRecord) -> bool:
        if self._rank < 0:
            try:
                import jax

                self._rank = jax.process_index()
            except Exception:
                pass  # keep retrying until a backend exists
        record.hvd_rank = self._rank
        return True


def _env_truthy(name: str) -> bool:
    v = (os.environ.get("HVD_TPU_" + name)
         or os.environ.get("HOROVOD_" + name) or "")
    return v.strip().lower() in ("1", "true", "yes", "on")


def configure_logging(level: str = "WARNING",
                      hide_timestamp: bool = False,
                      rank_prefix: bool = None) -> None:
    """(Re)configure the horovod_tpu logger. ``rank_prefix`` (default:
    the HOROVOD_LOG_RANK env) switches to the ``[rank N]`` line format;
    re-calling updates the level and format of the existing handler."""
    if rank_prefix is None:
        rank_prefix = _env_truthy("LOG_RANK")
    LOGGER.setLevel(_LEVELS.get(level.strip().lower(), logging.WARNING))
    if not LOGGER.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.addFilter(_RankFilter())
        h._hvd_managed = True  # only OUR handler gets re-formatted
        LOGGER.addHandler(h)
        LOGGER.propagate = False
    if rank_prefix:
        fmt = "[rank %(hvd_rank)s] <%(levelname)s> %(message)s"
    else:
        fmt = "[%(hvd_rank)s]<%(levelname)s> %(message)s"
    if not hide_timestamp:
        fmt = "%(asctime)s " + fmt
    for h in LOGGER.handlers:
        # re-applying on re-init keeps rank_prefix/level switchable,
        # but user-attached handlers keep their own formatters
        if getattr(h, "_hvd_managed", False):
            h.setFormatter(logging.Formatter(fmt))


def get_logger() -> logging.Logger:
    return LOGGER
