"""Leveled, rank-prefixed logging.

Reference: /root/reference/horovod/common/logging.{cc,h} — C++ macro logger
with levels TRACE/DEBUG/INFO/WARNING/ERROR/FATAL, env-configured via
HOROVOD_LOG_LEVEL and HOROVOD_LOG_HIDE_TIME. Python logging is the natural
host here; the C++ native runtime (horovod_tpu/_native) has its own
mirror-image logger for the background thread.
"""

from __future__ import annotations

import logging
import sys

_LEVELS = {
    "trace": logging.DEBUG,  # python has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

LOGGER = logging.getLogger("horovod_tpu")


class _RankFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        try:
            import jax

            record.hvd_rank = jax.process_index()
        except Exception:
            record.hvd_rank = -1
        return True


def configure_logging(level: str = "WARNING", hide_timestamp: bool = False) -> None:
    LOGGER.setLevel(_LEVELS.get(level.strip().lower(), logging.WARNING))
    if not LOGGER.handlers:
        h = logging.StreamHandler(sys.stderr)
        fmt = "[%(hvd_rank)s]<%(levelname)s> %(message)s"
        if not hide_timestamp:
            fmt = "%(asctime)s " + fmt
        h.setFormatter(logging.Formatter(fmt))
        h.addFilter(_RankFilter())
        LOGGER.addHandler(h)
        LOGGER.propagate = False


def get_logger() -> logging.Logger:
    return LOGGER
