"""Chrome-tracing timeline.

Reference: /root/reference/horovod/common/timeline.{cc,h} — per-tensor
negotiation/op phase events written as Chrome trace JSON by a dedicated
writer thread fed over a lock-free SPSC queue; dynamic start/stop via
horovod_start_timeline (operations.cc:1048). Activity names in
common.h:79-113 (NEGOTIATE_ALLREDUCE, QUEUE, WAIT_FOR_DATA, ...,
NCCL_ALLREDUCE).

TPU-native shape: device-side timing belongs to the XLA/JAX profiler
(`jax.profiler.trace` — xplane), which this module can drive; the
*host-side* phases unique to the framework (enqueue, negotiation rounds in
the eager runtime, fusion, cache hits, elastic transitions) are recorded
here in the same Chrome trace JSON format so `chrome://tracing` /
Perfetto render them identically to the reference's timeline
(docs/timeline.rst:20). A plain buffered writer thread replaces the
lock-free queue — host-side event rates here are orders of magnitude lower
than per-GPU-op rates in the reference, since XLA executes fused steps.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

from . import metrics as _metrics

# Phase/activity names kept verbatim from the reference (common.h:79-113)
# so downstream trace tooling written against Horovod timelines keeps
# working.
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
NEGOTIATE_ALLTOALL = "NEGOTIATE_ALLTOALL"
NEGOTIATE_REDUCESCATTER = "NEGOTIATE_REDUCESCATTER"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
ALLTOALL = "ALLTOALL"
REDUCESCATTER = "REDUCESCATTER"
QUEUE = "QUEUE"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
WAIT_FOR_OTHER_TENSOR_DATA = "WAIT_FOR_OTHER_TENSOR_DATA"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
XLA_COLLECTIVE = "XLA_COLLECTIVE"
CYCLE_START = "CYCLE_START"

# First event of every trace: maps the file's relative microsecond axis
# onto the wall clock (and names the emitting rank), so
# scripts/trace_merge.py can place per-rank host timelines, device op
# lines and flight events on ONE aligned axis (docs/timeline.md).
CLOCK_ANCHOR = "CLOCK_ANCHOR"


class Timeline:
    """Chrome trace event JSON writer with a background writer thread.

    Events: 'ts' (begin, phase push), 'te' (end, phase pop), 'i' (instant),
    mapping onto Chrome's B/E/i event types — same structure the reference
    emits (timeline.cc WriteEvent)."""

    def __init__(self, filename: Optional[str] = None, mark_cycles: bool = False):
        self._filename = filename
        self._mark_cycles = mark_cycles
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._active = False
        self._start_ns = time.perf_counter_ns()
        # metrics bridge: open-span start stamps keyed (tensor, activity)
        # so every closed phase can land in a latency histogram
        self._span_starts: dict = {}
        if filename:
            self.start(filename)

    # -- lifecycle (reference: horovod_start_timeline/stop, ops.cc:1048) ---

    def start(self, filename: str, mark_cycles: Optional[bool] = None) -> None:
        if self._active:
            return
        if mark_cycles is not None:
            self._mark_cycles = mark_cycles
        self._filename = filename
        self._active = True
        self._thread = threading.Thread(
            target=self._writer, name="hvd_tpu_timeline", daemon=True
        )
        self._thread.start()
        self._emit_anchor()

    def _emit_anchor(self) -> None:
        """The wall-clock anchor: an instant whose args carry the unix
        time of its own ``ts`` stamp plus this process's rank, letting
        offline tooling convert every event's relative microseconds to
        wall time (wall = time_unix + (ts - anchor_ts)/1e6)."""
        from . import flight as _flight

        self.emit("i", CLOCK_ANCHOR, "clock", {
            "time_unix": time.time(),
            "rank": _flight.rank(),
            "pid": os.getpid(),
        })

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        self.stop()

    @property
    def active(self) -> bool:
        return self._active

    # -- event API ---------------------------------------------------------

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._start_ns) / 1e3

    def emit(self, ph: str, name: str, tensor: str, args: Optional[dict] = None) -> None:
        if not self._active:
            return
        ev = {
            "ph": ph,
            "name": name,
            "ts": self._now_us(),
            "pid": os.getpid(),
            "tid": tensor,
        }
        if args:
            ev["args"] = args
        self._q.put(ev)

    def activity_start(self, tensor: str, activity: str, args: Optional[dict] = None) -> None:
        if _metrics.enabled():
            # bound the open-span table: spans whose end never arrives
            # (executor failures drop the handle before the E event;
            # auto-named tensors never repeat their key) would otherwise
            # accumulate forever — evict oldest-inserted when full
            if len(self._span_starts) >= 8192:
                for k in list(self._span_starts)[:1024]:
                    self._span_starts.pop(k, None)
            self._span_starts[(tensor, activity)] = time.perf_counter_ns()
        self.emit("B", activity, tensor, args)

    def activity_end(self, tensor: str, activity: str) -> None:
        if _metrics.enabled():
            t0 = self._span_starts.pop((tensor, activity), None)
            if t0 is not None:
                _metrics.record_timeline_activity(
                    activity, (time.perf_counter_ns() - t0) / 1e9
                )
        self.emit("E", activity, tensor)

    def instant(self, tensor: str, name: str, args: Optional[dict] = None) -> None:
        self.emit("i", name, tensor, args)

    def mark_cycle_start(self) -> None:
        if self._mark_cycles:
            self.instant("cycle", CYCLE_START)

    class _Activity:
        def __init__(self, tl: "Timeline", tensor: str, activity: str):
            self.tl, self.tensor, self.activity = tl, tensor, activity

        def __enter__(self):
            self.tl.activity_start(self.tensor, self.activity)
            return self

        def __exit__(self, *exc):
            self.tl.activity_end(self.tensor, self.activity)
            return False

    def activity(self, tensor: str, activity: str) -> "Timeline._Activity":
        return Timeline._Activity(self, tensor, activity)

    # -- writer thread -----------------------------------------------------

    def _writer(self) -> None:
        assert self._filename
        with open(self._filename, "w") as f:
            f.write("[\n")
            first = True
            while True:
                ev = self._q.get()
                if ev is None:
                    break
                if not first:
                    f.write(",\n")
                f.write(json.dumps(ev))
                first = False
            f.write("\n]\n")


def active_timeline() -> Optional["Timeline"]:
    """The framework's timeline when tracing is on, else None — the one
    gate every event-emitting layer uses. With metrics enabled the
    timeline is returned even when no trace file is being written:
    `emit` drops the events (no writer, no queue growth) but the span
    start/end pairs still feed the phase-latency histograms
    (utils/metrics.py record_timeline_activity)."""
    from ..core.state import global_state

    tl = global_state().timeline
    if tl is None:
        return None
    return tl if (tl.active or _metrics.enabled()) else None


# -- jax profiler passthrough ----------------------------------------------

def profiler_trace(logdir: str):
    """Context manager: XLA-level device tracing (xplane) alongside the
    host-side timeline; TPU-native replacement for the reference's
    NVTX ranges (nvtx_op_range.h)."""
    import jax

    return jax.profiler.trace(logdir)
