"""Deterministic fault injection for the control plane.

The elastic recovery machinery (elastic/state.py run-wrapper,
runner/elastic/driver.py rounds, blacklisting) existed without any way
to *prove* it works under failure. This module is the chaos layer: a
spec string — ``HOROVOD_TPU_FAULT_SPEC`` — compiles into rules that
fire at named injection points threaded through the HTTP client/server,
elastic discovery, worker exec, eager-runtime negotiation
(``collective``) and plan-cache activation (``eager.fast_path``,
docs/eager.md), checkpoint I/O, and the serving path (admission,
replica dispatch, engine execution — ``serving.*``, docs/serving.md).

Spec grammar (entries separated by ``;`` or ``,``; fields by ``:``)::

    point:action[:probability][:key=value ...]

    http.put:error:0.3:seed=7        30% of KV puts raise (seeded rng)
    worker:kill:rank=2:step=5        rank 2's worker dies at commit 5
    discovery:flap:after=5:times=1   one empty discovery poll
    collective:delay:secs=0.02       20ms pause on every enqueue
    checkpoint.save:error:times=2    first two saves fail (then heal)

Actions:

* ``error`` — raise :class:`InjectedFault` (a ``ConnectionError``, so
  real retry paths treat it exactly like a transport failure).
* ``delay`` — sleep ``secs`` (default 0.05) in the caller.
* ``kill``  — ``os._exit(code)`` (default 1): simulated process death.
* ``flap``  — cooperative: ``inject()`` returns the action name and the
  call site implements the behavior (discovery returns an empty host
  set for one poll).
* ``corrupt`` — deterministically XOR-flips ``nbytes`` (default 8)
  bytes of a serialized payload at sites that route their bytes
  through :func:`corrupt` (emergency checkpoints, snapshot replicas),
  so checksum-verification paths are testable like every other
  failure mode (docs/recovery.md).

A rule's ``point`` matches an injection point exactly or as a
dot-prefix (``http`` matches ``http.put``). Remaining ``key=value``
fields are either rule parameters (``seed``, ``times``, ``after``,
``secs``, ``code``) or context constraints matched against the
``inject()`` call's keyword context (``rank=2``, ``step=5``,
``scope=workers``); a constraint whose key the call site does not
supply never matches, so a ``worker:kill:step=5`` rule cannot
accidentally fire at ``worker.register``.

Determinism: each rule owns a ``random.Random(seed)`` (seed defaults
to 0), so a given spec produces the same fire pattern every run —
chaos tests assert exact recovery behavior, not luck.

Cost discipline mirrors utils/metrics.py: with the spec unset the
module is disabled and every ``inject()`` is a single predicted
branch; the injection points add nothing measurable to the eager path
(scripts/eager_path_bench.py).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics

# ---------------------------------------------------------------------------
# module gate (the no-op fast path)
# ---------------------------------------------------------------------------

_enabled = False
_rules: List["_Rule"] = []
# RLock, not Lock: the preemption SIGTERM handler routes the emergency
# payload through corrupt() and may interrupt the main thread while it
# holds this lock inside inject() — re-entry from the same thread must
# not self-deadlock (same reasoning as PreemptionHandler._lock)
_lock = threading.RLock()

# test hook: kill-action exit (os._exit in production)
_exit = os._exit
# test hook: delay-action sleep
_sleep = time.sleep

ENV_SPEC = "HOROVOD_TPU_FAULT_SPEC"

_ACTIONS = ("error", "delay", "kill", "flap", "corrupt")
_PARAM_KEYS = ("seed", "times", "after", "secs", "code", "nbytes")


class InjectedFault(ConnectionError):
    """An injected transport-shaped failure. Subclasses
    ``ConnectionError`` so the retry machinery and every call site that
    survives real ECONNRESETs handles it identically."""


class FaultSpecError(ValueError):
    """The fault spec string could not be parsed."""


class _Rule:
    __slots__ = (
        "point", "action", "prob", "times", "after", "secs", "code",
        "nbytes", "match", "_rng", "calls", "fires", "text",
    )

    def __init__(self, text: str):
        import random

        fields = [f for f in text.strip().split(":") if f != ""]
        if len(fields) < 2:
            raise FaultSpecError(
                f"fault rule {text!r} needs at least point:action"
            )
        self.text = text.strip()
        self.point = fields[0]
        self.action = fields[1]
        if self.action not in _ACTIONS:
            raise FaultSpecError(
                f"unknown fault action {self.action!r} in {text!r} "
                f"(know {_ACTIONS})"
            )
        self.prob = 1.0
        self.times: Optional[int] = None
        self.after = 0
        self.secs = 0.05
        self.code = 1
        self.nbytes = 8
        self.match: Dict[str, str] = {}
        seed = 0
        for field in fields[2:]:
            key, sep, value = field.partition("=")
            if not sep:
                try:
                    self.prob = float(field)
                except ValueError:
                    raise FaultSpecError(
                        f"bare field {field!r} in {text!r} is not a "
                        "probability"
                    ) from None
                if not 0.0 <= self.prob <= 1.0:
                    raise FaultSpecError(
                        f"probability {self.prob} in {text!r} not in [0,1]"
                    )
                continue
            if key == "seed":
                seed = int(value)
            elif key == "times":
                self.times = int(value)
            elif key == "after":
                self.after = int(value)
            elif key == "secs":
                self.secs = float(value)
            elif key == "code":
                self.code = int(value)
            elif key == "nbytes":
                self.nbytes = int(value)
            elif key == "p":
                self.prob = float(value)
            else:
                self.match[key] = value
        self._rng = random.Random(seed)
        self.calls = 0
        self.fires = 0

    def matches_point(self, point: str) -> bool:
        return point == self.point or point.startswith(self.point + ".")

    def consider(self, point: str, ctx: Dict[str, object]) -> bool:
        """Does this rule fire for this call? Mutates call/fire counts
        (caller holds the module lock)."""
        if not self.matches_point(point):
            return False
        for key, want in self.match.items():
            if key not in ctx or str(ctx[key]) != want:
                return False
        self.calls += 1
        if self.calls <= self.after:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self.fires += 1
        return True


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return _enabled


def configure(spec: Optional[str] = None) -> None:
    """Compile a fault spec (default: the ``HOROVOD_TPU_FAULT_SPEC`` /
    ``HVD_TPU_FAULT_SPEC`` / ``HOROVOD_FAULT_SPEC`` env) and enable
    injection. An empty/absent spec disables."""
    global _enabled, _rules
    if spec is None:
        spec = (
            os.environ.get(ENV_SPEC, "")
            or os.environ.get("HVD_TPU_FAULT_SPEC", "")
            or os.environ.get("HOROVOD_FAULT_SPEC", "")
        )
    rules = []
    for chunk in spec.replace(",", ";").split(";"):
        chunk = chunk.strip()
        if chunk:
            rules.append(_Rule(chunk))
    with _lock:
        _rules = rules
        _enabled = bool(rules)


def reset() -> None:
    """Disable injection and drop all rules (test hook)."""
    global _enabled, _rules
    with _lock:
        _rules = []
        _enabled = False


def rules() -> List[str]:
    """The active rule texts, for diagnostics."""
    with _lock:
        return [r.text for r in _rules]


def _fired_rules(point: str, ctx: Dict[str, object]) -> List[_Rule]:
    fired: List[_Rule] = []
    with _lock:
        for rule in _rules:
            if rule.consider(point, ctx):
                fired.append(rule)
    return fired


def _flip_bytes(data: bytes, rule: _Rule) -> bytes:
    """Deterministically XOR-flip ``rule.nbytes`` bytes of ``data`` at
    positions drawn from the rule's seeded RNG — the same fire pattern
    every run, like every other action."""
    if not data:
        return data
    buf = bytearray(data)
    for _ in range(max(1, min(len(buf), rule.nbytes))):
        buf[rule._rng.randrange(len(buf))] ^= 0xFF
    return bytes(buf)


def _run_actions(fired: List[_Rule], point: str,
                 data: Optional[bytes] = None,
                 ) -> "Tuple[Optional[str], Optional[bytes]]":
    """Execute fired rules' actions. Every fired rule is recorded and
    its non-raising action executed BEFORE any error rule raises:
    consider() already spent the rules' times/probability budget, so a
    raise must not swallow a co-fired delay/flap/kill/corrupt or its
    accounting."""
    coop: Optional[str] = None
    error_rule: Optional[_Rule] = None
    for rule in fired:
        _metrics.record_fault(point, rule.action)
        if rule.action == "delay":
            _sleep(rule.secs)
        elif rule.action == "error":
            error_rule = error_rule or rule
        elif rule.action == "corrupt":
            if data is not None:
                data = _flip_bytes(data, rule)
            else:
                # an inject()-only site has no payload to damage; hand
                # the action name to the caller like any cooperative
                # action so spec typos surface instead of vanishing
                coop = rule.action
        elif rule.action != "kill":
            coop = rule.action
    for rule in fired:
        if rule.action == "kill":
            _exit(rule.code)
    if error_rule is not None:
        raise InjectedFault(
            f"injected fault at {point}"
            + (f" [{error_rule.text}]" if error_rule.text else "")
        )
    return coop, data


def inject(point: str, **ctx) -> Optional[str]:
    """Fire any matching rules at a named injection point.

    Raising actions raise (``error`` → :class:`InjectedFault`); the
    ``kill`` action exits the process; ``delay`` sleeps inline.
    Cooperative actions (``flap``, payload-less ``corrupt``) are
    returned by name for the call site to implement. Returns None when
    nothing cooperative fired — including always when injection is
    disabled (the fast path).
    """
    if not _enabled:
        return None
    coop, _ = _run_actions(_fired_rules(point, ctx), point)
    return coop


def corrupt(point: str, data: bytes, **ctx) -> bytes:
    """Pass a serialized payload through the corruption gate at a named
    point (checkpoint/replica payloads: ``emergency.payload``,
    ``replication.payload``). A matching ``corrupt`` rule
    deterministically flips ``nbytes`` (default 8) bytes; co-fired
    error/delay/kill rules behave exactly as in :func:`inject`. Returns
    ``data`` unchanged when injection is disabled (the fast path) or no
    rule fires — integrity-verification paths are testable like every
    other failure mode."""
    if not _enabled:
        return data
    _, out = _run_actions(_fired_rules(point, ctx), point, data)
    return out if out is not None else data


# Worker processes are spawned by the launcher with the spec in their
# env and never necessarily call hvd.init(), so arm at import. Never
# let a malformed spec break `import horovod_tpu` — a spec typo
# surfaces loudly the first time someone configures explicitly.
try:
    configure()
except FaultSpecError as _e:
    import logging

    logging.getLogger("horovod_tpu.faults").warning(
        "ignoring malformed %s: %s", ENV_SPEC, _e
    )
