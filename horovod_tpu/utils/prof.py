"""Continuous step profiler: sampled device capture + live attribution.

The host timeline (utils/timeline.py), metrics (utils/metrics.py) and
flight recorder (utils/flight.py) all stop at the host: none of them
sees what the TPU actually executed, so statements like "the overlap
window is 0.89" rested on AOT schedule analysis, not measured device
events. This module closes that gap in the always-on, bounded-overhead
mold of Google's fleet-wide continuous profiling (PAPERS.md: "Profiling
a warehouse-scale computer"): every ``HOROVOD_PROF_EVERY``-th step is
wrapped in ``jax.profiler`` device tracing, the resulting xplane is
parsed off-thread (utils/xplane.py — no TensorFlow needed), and the
sampled step is attributed into **compute / exposed-collective /
host-gap / idle** buckets that feed the live registry:

* ``hvd_step_compute_frac`` / ``hvd_step_exposed_wire_frac`` /
  ``hvd_step_idle_frac`` — where the sampled step's wall time went;
* ``hvd_overlap_window_measured_frac`` — the measured twin of PR 9's
  structural ``hvd_overlap_window_frac``: how much collective time the
  device really hid under compute;
* ``hvd_mfu`` — model-FLOPs utilization every step (not only sampled
  ones), once :func:`set_step_flops` declares the model's per-step
  cost (utils/mfu.py owns the peak tables).

Cost discipline (the PR-6 replicator's duty-cycle model): sampling is
OFF by default; when off, the per-step hook is a single predicted
branch (asserted by tests/test_prof.py). When on, each sample's
measured overhead T (trace start/stop + off-thread parse CPU) charges
a budget — the next sample cannot start until ``T*(1/d - 1)`` wall
seconds pass (``HOROVOD_PROF_DUTY_CYCLE``, default 2%), so profiling
consumes at most ~d of the run no matter how slow parsing is.

Each sample directory (``HOROVOD_PROF_DIR``, default
``<tmpdir>/hvd_prof/rank<r>``) carries a ``hvd_prof_meta.json`` sidecar
(rank, step, wall-clock window, /clock offset to the driver) so
``scripts/trace_merge.py`` can place its device ops on the same
clock-aligned axis as host timelines and flight dumps
(docs/timeline.md).

The profiler rides the existing step boundary: ``with
hvd.metrics.step():`` is the only annotation needed (the module
registers a step wrapper with utils/metrics.py at ``hvd.init``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Callable, List, Optional

from . import flight as _flight
from . import metrics as _metrics

# ---------------------------------------------------------------------------
# module state (the no-op fast path)
# ---------------------------------------------------------------------------

_active = False          # True iff sampling and/or MFU accounting is on
_configured = False      # True when configure() (hvd.init) armed us
_every = 0               # sample every N-th step; 0 = sampling off
_duty = 0.02             # max fraction of wall time spent profiling
_dir = ""                # sample-capture root
_step_flops = 0.0        # model FLOPs per optimizer step (whole batch)
_n_chips = 0             # devices dividing the FLOPs; 0 = auto
_lock = threading.Lock()
_counter = 0             # steps seen
_samples = 0             # captures taken
_next_ok_t = 0.0         # monotonic floor for the next sample
_inflight = False        # a capture/parse is outstanding
_force_next = False      # capture the NEXT step regardless of cadence
_parse_thread: Optional[threading.Thread] = None
_last_attribution: Optional[dict] = None
_last_mfu: Optional[float] = None
_overhead_s = 0.0        # cumulative measured profiling overhead
_errors = 0
_clock: Callable[[], float] = time.monotonic   # injectable for tests


def active() -> bool:
    return _active


def sample_count() -> int:
    return _samples


def overhead_s() -> float:
    """Cumulative measured profiling overhead (capture + parse CPU) —
    the numerator of the duty-cycle bound."""
    return _overhead_s


def last_attribution() -> Optional[dict]:
    """The most recent sampled-step attribution (utils/xplane.attribute
    output + ``sampled_step``/``mfu`` context), or None before the
    first completed sample."""
    return _last_attribution


def last_mfu() -> Optional[float]:
    return _last_mfu


def set_step_flops(flops: float, n_chips: int = 0) -> None:
    """Declare the model's FLOPs per optimizer step (whole global
    batch; utils/mfu.py transformer_train_flops / cnn_train_flops are
    the standard sources). Enables the per-step ``hvd_mfu`` gauge:
    mfu = flops / (step_time x chips x peak chip FLOP/s). ``n_chips``
    0 = all visible devices."""
    global _step_flops, _n_chips, _peak_total
    _step_flops = float(flops)
    _n_chips = int(n_chips)
    _peak_total = 0.0  # chip count may have changed; recompute lazily
    if _configured:
        _update_activation()


def step_flops() -> float:
    return _step_flops


_peak_total = 0.0  # cached chips x peak FLOP/s (fixed per process)


def _peak_total_flops() -> float:
    """chips x peak per-chip FLOP/s — resolved once (jax device query +
    device-kind parsing are not per-step costs) and cached until
    set_step_flops/reset invalidates."""
    global _peak_total
    if _peak_total > 0:
        return _peak_total
    from . import mfu as _mfu

    n = _n_chips
    if n <= 0:
        try:
            import jax

            n = jax.device_count()
        except Exception:
            n = 1
    _peak_total = max(n, 1) * _mfu.peak_flops_per_chip()
    return _peak_total


def default_dir() -> str:
    base = _dir or os.path.join(tempfile.gettempdir(), "hvd_prof")
    r = _flight.rank()
    return os.path.join(base, f"rank{max(r, 0)}")


# ---------------------------------------------------------------------------
# the step wrapper (registered with utils/metrics.set_step_wrapper)
# ---------------------------------------------------------------------------

class _Token:
    __slots__ = ("t0", "t0_wall", "logdir", "step",
                 "capture_overhead_s", "mfu")

    def __init__(self, t0: float, t0_wall: float,
                 logdir: Optional[str], step: int):
        self.t0 = t0
        self.t0_wall = t0_wall
        self.logdir = logdir
        self.step = step
        self.capture_overhead_s = 0.0
        self.mfu: Optional[float] = None


class _StepWrapper:
    """What utils/metrics.step() drives: one begin/end pair per step."""

    def begin_step(self):
        if not _active:
            return None
        return _begin_step()

    def end_step(self, token) -> None:
        if token is not None:
            _end_step(token)


_wrapper = _StepWrapper()


def _begin_step() -> _Token:
    global _counter, _inflight, _samples, _force_next
    with _lock:
        _counter += 1
        step = _counter
        sample = not _inflight and (
            (_every > 0
             and step % _every == 0
             and _clock() >= _next_ok_t)
            # anomaly-triggered forensics (health/): a requested
            # capture bypasses the cadence and the duty-budget floor —
            # the one step that explains an alert is worth its cost
            or _force_next
        )
        if sample:
            _inflight = True
            _samples += 1
            _force_next = False
    logdir = None
    if sample:
        logdir = os.path.join(default_dir(), f"step{step}")
        t0 = _clock()
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception:
            _note_error()
            with _lock:
                _samples -= 1  # a failed capture is not a sample
            # charge the failed attempt to the duty budget: a
            # persistently failing capture (unwritable dir, wedged
            # profiler session) backs off under the same bound instead
            # of paying makedirs + raise on every N-th step forever
            _finish_sample(_clock() - t0)
            logdir = None
        tok = _Token(_clock(), time.time(), logdir, step)
        tok.capture_overhead_s = _clock() - t0
        return tok
    return _Token(_clock(), time.time(), None, step)


def _end_step(token: _Token) -> None:
    dt = _clock() - token.t0
    if _step_flops > 0 and dt > 0:
        # stamped on the token too: the async parse must attach THIS
        # step's MFU to the attribution record, not whatever later
        # step last updated the global by the time parsing finishes
        token.mfu = _step_flops / (dt * _peak_total_flops())
        _record_mfu(token.mfu)
    if token.logdir is None:
        return
    t0 = _clock()
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        _note_error()
        _finish_sample(token.capture_overhead_s + (_clock() - t0))
        return
    token.capture_overhead_s += _clock() - t0
    _spawn_parse(token, dt)


def _record_mfu(mfu: float) -> None:
    global _last_mfu
    _last_mfu = mfu
    _metrics.record_mfu(mfu)


def _write_sidecar(token: _Token, host_wall_s: float) -> None:
    """The clock anchor trace_merge.py aligns device ops with: the
    capture's wall window on this rank plus the /clock offset onto the
    driver's axis (same probe as flight dumps)."""
    meta = {
        "hvd_prof_meta": 1,
        "rank": _flight.rank(),
        "step": token.step,
        "t_start_unix": token.t0_wall,
        "t_stop_unix": time.time(),
        "host_wall_s": round(host_wall_s, 6),
    }
    meta.update(_flight.clock_probe())
    # atomic write: trace_merge.py places this sample's device ops by
    # t_start_unix, so a torn sidecar must not exist under its final
    # name (the merger skips samples with no valid anchor)
    path = os.path.join(token.logdir, "hvd_prof_meta.json")
    try:
        with open(path + ".tmp", "w") as f:
            json.dump(meta, f)
            f.write("\n")
        os.replace(path + ".tmp", path)
    except OSError:
        _note_error()


def _spawn_parse(token: _Token, host_wall_s: float) -> None:
    """Sidecar write + parse + attribute off-thread: the training step
    resumes immediately; the sidecar's /clock probe (a bounded HTTP
    round-trip) and the parse CPU both charge the duty-cycle budget
    when the thread finishes."""
    global _parse_thread

    def work():
        t0 = _clock()
        try:
            _write_sidecar(token, host_wall_s)
            _parse_sample(token, host_wall_s)
        except Exception:
            _note_error()
        finally:
            _finish_sample(
                token.capture_overhead_s + (_clock() - t0))

    try:
        t = threading.Thread(target=work, daemon=True,
                             name="hvd-prof-parse")
        t.start()
    except Exception:
        # thread exhaustion must not crash the user's training step or
        # wedge sampling (_inflight would stay set forever)
        _note_error()
        _finish_sample(token.capture_overhead_s)
        return
    _parse_thread = t


#: capture dirs kept per rank — a continuous run must not grow tmpdir
#: without bound (each sample's .xplane.pb is megabytes); the newest K
#: stay available for trace_merge.py
_KEEP_SAMPLES = 8


def _prune_samples() -> None:
    """Drop all but the newest ``_KEEP_SAMPLES`` step<N> capture dirs
    under this rank's root (runs on the parse thread, off the step
    path). Newest by mtime, not step number: a restarted run's fresh
    low-step captures must survive a dead run's stale high-step
    leftovers in the same (default, shared-tmpdir) root."""
    import re
    import shutil

    root = default_dir()
    entries = []
    try:
        for name in os.listdir(root):
            if re.fullmatch(r"step\d+", name):
                try:
                    entries.append(
                        (os.path.getmtime(os.path.join(root, name)),
                         name))
                except OSError:
                    continue
    except OSError:
        return
    entries.sort()
    for _, name in entries[:-_KEEP_SAMPLES or None]:
        shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def _parse_sample(token: _Token, host_wall_s: float) -> None:
    global _last_attribution
    from . import xplane

    _prune_samples()
    xs, _ = xplane.load_xspace(token.logdir)
    ops = xplane.op_events(xs)
    if not ops:
        raise xplane.XPlaneUnavailable("capture holds no op events")
    attr = xplane.attribute_by_plane(ops, host_wall_us=host_wall_s * 1e6)
    attr["sampled_step"] = token.step
    if token.mfu is not None:
        attr["mfu"] = round(token.mfu, 6)
    _last_attribution = attr
    _metrics.record_step_attribution(attr)
    _flight.record("prof_sample", f"step{token.step}",
                   compute_frac=attr["compute_frac"],
                   exposed_wire_frac=attr["exposed_wire_frac"])


def _finish_sample(overhead_s: float) -> None:
    """Charge the duty budget and reopen the sampling gate: after a
    sample costing T the next one waits T*(1/d - 1), so profiling's
    share of wall time stays ≤ d."""
    global _inflight, _next_ok_t, _overhead_s
    with _lock:
        _overhead_s += overhead_s
        if _duty > 0:
            _next_ok_t = _clock() + overhead_s * (1.0 / _duty - 1.0)
        _inflight = False
    # a forced (anomaly-triggered) capture may have armed the wrapper
    # with sampling otherwise off: drop back to the knob-driven state
    if not _force_next:
        _update_activation()


def _note_error() -> None:
    global _errors
    _errors += 1


# ---------------------------------------------------------------------------
# manual step marking (for callers not using hvd.metrics.step())
# ---------------------------------------------------------------------------

class _StepCtx:
    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _wrapper.begin_step()
        return self

    def __exit__(self, *exc):
        _wrapper.end_step(self._token)
        return False


def step() -> "_StepCtx":
    """Standalone step boundary for code that does not use
    ``hvd.metrics.step()`` (which already drives the profiler). Do not
    nest the two — each entry counts one step."""
    return _StepCtx()


# ---------------------------------------------------------------------------
# lifecycle (core/basics.py calls configure/on_shutdown)
# ---------------------------------------------------------------------------

def _activate() -> None:
    global _active
    _active = True
    _metrics.set_step_wrapper(_wrapper)


def _update_activation() -> None:
    """Arm or disarm to match the current knobs: sampling or MFU wanted
    → wrapper installed; neither → fully off (metrics.step() back to
    its no-op fast path, not a per-step token allocation)."""
    global _active
    if _every > 0 or _step_flops > 0:
        _activate()
    elif _active:
        _active = False
        if _metrics._step_wrapper is _wrapper:
            _metrics.set_step_wrapper(None)


def request_sample(reason: str = "") -> None:
    """Force a device capture on the NEXT step, bypassing the
    ``prof_every`` cadence and the duty-budget floor (one outstanding
    capture at a time still applies). The health monitor calls this
    when an alert fires so the xplane trace of a degraded step exists
    before anyone goes looking for it. Arms the step wrapper if
    sampling was otherwise off; after the forced capture the
    knob-driven activation state is restored."""
    global _force_next
    _force_next = True
    _flight.record("prof_request", reason or "manual")
    _activate()


def configure(knobs=None, *, every: Optional[int] = None,
              duty_cycle: Optional[float] = None,
              directory: Optional[str] = None,
              clock: Optional[Callable[[], float]] = None) -> None:
    """Arm the profiler from the knob snapshot (hvd.init) or explicit
    overrides (tests/benches). ``HOROVOD_PROF_EVERY=0`` (the default)
    leaves the whole subsystem a no-op — no wrapper is registered
    unless sampling or MFU accounting is wanted."""
    global _configured, _every, _duty, _dir, _clock
    _every = int(every if every is not None
                 else getattr(knobs, "prof_every", 0) or 0)
    if duty_cycle is not None:
        _duty = float(duty_cycle)
    else:
        knob_duty = getattr(knobs, "prof_duty_cycle", None)
        # 0 is a valid value (gate disabled); only None falls back
        _duty = 0.02 if knob_duty is None else float(knob_duty)
    if directory is not None:
        _dir = directory
    elif knobs is not None:
        # re-read like every/duty: a re-init with a different
        # HOROVOD_PROF_DIR must not keep capturing under the old root
        _dir = getattr(knobs, "prof_dir", "") or ""
    if clock is not None:
        _clock = clock
    _configured = True
    _update_activation()


def join(timeout_s: float = 10.0) -> None:
    """Wait for an outstanding sample parse (tests / run teardown)."""
    t = _parse_thread
    if t is not None and t.is_alive():
        t.join(timeout=timeout_s)


def summary() -> dict:
    """Point-in-time profiler state (benches, perf_baseline.py)."""
    return {
        "active": _active,
        "every": _every,
        "duty_cycle": _duty,
        "steps": _counter,
        "samples": _samples,
        "overhead_s": round(_overhead_s, 6),
        "errors": _errors,
        "mfu": _last_mfu,
        "attribution": _last_attribution,
    }


def on_shutdown() -> None:
    """hvd.shutdown(): stop sampling; leave counters for inspection."""
    global _active, _configured
    join(timeout_s=5.0)
    if _configured:
        _configured = False
        _active = False
        if _metrics._step_wrapper is _wrapper:
            _metrics.set_step_wrapper(None)


def reset() -> None:
    """Test hook: return to the disabled, unconfigured state."""
    global _active, _configured, _every, _duty, _dir, _step_flops
    global _n_chips, _counter, _samples, _next_ok_t, _inflight
    global _last_attribution, _last_mfu, _overhead_s, _errors, _clock
    global _parse_thread, _peak_total, _force_next
    join(timeout_s=5.0)
    _active = False
    _configured = False
    _every = 0
    _duty = 0.02
    _dir = ""
    _step_flops = 0.0
    _n_chips = 0
    _peak_total = 0.0
    _counter = 0
    _samples = 0
    _next_ok_t = 0.0
    _inflight = False
    _force_next = False
    _parse_thread = None
    _last_attribution = None
    _last_mfu = None
    _overhead_s = 0.0
    _errors = 0
    _clock = time.monotonic
    if _metrics._step_wrapper is _wrapper:
        _metrics.set_step_wrapper(None)
