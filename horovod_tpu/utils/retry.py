"""Shared control-plane retry policy: exponential backoff + jitter,
monotonic-clock deadlines, injectable clock/sleep for tests.

The reference's control plane leans on transport-level robustness (Gloo
rendezvous retries, MPI's own fault model); our HTTP/TCP bootstrap has
none, so one transient ECONNRESET in `runner/http/http_client.py` used
to kill a worker. This module is the one retry implementation every
control-plane call site adopts — the KV store client, worker
registration/notification, discovery polling, rendezvous init, and
orbax checkpoint I/O — so backoff behavior (and its telemetry:
``hvd_retries_total`` / ``hvd_retry_giveups_total`` by call point) is
uniform and testable with a fake clock.

Deliberately NOT used on the data plane: collective execution has its
own negotiation/stall machinery (`ops/eager_runtime.py`); retrying a
collective would desynchronize the negotiated batch order.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple

from . import metrics as _metrics


class Deadline:
    """A monotonic-clock deadline: immune to wall-clock steps (NTP
    slew, manual `date -s`) that broke every `time.time() + timeout`
    loop in the control plane. ``timeout_s=None`` never expires."""

    def __init__(self, timeout_s: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._timeout = timeout_s
        self._t0 = clock()

    def remaining(self) -> float:
        if self._timeout is None:
            return float("inf")
        return self._timeout - (self._clock() - self._t0)

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def elapsed(self) -> float:
        return self._clock() - self._t0


def _default_retryable(exc: BaseException) -> bool:
    """Transport-shaped failures retry; everything else propagates.
    OSError covers ConnectionError/TimeoutError/socket errors and
    urllib's URLError (an OSError subclass)."""
    return isinstance(exc, (OSError, EOFError))


class RetryPolicy:
    """Exponential backoff with jitter and an overall deadline.

    Two jitter disciplines (``jitter=``):

    * ``"bounded"`` — the historical ±``jitter_frac`` symmetric band
      around the exponential delay. Fine for one isolated caller;
      useless against synchronized fleets: after a rendezvous failover
      every host computes the SAME schedule ±25%, so hundreds of
      reconnects land on the root in tight waves (thundering herd).
    * ``"full"`` — AWS-style full jitter: the delay is uniform on
      ``[0, exp_backoff]``, spreading a fleet's retries across the
      whole backoff window. The shared :func:`default_policy` uses
      this (``HOROVOD_RETRY_JITTER=bounded`` restores the old band).

    ``max_elapsed_s`` is a shared cap on TOTAL elapsed time across
    attempts, applied even when no per-call ``deadline_s`` was given —
    the fleet-wide bound that keeps a reconnect storm finite
    (``HOROVOD_RETRY_MAX_ELAPSED``; <=0 disables).

    All time arithmetic runs on an injectable monotonic ``clock`` and
    ``sleep`` so tests exercise the exact schedule with zero real
    waiting (tests/test_faults.py). ``seed`` pins the jitter sequence.
    """

    def __init__(
        self,
        max_attempts: int = 5,
        base_delay_s: float = 0.1,
        max_delay_s: float = 2.0,
        multiplier: float = 2.0,
        jitter_frac: float = 0.25,
        deadline_s: Optional[float] = None,
        retryable: Optional[Callable[[BaseException], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        seed: Optional[int] = None,
        record_metrics: bool = True,
        jitter: str = "bounded",
        max_elapsed_s: Optional[float] = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if jitter not in ("bounded", "full"):
            raise ValueError(f"unknown jitter mode {jitter!r}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter_frac = float(jitter_frac)
        self.deadline_s = deadline_s
        self.retryable = retryable or _default_retryable
        self.clock = clock
        self.sleep = sleep
        self.seed = seed
        self.jitter = jitter
        self.max_elapsed_s = (
            float(max_elapsed_s)
            if max_elapsed_s and max_elapsed_s > 0 else None)
        # record_metrics=False is for callers that may run inside a
        # signal handler (the flight recorder's dump push): the metrics
        # registry locks must never be touched there
        # (elastic/preemption.py explains the deadlock).
        self.record_metrics = record_metrics

    def delay_for_attempt(self, attempt: int,
                          rng: Optional[random.Random] = None) -> float:
        """Backoff before retry number ``attempt`` (1-based): full
        jitter draws uniformly on [0, exp_backoff]; bounded jitters
        symmetrically by ±jitter_frac."""
        d = min(
            self.base_delay_s * (self.multiplier ** (attempt - 1)),
            self.max_delay_s,
        )
        if rng is not None:
            if self.jitter == "full":
                d *= rng.random()
            elif self.jitter_frac:
                d *= 1.0 + self.jitter_frac * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)

    def call(self, fn: Callable, *args, point: str = "",
             retryable: Optional[Callable[[BaseException], bool]] = None,
             **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying retryable failures.

        ``point`` labels the retry/giveup counters in the metrics
        registry (e.g. "http.put"). Gives up — re-raising the last
        failure — after ``max_attempts`` tries or when the monotonic
        ``deadline_s`` budget is spent, whichever comes first.
        """
        is_retryable = retryable or self.retryable
        budget = self.deadline_s
        if self.max_elapsed_s is not None:
            # the shared cap binds even deadline-less callers, and
            # tightens any caller deadline that exceeds it
            budget = (self.max_elapsed_s if budget is None
                      else min(budget, self.max_elapsed_s))
        deadline = Deadline(budget, clock=self.clock)
        rng = random.Random(self.seed)
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if not is_retryable(e):
                    raise
                attempt += 1
                if attempt >= self.max_attempts or deadline.expired():
                    if self.record_metrics:
                        _metrics.record_retry_giveup(point or "unnamed")
                    raise
                delay = self.delay_for_attempt(attempt, rng)
                remaining = deadline.remaining()
                if remaining != float("inf"):
                    if remaining <= 0:
                        if self.record_metrics:
                            _metrics.record_retry_giveup(point or "unnamed")
                        raise
                    delay = min(delay, remaining)
                if self.record_metrics:
                    _metrics.record_retry(point or "unnamed")
                self.sleep(delay)


class Outage:
    """Log-spam suppressor for best-effort periodic loops (metrics
    push, flight-dump shipping): a rendezvous outage produces ONE
    warning when it starts and one recovery line when it ends, not one
    warning per interval. Thread-safe; the boolean flip is the only
    state, so it is also safe to call from signal-handler contexts
    (logging's own handler lock is the caller's concern — the
    preemption handler already accepts that trade, elastic/
    preemption.py)."""

    def __init__(self, logger, what: str):
        self._logger = logger
        self._what = what
        self._down = False
        self._failures = 0

    @property
    def down(self) -> bool:
        return self._down

    @property
    def failures(self) -> int:
        return self._failures

    def failure(self, err: object = None) -> bool:
        """Record one failed attempt. Returns True (and warns) only on
        the first failure of an outage."""
        self._failures += 1
        if self._down:
            return False
        self._down = True
        self._logger.warning(
            "%s failing (%s); suppressing further warnings until it "
            "recovers", self._what, err,
        )
        return True

    def success(self) -> bool:
        """Record one successful attempt; logs the recovery if an
        outage was in progress. Returns True on that transition."""
        if not self._down:
            return False
        self._down = False
        self._logger.info("%s recovered", self._what)
        return True


# ---------------------------------------------------------------------------
# process-wide default policy (env-tunable; the one control-plane call
# sites share so HOROVOD_RETRY_* steers every bootstrap path at once)
# ---------------------------------------------------------------------------

_default_policy: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    """The shared control-plane policy, built once from
    ``HOROVOD_RETRY_MAX_ATTEMPTS`` / ``HOROVOD_RETRY_BASE_DELAY`` /
    ``HOROVOD_RETRY_MAX_DELAY`` (``HVD_TPU_`` prefixes win, as for
    every knob). Worker processes read it before ``hvd.init()``, so it
    parses the env directly instead of going through the Knobs
    snapshot."""
    global _default_policy
    if _default_policy is None:
        from ..core.knobs import _env, _env_float, _env_int

        jitter = (_env("RETRY_JITTER", "full") or "full").strip().lower()
        if jitter not in ("bounded", "full"):
            jitter = "full"
        _default_policy = RetryPolicy(
            max_attempts=_env_int("RETRY_MAX_ATTEMPTS", 5),
            base_delay_s=_env_float("RETRY_BASE_DELAY", 0.1),
            max_delay_s=_env_float("RETRY_MAX_DELAY", 2.0),
            # fleet discipline: full jitter + a shared elapsed cap, so
            # hundreds of hosts reconnecting after a rendezvous
            # failover spread across the backoff window instead of
            # retrying in lockstep (thundering herd on the root)
            jitter=jitter,
            max_elapsed_s=_env_float("RETRY_MAX_ELAPSED", 60.0),
        )
    return _default_policy


def set_default_policy(policy: Optional[RetryPolicy]) -> None:
    """Override the shared policy (tests: zero-sleep policies). Pass
    None to fall back to the env-built default on next use."""
    global _default_policy
    _default_policy = policy


def configure(knobs) -> None:
    """Rebuild the shared policy from a Knobs snapshot — the
    programmatic twin of the env path (hvd.init calls this, so
    ``Knobs(retry_max_attempts=...)`` works like every other knob)."""
    jitter = str(getattr(knobs, "retry_jitter", "full") or "full")
    if jitter not in ("bounded", "full"):
        jitter = "full"
    set_default_policy(RetryPolicy(
        max_attempts=int(getattr(knobs, "retry_max_attempts", 5)),
        base_delay_s=float(getattr(knobs, "retry_base_delay_seconds", 0.1)),
        max_delay_s=float(getattr(knobs, "retry_max_delay_seconds", 2.0)),
        jitter=jitter,
        max_elapsed_s=float(
            getattr(knobs, "retry_max_elapsed_seconds", 60.0)),
    ))
