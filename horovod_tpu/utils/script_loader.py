"""Load a repo example script as a module (examples/ is intentionally NOT
a package — each script is a self-contained file users copy). Shared by
bench.py and the example smoke tests."""

from __future__ import annotations

import importlib.util
import os
import sys


def load_example(name: str):
    """Import examples/<name>.py by path and return the module."""
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    path = os.path.join(repo_root, "examples", f"{name}.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no example script {path}")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod
