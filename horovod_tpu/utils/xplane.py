"""XPlane device-trace parsing: the library behind the unified profiler.

``jax.profiler.trace`` leaves an ``.xplane.pb`` protobuf (the XLA
profiler's XSpace container) under ``<logdir>/plugins/profile/...``.
Until PR 10 the only reader was ``scripts/xplane_summary.py``, which
hard-imported ``tensorflow.tsl.profiler.protobuf.xplane_pb2`` — a whole
TensorFlow just to decode six message types. This module owns the
parsing with **no TF dependency**: a minimal protobuf varint decoder
covering exactly the XSpace schema (``xplane.proto``), cross-checked
field-for-field against the TF proto when TF happens to be installed
(tests/test_prof.py).

Three layers:

* **decode** — :func:`load_xspace` / :func:`find_xplane_pbs`: the raw
  ``XSpace``/``XPlane``/``XLine``/``XEvent`` tree as plain dataclasses;
* **extract** — :func:`op_events`: the device-op timeline flattened to
  ``{name, cat, start_us, dur_us, plane, line}`` dicts, selecting the
  XLA op lines on TPU/GPU device planes (skipping ``Async`` DMA lines,
  which run concurrently and would double-book the device) and, on the
  CPU backend, the ``tf_XLATfrtCpuClient`` execution-thread lines — so
  loopback test worlds exercise the same pipeline as real TPU runs;
* **attribute** — :func:`attribute`: interval arithmetic over the op
  spans → compute / collective / **exposed** collective (collective
  time not overlapped by compute — the wire time a training step
  actually pays) / idle, the measured counterpart of the structural
  overlap-window bound from ops/overlap.py (docs/overlap.md).

``utils/prof.py`` drives this per sampled step; ``scripts/
xplane_summary.py`` and ``scripts/trace_merge.py`` are the CLIs.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import struct
from typing import Dict, Iterable, List, Optional, Tuple


class XPlaneUnavailable(RuntimeError):
    """No parseable ``.xplane.pb`` where one was expected — the message
    says what to do (was the profiler actually started? did the run
    point at the right logdir?)."""


# ---------------------------------------------------------------------------
# minimal protobuf wire-format decoder (varint + length-delimited),
# covering the XSpace schema only. Field numbers transcribed from
# tensorflow/tsl/profiler/protobuf/xplane.proto and verified against the
# TF-generated parser on real captures (tests/test_prof.py).
# ---------------------------------------------------------------------------

def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7
        if s > 70:
            raise ValueError("varint overflow (corrupt xplane?)")


def _fields(buf: bytes, start: int = 0, end: Optional[int] = None):
    """Yield (field_number, wire_type, value) triples of one message."""
    i, stop = start, len(buf) if end is None else end
    while i < stop:
        tag, i = _varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v, i = _varint(buf, i)
        elif wt == 2:  # length-delimited
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:  # fixed32
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:  # fixed64
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fn, wt, v


@dataclasses.dataclass
class XStat:
    metadata_id: int = 0
    double_value: float = 0.0
    uint64_value: int = 0
    int64_value: int = 0
    str_value: str = ""
    bytes_value: bytes = b""
    ref_value: int = 0


@dataclasses.dataclass
class XEvent:
    metadata_id: int = 0
    offset_ps: int = 0
    duration_ps: int = 0
    num_occurrences: int = 0
    stats: List[XStat] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class XLine:
    id: int = 0
    name: str = ""
    display_name: str = ""
    timestamp_ns: int = 0
    duration_ps: int = 0
    events: List[XEvent] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class XMeta:
    id: int = 0
    name: str = ""


@dataclasses.dataclass
class XPlane:
    id: int = 0
    name: str = ""
    lines: List[XLine] = dataclasses.field(default_factory=list)
    event_metadata: Dict[int, XMeta] = dataclasses.field(
        default_factory=dict)
    stat_metadata: Dict[int, XMeta] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class XSpace:
    planes: List[XPlane] = dataclasses.field(default_factory=list)


def _parse_stat(b: bytes) -> XStat:
    st = XStat()
    for fn, _, v in _fields(b):
        if fn == 1:
            st.metadata_id = v
        elif fn == 2:
            st.double_value = struct.unpack("<d", v)[0]
        elif fn == 3:
            st.uint64_value = v
        elif fn == 4:
            st.int64_value = v
        elif fn == 5:
            st.str_value = v.decode("utf-8", "replace")
        elif fn == 6:
            st.bytes_value = v
        elif fn == 7:
            st.ref_value = v
    return st


def _parse_event(b: bytes) -> XEvent:
    ev = XEvent()
    for fn, _, v in _fields(b):
        if fn == 1:
            ev.metadata_id = v
        elif fn == 2:
            ev.offset_ps = v
        elif fn == 3:
            ev.duration_ps = v
        elif fn == 4:
            ev.stats.append(_parse_stat(v))
        elif fn == 5:
            ev.num_occurrences = v
    return ev


def _parse_line(b: bytes) -> XLine:
    ln = XLine()
    for fn, _, v in _fields(b):
        if fn == 1:
            ln.id = v
        elif fn == 2:
            ln.name = v.decode("utf-8", "replace")
        elif fn == 3:
            ln.timestamp_ns = v
        elif fn == 4:
            ln.events.append(_parse_event(v))
        elif fn == 9:
            ln.duration_ps = v
        elif fn == 11:
            ln.display_name = v.decode("utf-8", "replace")
    return ln


def _parse_meta(b: bytes) -> XMeta:
    m = XMeta()
    for fn, _, v in _fields(b):
        if fn == 1:
            m.id = v
        elif fn == 2:
            m.name = v.decode("utf-8", "replace")
    return m


def _parse_map_entry(b: bytes) -> Tuple[int, XMeta]:
    k, m = 0, XMeta()
    for fn, _, v in _fields(b):
        if fn == 1:
            k = v
        elif fn == 2:
            m = _parse_meta(v)
    return k, m


def _parse_plane(b: bytes) -> XPlane:
    p = XPlane()
    for fn, _, v in _fields(b):
        if fn == 1:
            p.id = v
        elif fn == 2:
            p.name = v.decode("utf-8", "replace")
        elif fn == 3:
            p.lines.append(_parse_line(v))
        elif fn == 4:
            k, m = _parse_map_entry(v)
            p.event_metadata[k] = m
        elif fn == 5:
            k, m = _parse_map_entry(v)
            p.stat_metadata[k] = m
    return p


def parse_xspace(data: bytes) -> XSpace:
    """Decode serialized XSpace bytes (the ``.xplane.pb`` content)."""
    xs = XSpace()
    for fn, _, v in _fields(data):
        if fn == 1:
            xs.planes.append(_parse_plane(v))
    return xs


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def find_xplane_pbs(logdir: str) -> List[str]:
    """All ``.xplane.pb`` files under a profiler logdir (sorted — the
    last is the most recent capture)."""
    direct = sorted(glob.glob(
        os.path.join(logdir, "plugins/profile/*/*.xplane.pb")))
    if direct:
        return direct
    return sorted(glob.glob(
        os.path.join(logdir, "**", "*.xplane.pb"), recursive=True))


def load_xspace(path: str) -> Tuple[XSpace, str]:
    """(XSpace, pb_path) from a profiler logdir or a direct ``.pb``
    path. Raises :class:`XPlaneUnavailable` with an actionable message
    when nothing parseable is there — the graceful replacement for the
    old hard ``tensorflow.tsl`` import chain."""
    if os.path.isfile(path):
        pb = path
    else:
        pbs = find_xplane_pbs(path)
        if not pbs:
            raise XPlaneUnavailable(
                f"no .xplane.pb under {path!r} — is this a "
                "jax.profiler.trace logdir, and did the traced program "
                "actually execute device work inside the trace window?"
            )
        pb = pbs[-1]
    try:
        with open(pb, "rb") as f:
            data = f.read()
        return parse_xspace(data), pb
    except (OSError, ValueError, IndexError) as e:
        raise XPlaneUnavailable(f"cannot parse {pb!r}: {e}") from e


# ---------------------------------------------------------------------------
# op-event extraction
# ---------------------------------------------------------------------------

#: substrings marking a cross-device collective / communication HLO.
#: Covers both HLO op names (all-reduce.3, all-gather-start) and the
#: profiler's category strings.
_COLLECTIVE_MARKERS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective", "send", "recv", "psum",
    "allreduce", "allgather", "alltoall",
)

#: event names on the CPU client lines that are bookkeeping, not HLO ops
_NON_OP_PREFIXES = ("ThreadpoolListener", "$", "EigenDevice")


def is_collective(name: str) -> bool:
    """Does this HLO op/category name move bytes between ranks?"""
    low = name.lower()
    return any(m in low for m in _COLLECTIVE_MARKERS)


def is_device_plane(plane_name: str) -> bool:
    return "TPU" in plane_name or "GPU" in plane_name or (
        "Device" in plane_name and "Host" not in plane_name)


def _is_op_line(plane: XPlane, line: XLine) -> bool:
    lname = line.name or line.display_name
    if is_device_plane(plane.name):
        # TPU/GPU device planes: only the "XLA Ops" lines carry the
        # per-HLO timeline (summarize_plane's long-standing rule).
        # "XLA Modules" / "XLA TraceMe" / framework lines span whole
        # steps and would book the entire window as one giant op.
        return "XLA Ops" in lname or lname == "Ops"
    # CPU backend: XLA executes on client threads of the host plane
    return lname.startswith("tf_XLATfrtCpuClient")


def _event_category(name: str, ev: XEvent,
                    stmeta: Dict[int, XMeta]) -> str:
    """The profiler's category stat when present (LAST match wins —
    an op carrying both 'equation' and 'hlo_category' categorizes by
    the category, not the einsum string), else derived from the HLO op
    name. One rule for both attribution and summary tables."""
    cat = None
    for st in ev.stats:
        sname = stmeta.get(st.metadata_id)
        if sname and sname.name in ("equation", "hlo_category",
                                    "category"):
            cat = st.str_value
    if cat is not None:
        return cat
    return name.split(".")[0].split("-start")[0]


def op_events(xspace: XSpace,
              include_async: bool = False) -> List[dict]:
    """Flatten the device-op timeline: one dict per executed HLO op,
    with absolute microsecond start times (``line.timestamp_ns`` +
    event offset — the profiler's own session clock)."""
    out: List[dict] = []
    for plane in xspace.planes:
        evmeta = plane.event_metadata
        stmeta = plane.stat_metadata
        for line in plane.lines:
            lname = line.name or line.display_name
            if not _is_op_line(plane, line):
                continue
            async_line = "Async" in lname
            if async_line and not include_async:
                # overlapped DMA runs concurrently with the sync op
                # line; counting both would double-book the device
                continue
            base_us = line.timestamp_ns / 1e3
            for ev in line.events:
                md = evmeta.get(ev.metadata_id)
                name = md.name if md else str(ev.metadata_id)
                if ev.duration_ps <= 0:
                    continue
                if name.startswith(_NON_OP_PREFIXES):
                    continue
                cat = _event_category(name, ev, stmeta)
                out.append({
                    "name": name,
                    "cat": cat,
                    "start_us": base_us + ev.offset_ps / 1e6,
                    "dur_us": ev.duration_ps / 1e6,
                    "plane": plane.name,
                    "line": lname,
                    "async": async_line,
                    "collective": is_collective(name) or is_collective(
                        cat),
                })
    out.sort(key=lambda e: e["start_us"])
    return out


# ---------------------------------------------------------------------------
# interval arithmetic + attribution
# ---------------------------------------------------------------------------

def merge_intervals(
        spans: Iterable[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Union of (start, end) intervals, sorted and coalesced."""
    spans = sorted((s, e) for s, e in spans if e > s)
    if not spans:
        return []
    out = [spans[0]]
    for s, e in spans[1:]:
        ls, le = out[-1]
        if s > le:
            out.append((s, e))
        else:
            out[-1] = (ls, max(le, e))
    return out


def _total(intervals: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in intervals)


def _intersect(a: List[Tuple[float, float]],
               b: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out.append((s, e))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def attribute(ops: List[dict],
              host_wall_us: Optional[float] = None) -> dict:
    """Attribute a sampled window into compute / collective / exposed
    collective / idle / host-gap buckets.

    * ``compute_us`` — union of non-collective device op time;
    * ``collective_us`` — union of collective op time;
    * ``exposed_collective_us`` — collective time **not** overlapped by
      compute: the wire time the step actually pays (the measured twin
      of ops/overlap.py's structural window — a perfect schedule drives
      this to ~0);
    * ``idle_us`` — gaps in the device timeline inside the window;
    * ``host_gap_us`` — host wall time beyond the device window (host
      input pipeline / dispatch latency), only when ``host_wall_us``
      is given.

    Fractions normalize by the host wall when known, else device wall.
    ``measured_overlap_frac`` is the overlapped share of collective
    time (1.0 = fully hidden; None when the window has no collectives).
    """
    compute = merge_intervals(
        (e["start_us"], e["start_us"] + e["dur_us"])
        for e in ops if not e["collective"])
    coll = merge_intervals(
        (e["start_us"], e["start_us"] + e["dur_us"])
        for e in ops if e["collective"])
    busy = merge_intervals(compute + coll)
    wall = (busy[-1][1] - busy[0][0]) if busy else 0.0
    compute_us = _total(compute)
    coll_us = _total(coll)
    overlapped_us = _total(_intersect(compute, coll))
    exposed_us = coll_us - overlapped_us
    idle_us = max(wall - _total(busy), 0.0)
    host_gap_us = None
    denom = wall
    if host_wall_us is not None:
        host_gap_us = max(host_wall_us - wall, 0.0)
        denom = max(host_wall_us, wall)
    denom = denom or 1.0
    out = {
        "ops": len(ops),
        "device_wall_us": round(wall, 3),
        "compute_us": round(compute_us, 3),
        "collective_us": round(coll_us, 3),
        "exposed_collective_us": round(exposed_us, 3),
        "idle_us": round(idle_us, 3),
        "compute_frac": round(compute_us / denom, 6),
        "exposed_wire_frac": round(exposed_us / denom, 6),
        "idle_frac": round(idle_us / denom, 6),
        "measured_overlap_frac": (
            round(overlapped_us / coll_us, 6) if coll_us > 0 else None
        ),
    }
    if host_gap_us is not None:
        out["host_wall_us"] = round(host_wall_us, 3)
        out["host_gap_us"] = round(host_gap_us, 3)
        out["host_gap_frac"] = round(host_gap_us / denom, 6)
    return out


def attribute_by_plane(ops: List[dict],
                       host_wall_us: Optional[float] = None) -> dict:
    """:func:`attribute`, but computed per device plane and then
    aggregated. One capture on a multi-chip host holds one plane per
    chip; if their op spans shared a single interval axis, chip A's
    compute would mask chip B's exposed collective wait — exactly the
    straggler signal this instrument exists to surface — so each plane
    is attributed on its own axis first. Per-plane fracs average with
    equal weight (each chip owns the same wall);
    ``measured_overlap_frac`` is the overlapped share of total
    collective microseconds across chips. A single-plane capture
    returns :func:`attribute`'s dict unchanged."""
    planes: Dict[str, List[dict]] = {}
    for e in ops:
        planes.setdefault(e["plane"], []).append(e)
    if len(planes) <= 1:
        return attribute(ops, host_wall_us=host_wall_us)
    per = {name: attribute(evs, host_wall_us=host_wall_us)
           for name, evs in sorted(planes.items())}
    vals = list(per.values())
    n = len(vals)
    coll_us = sum(a["collective_us"] for a in vals)
    overlapped_us = sum(
        a["collective_us"] - a["exposed_collective_us"] for a in vals)
    out = {
        "ops": len(ops),
        "planes": n,
        "device_wall_us": round(max(a["device_wall_us"] for a in vals), 3),
        "compute_us": round(sum(a["compute_us"] for a in vals), 3),
        "collective_us": round(coll_us, 3),
        "exposed_collective_us": round(
            sum(a["exposed_collective_us"] for a in vals), 3),
        "idle_us": round(sum(a["idle_us"] for a in vals), 3),
        "compute_frac": round(
            sum(a["compute_frac"] for a in vals) / n, 6),
        "exposed_wire_frac": round(
            sum(a["exposed_wire_frac"] for a in vals) / n, 6),
        "idle_frac": round(sum(a["idle_frac"] for a in vals) / n, 6),
        "measured_overlap_frac": (
            round(overlapped_us / coll_us, 6) if coll_us > 0 else None),
        "per_plane": {
            name: {k: a[k] for k in (
                "device_wall_us", "compute_frac", "exposed_wire_frac",
                "idle_frac", "measured_overlap_frac")}
            for name, a in per.items()},
    }
    if host_wall_us is not None:
        out["host_wall_us"] = round(host_wall_us, 3)
        out["host_gap_us"] = round(
            sum(a.get("host_gap_us", 0.0) for a in vals) / n, 3)
        out["host_gap_frac"] = round(
            sum(a.get("host_gap_frac", 0.0) for a in vals) / n, 6)
    return out


# ---------------------------------------------------------------------------
# per-plane summary (the xplane_summary.py engine)
# ---------------------------------------------------------------------------

def summarize_plane(plane: XPlane) -> Optional[dict]:
    """Busy/idle + by-category/by-op totals for one device plane
    (identical accounting to the pre-PR-10 xplane_summary.py)."""
    by_op: Dict[str, float] = {}
    by_cat: Dict[str, float] = {}
    occur: Dict[str, int] = {}
    spans: List[Tuple[int, int]] = []
    evmeta, stmeta = plane.event_metadata, plane.stat_metadata
    for line in plane.lines:
        lname = line.name or line.display_name
        if not _is_op_line(plane, line) or "Async" in lname:
            # same op-line rule as attribution; Async DMA runs
            # CONCURRENTLY with the sync op line and counting both
            # double-books the device
            continue
        for ev in line.events:
            md = evmeta.get(ev.metadata_id)
            name = md.name if md else str(ev.metadata_id)
            dur = ev.duration_ps / 1e6  # -> us
            cat = _event_category(name, ev, stmeta)
            by_op[name] = by_op.get(name, 0.0) + dur
            by_cat[cat] = by_cat.get(cat, 0.0) + dur
            occur[name] = occur.get(name, 0) + 1
            spans.append((ev.offset_ps, ev.offset_ps + ev.duration_ps))
    if not spans:
        return None
    merged = merge_intervals(spans)
    total_busy = _total(merged)
    wall = max(e for _, e in spans) - min(s for s, _ in spans)
    return {
        "plane": plane.name,
        "wall_us": wall / 1e6,
        "busy_us": total_busy / 1e6,
        "idle_frac": 1.0 - total_busy / max(wall, 1),
        "by_cat": by_cat,
        "by_op": by_op,
        "occur": occur,
    }


def summarize(path: str) -> List[dict]:
    """Per-device-plane summaries for a logdir/pb (empty when the
    capture has no device op lines — e.g. a CPU-only capture, whose op
    events still flow through :func:`op_events`)."""
    xs, _ = load_xspace(path)
    out = []
    for plane in xs.planes:
        if not is_device_plane(plane.name):
            continue
        s = summarize_plane(plane)
        if s is not None:
            out.append(s)
    return out
