"""Cross-rank flight recorder: post-hoc forensics for distributed stalls.

Horovod's signature debugging aid is the coordinator's stall check that
names *which ranks have not submitted which tensors*
(stall_inspector.cc, Sergeev & Del Balso 2018). Our timeline
(utils/timeline.py) and metrics (utils/metrics.py) are per-process:
when a world-N job hangs, each rank holds only its own view, and the
stall watchdog (PR 2) aborts with a message that cannot say *who* is
late. This module closes that gap with an aircraft-style black box:

* a **bounded ring buffer** of control-plane events — collective
  enqueue / negotiation response / exec begin+end, fast-path plan
  activation/invalidation, elastic transitions, retry/fault firings,
  serving dispatch — each stamped with rank, monotonic + wall time and
  a per-rank sequence number. Recording is lock-light: one enabled
  check, a ``deque.append`` (atomic under the GIL) and an
  ``itertools.count`` bump — no lock on the hot path, and a single
  predicted branch when ``HOROVOD_FLIGHT_RECORDER=0`` (the same no-op
  discipline as utils/metrics.py, asserted by tests/test_flight.py);
* **dump triggers**: the stall watchdog (before it raises
  ``HorovodInternalError``), executor errors, preemption SIGTERM,
  ``SIGUSR2`` on demand, and an excepthook for crash-at-exit. Dumps
  write rank-local JSONL under ``HOROVOD_FLIGHT_DIR`` and ship to the
  driver via ``PUT /flight/<rank>`` on the rendezvous HTTP server
  (runner/http/http_server.py), with a ``GET /clock`` ping so every
  dump carries its clock offset to the driver for cross-rank
  alignment;
* **straggler attribution**: :func:`straggler_report` cross-references
  peers' last dumps (when available) against the aborting rank's
  pending tensors, so the stall-abort message names the suspected
  straggler ranks and the tensors they have not submitted — the
  distributed form of the reference's stall warning.

``scripts/flight_analyze.py`` merges per-rank dumps (clock-offset
aligned) into a straggler / critical-path report;
``scripts/flight_check.py`` is the world-2 loopback smoke gate.

Signal-handler safety: every function a signal handler may reach
(``record``, ``dump``) avoids the metrics/StepStats locks entirely
(see elastic/preemption.py for why) — the only lock here serializes
whole dumps against each other, and it is never held by ``record``.
"""

from __future__ import annotations

import itertools
import json
import os
import signal as _signal
import sys
import tempfile
import threading
import time
import urllib.request
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# module state (the no-op fast path)
# ---------------------------------------------------------------------------

DEFAULT_CAPACITY = 4096

_enabled = False
_configured = False  # True when configure() (hvd.init) enabled us
_events: "deque" = deque(maxlen=DEFAULT_CAPACITY)
_seq = itertools.count()
_rank = -1
_sink: Optional[Tuple[str, int]] = None  # rendezvous (addr, port)
_dir = ""
_dump_lock = threading.Lock()
_dump_count = 0
_handlers_installed = False
_prev_excepthook = None
_prev_sigusr2 = None
# the autotuner's most recent agreed pin (ops/autotune.py): kept out of
# the ring so it survives ring wraparound — an autopsy must show the
# tuned configuration the crashed step was compiled under even when
# thousands of later events displaced the pin event itself
_last_autotune: Optional[dict] = None

FLIGHT_SCOPE = "flight"  # rendezvous KV scope dumps land in


def _default_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "hvd_flight")


def enabled() -> bool:
    """Whether the recorder is recording. Hot paths with per-event
    assembly work (building a names list) should gate on this to skip
    the assembly too; plain record() calls need no guard."""
    return _enabled


def enable(capacity: Optional[int] = None) -> None:
    global _enabled, _events
    if capacity is not None and capacity != _events.maxlen:
        _events = deque(_events, maxlen=max(int(capacity), 16))
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def set_rank(rank: int) -> None:
    global _rank
    _rank = int(rank)


def rank() -> int:
    return _rank


def set_sink(addr: Optional[str], port: int = 0) -> None:
    """Where dumps ship: the rendezvous/KV HTTP server. ``None``
    disables shipping (dumps stay rank-local files)."""
    global _sink
    _sink = (addr, int(port)) if addr and port else None


def sink() -> Optional[Tuple[str, int]]:
    return _sink


def dump_dir() -> str:
    return _dir or _default_dir()


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def record(kind: str, name: str = "", **detail) -> None:
    """Append one event to the ring. Lock-free: a tuple build plus a
    ``deque.append`` with ``maxlen`` (old events fall off the far end).
    Safe from any thread and from signal handlers."""
    if not _enabled:
        return
    if kind == "autotune" and name in ("pin", "final", "warm_start"):
        global _last_autotune
        _last_autotune = {"name": name, **detail}
    _events.append((
        next(_seq), time.monotonic(), time.time(), kind, name,
        detail or None,
    ))


def last_autotune() -> Optional[dict]:
    """The most recent autotune pin recorded (None before any)."""
    return _last_autotune


def snapshot() -> List[tuple]:
    """A point-in-time copy of the ring (oldest first)."""
    return list(_events)


def event_count() -> int:
    return len(_events)


def clear() -> None:
    _events.clear()


# ---------------------------------------------------------------------------
# clock alignment + dumping
# ---------------------------------------------------------------------------

def _clock_probe() -> dict:
    """One ping to the sink's ``GET /clock``: returns the offset that
    maps this rank's wall clock onto the driver's
    (``t_driver ≈ t_wall + clock_offset_s``) plus the ping RTT, or {}
    when no sink is reachable. flight_analyze uses the offsets to merge
    per-rank dumps onto one time axis."""
    if _sink is None:
        return {}
    addr, port = _sink
    try:
        t0m = time.monotonic()
        t0w = time.time()
        with urllib.request.urlopen(
                f"http://{addr}:{port}/clock", timeout=2.0) as resp:
            body = json.loads(resp.read())
        rtt = time.monotonic() - t0m
        server_t = float(body["time_unix"])
        # the server stamped mid-flight; our best wall-clock estimate of
        # that instant is request start + rtt/2
        return {
            "clock_offset_s": server_t - (t0w + rtt / 2.0),
            "clock_rtt_s": rtt,
        }
    except Exception:
        return {}


def clock_probe() -> dict:
    """Public form of the dump-time clock probe (utils/prof.py sidecar
    metadata, analyzer tooling): the offset mapping this rank's wall
    clock onto the driver's, or {} with no reachable sink."""
    return _clock_probe()


_push_policy = None
_push_outage = None


def _push_degradation():
    """Lazy bounded policy + outage tracker for dump shipping. The
    policy is built with ``record_metrics=False``: pushes run from
    abort paths and signal handlers, where the shared RetryPolicy's
    metrics recording (registry locks) must not be touched. The outage
    tracker turns a rendezvous outage into ONE warning, not one per
    dump attempt."""
    global _push_policy, _push_outage
    if _push_policy is None:
        import logging

        from . import retry as _retry

        _push_policy = _retry.RetryPolicy(
            max_attempts=2, base_delay_s=0.1, max_delay_s=0.25,
            record_metrics=False)
        _push_outage = _retry.Outage(
            logging.getLogger("horovod_tpu.flight"),
            "flight-dump push to the rendezvous store")
    return _push_policy, _push_outage


def _push(payload: bytes) -> bool:
    """Ship a dump to ``PUT /flight/<rank>`` on the sink, under a
    bounded metrics-free RetryPolicy (one quick retry) so a dead
    driver costs at most two short timeouts, with log-spam suppression
    across dumps (one warning per outage)."""
    if _sink is None:
        return False
    addr, port = _sink
    policy, outage = _push_degradation()

    def _do() -> None:
        req = urllib.request.Request(
            f"http://{addr}:{port}/{FLIGHT_SCOPE}/{_rank}",
            data=payload, method="PUT",
        )
        with urllib.request.urlopen(req, timeout=2.0):
            pass

    try:
        policy.call(_do, point="flight.push")
        outage.success()
        return True
    except Exception as e:
        outage.failure(e)
        return False


def dump(reason: str = "manual") -> Optional[str]:
    """Serialize the ring to rank-local JSONL and ship it to the driver.

    Line 1 is a header (rank, reason, wall/monotonic stamps, clock
    offset to the driver, event count); each further line is one event.
    Returns the local file path (None when nothing could be written —
    the push may still have succeeded)."""
    if not _enabled:
        return None
    # non-blocking: a signal handler (SIGUSR2, preemption SIGTERM) runs
    # on the main thread and may interrupt a frame that already holds
    # this non-reentrant lock mid-dump — blocking here would deadlock
    # the handler (and, for preemption, eat the whole grace window).
    # A dump is best-effort; the one in flight carries the same ring.
    if not _dump_lock.acquire(blocking=False):
        return None
    try:
        global _dump_count
        _dump_count += 1
        events = snapshot()
        header = {
            "flight_header": 1,
            "rank": _rank,
            "reason": reason,
            "dump": _dump_count,
            "time_unix": time.time(),
            "monotonic": time.monotonic(),
            "events": len(events),
        }
        if _last_autotune is not None:
            header["autotune"] = _last_autotune
        header.update(_clock_probe())
        lines = [json.dumps(header)]
        for seq, t_mono, t_wall, kind, name, detail in events:
            ev = {
                "seq": seq,
                "t_mono": round(t_mono, 6),
                "t_wall": round(t_wall, 6),
                "kind": kind,
                "name": name,
            }
            if detail:
                for k, v in detail.items():
                    ev.setdefault(k, v)
            lines.append(json.dumps(ev, default=str))
        payload = ("\n".join(lines) + "\n").encode()
        path: Optional[str] = None
        try:
            d = dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"flight_rank{_rank}.jsonl")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except Exception:
            path = None
        _push(payload)
        return path
    finally:
        _dump_lock.release()


def dump_count() -> int:
    return _dump_count


# one rate-limit slot per firing rule: a rule that flaps must not turn
# the recorder into a dump firehose, but two DIFFERENT rules firing
# back-to-back each deserve their forensic snapshot
_anomaly_last: Dict[str, float] = {}
ANOMALY_DUMP_MIN_INTERVAL_S = 30.0


def anomaly_dump(rule: str,
                 min_interval_s: float = ANOMALY_DUMP_MIN_INTERVAL_S,
                 ) -> Optional[str]:
    """Anomaly-triggered forensic dump (the health monitor calls this
    when an SLO rule fires): a normal ``dump`` with reason
    ``anomaly:<rule>``, rate-limited per rule."""
    if not _enabled:
        return None
    now = time.monotonic()
    last = _anomaly_last.get(rule, 0.0)
    if now - last < min_interval_s:
        return None
    _anomaly_last[rule] = now
    return dump(f"anomaly:{rule}")


# ---------------------------------------------------------------------------
# cross-rank straggler attribution
# ---------------------------------------------------------------------------

def parse_dump(text: str) -> Tuple[dict, List[dict]]:
    """(header, events) from a dump's JSONL text. Unparseable lines are
    skipped — a truncated dump should still yield what it carries."""
    header: dict = {}
    events: List[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("flight_header"):
            header = obj
        else:
            events.append(obj)
    return header, events


def fetch_peer_dump(peer_rank: int) -> Optional[Tuple[dict, List[dict]]]:
    """The peer's last dump from the sink (``GET /flight/<rank>``), or
    None when the sink has none / is unreachable."""
    if _sink is None:
        return None
    addr, port = _sink
    try:
        with urllib.request.urlopen(
                f"http://{addr}:{port}/{FLIGHT_SCOPE}/{peer_rank}",
                timeout=2.0) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception:
        return None
    return parse_dump(text)


def _enqueue_counts(names: Sequence[str], events) -> Dict[str, int]:
    """Per-name enqueue counts restricted to ``names``. Counts — not
    sets — so a tensor enqueued on every previous step but missing from
    the current one still reads as 'behind' (the peer's count lags)."""
    want = set(names)
    counts: Dict[str, int] = {}
    for ev in events:
        if isinstance(ev, dict):
            kind, name = ev.get("kind"), ev.get("name")
        else:
            kind, name = ev[3], ev[4]
        if kind == "enqueue" and name in want:
            counts[name] = counts.get(name, 0) + 1
    return counts


def _fmt_names(names: Sequence[str], limit: int = 6) -> str:
    names = list(names)
    head = ", ".join(names[:limit])
    if len(names) > limit:
        head += f" (+{len(names) - limit} more)"
    return head


def straggler_report(pending_names: Sequence[str], world_size: int,
                     my_rank: Optional[int] = None,
                     reason: str = "stall_abort") -> str:
    """Attribute a stall: dump our own ring (so the driver and peers
    can see it), fetch every peer's last dump from the sink, and name
    the ranks whose enqueue counts lag ours on the tensors we are
    still waiting for. Returns a one-line human report ('' when the
    recorder is off)."""
    if not _enabled:
        return ""
    my_rank = _rank if my_rank is None else my_rank
    pending = sorted(set(pending_names))
    path = dump(reason)
    parts: List[str] = []
    stragglers: List[Tuple[int, List[str]]] = []
    unavailable: List[int] = []
    fetched = 0
    if pending and _sink is not None and world_size > 1:
        mine = _enqueue_counts(pending, snapshot())
        # total wall budget on the peer sweep: a stall is exactly when
        # the sink is most likely wedged, and elastic recovery is
        # blocked until this report's HorovodInternalError raises — at
        # large world sizes N serial 2s timeouts would dwarf the stall
        # window itself. Unfetched ranks read as unavailable.
        fetch_deadline = time.monotonic() + 8.0
        for r in range(world_size):
            if r == my_rank:
                continue
            if time.monotonic() >= fetch_deadline:
                unavailable.append(r)
                continue
            peer = fetch_peer_dump(r)
            if peer is None:
                unavailable.append(r)
                continue
            fetched += 1
            theirs = _enqueue_counts(pending, peer[1])
            behind = [
                n for n in pending
                if theirs.get(n, 0) < mine.get(n, 0)
            ]
            if behind:
                stragglers.append((r, behind))
    if stragglers:
        parts.append(
            "suspected straggler "
            + ("rank" if len(stragglers) == 1 else "ranks")
            + " (per peer flight dumps): "
            + "; ".join(
                f"rank {r} has not submitted {_fmt_names(b)}"
                for r, b in stragglers
            )
        )
    if pending and world_size > 1:
        if _sink is None:
            parts.append("no flight sink configured to fetch peer dumps")
        elif unavailable:
            # a dump-less peer is itself a forensic signal (it may be
            # the dead rank) — report it whether or not some other
            # peer's counts already lag
            parts.append(
                "no peer flight dumps available to attribute the stall"
                if not fetched else
                f"no dumps from ranks {unavailable}"
            )
        elif not stragglers and fetched:
            parts.append("peer dumps show no enqueue lag")
    if pending:
        parts.append(f"locally pending: {_fmt_names(pending)}")
    if path:
        parts.append(f"flight dump: {path}")
    return "; ".join(parts)


# ---------------------------------------------------------------------------
# trigger handlers (SIGUSR2 on demand, crash excepthook)
# ---------------------------------------------------------------------------

def _sigusr2(signum, frame) -> None:
    record("signal_dump", signum=signum)
    dump("sigusr2")
    # chain: an application's own SIGUSR2 tooling (stack dumps, config
    # reload) must keep firing — the recorder defaults ON and must not
    # silently eat a signal the app was using
    prev = _prev_sigusr2
    if callable(prev):
        prev(signum, frame)


def _excepthook(exc_type, exc, tb):
    # a crashing worker leaves its last control-plane moments behind —
    # the dump ships before the interpreter dies (atexit would be too
    # late for os._exit paths, too broad for clean exits)
    try:
        record("crash", exc_type.__name__, error=str(exc)[:200])
        dump("crash")
    except Exception:
        pass
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def install_handlers() -> bool:
    """Arm SIGUSR2 (dump on demand) and the crash excepthook.
    Idempotent; returns False when signal handlers cannot be installed
    from this thread (the excepthook is still chained)."""
    global _handlers_installed, _prev_excepthook, _prev_sigusr2
    if _handlers_installed:
        return True
    if _prev_excepthook is None and sys.excepthook is not _excepthook:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
    try:
        prev = _signal.signal(_signal.SIGUSR2, _sigusr2)
    except (ValueError, AttributeError, OSError):
        return False  # not the main thread / no SIGUSR2 on platform
    if prev is not _sigusr2 and prev not in (
            _signal.SIG_IGN, _signal.SIG_DFL, None):
        _prev_sigusr2 = prev
    _handlers_installed = True
    return True


# ---------------------------------------------------------------------------
# lifecycle (core/basics.py calls configure/on_shutdown)
# ---------------------------------------------------------------------------

def _env_first(*names: str) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return None


def configure(knobs=None, *, enabled_override: Optional[bool] = None,
              rank: Optional[int] = None,
              sink_addr: Optional[str] = None,
              sink_port: Optional[int] = None,
              directory: Optional[str] = None,
              capacity: Optional[int] = None,
              handlers: Optional[bool] = None) -> None:
    """Arm the recorder from the knob snapshot (hvd.init) or explicit
    overrides (tests, check scripts). Rank defaults to the launcher's
    HOROVOD_RANK env; the sink defaults to the launcher-published
    rendezvous address, so worker dumps reach the driver with zero
    extra wiring."""
    global _configured, _dir
    want = bool(getattr(knobs, "flight_recorder", True)
                if enabled_override is None else enabled_override)
    if rank is not None:
        set_rank(rank)
    elif _rank < 0:
        env_rank = _env_first("HVD_TPU_RANK", "HOROVOD_RANK")
        if env_rank is not None:
            try:
                set_rank(int(env_rank))
            except ValueError:
                pass
    if sink_addr is not None:
        set_sink(sink_addr, sink_port or 0)
    elif _sink is None:
        # prefer the pod relay when one is configured: dumps land
        # pod-locally and the relay batches them to the root with the
        # other control-plane pushes (multipod/relay.py); the root's
        # relay-batch unpack stamps FLIGHT_META receipts exactly as a
        # direct PUT would. push_endpoint() resolves the relay as a
        # PAIR (addr+port both set, else the rendezvous pair) —
        # independent per-var fallbacks could mix a relay address with
        # the rendezvous port and lose every dump.
        endpoint = None
        try:
            from ..multipod.relay import push_endpoint

            endpoint = push_endpoint()
        except Exception:
            pass
        if endpoint is not None:
            set_sink(endpoint[0], endpoint[1])
    if directory is not None:
        _dir = directory
    elif not _dir:
        _dir = getattr(knobs, "flight_dir", "") or ""
    cap = capacity if capacity is not None else getattr(
        knobs, "flight_capacity", None)
    if not want:
        disable()
        return
    _configured = True
    enable(cap)
    if handlers if handlers is not None else True:
        install_handlers()


def on_shutdown() -> None:
    """hvd.shutdown(): stop recording if configure() was what enabled
    us. Handlers stay installed (they no-op while disabled); the ring
    keeps its contents for post-shutdown inspection."""
    global _configured
    if _configured:
        _configured = False
        disable()


def reset() -> None:
    """Test hook: clear events/counters and return to the disabled,
    unconfigured state."""
    global _configured, _dump_count, _rank, _sink, _dir, _seq
    global _push_policy, _push_outage, _last_autotune
    _push_policy = _push_outage = None
    _anomaly_last.clear()
    disable()
    _configured = False
    _events.clear()
    _seq = itertools.count()
    _dump_count = 0
    _rank = -1
    _sink = None
    _dir = ""
    _last_autotune = None
