"""Unified runtime telemetry: metrics registry + per-step stats.

The reference's only observability surfaces are offline — the Chrome-trace
timeline (timeline.cc) and the stall inspector's log warnings
(stall_inspector.cc). This module is the live counterpart: a thread-safe
registry of counters, gauges and fixed-bucket histograms that the hot
paths (ops/collectives.py, ops/eager_runtime.py, ops/fusion.py,
optim/distributed.py, elastic transitions, the native runtime's
cycle/cache stats) feed while training runs, exposed as

  * Prometheus text format on ``GET /metrics`` — mounted on the
    rendezvous/KV HTTP server (runner/http/http_server.py) and, with
    ``HOROVOD_METRICS_PORT``, on a standalone per-worker endpoint;
  * an optional JSON-lines per-step log (``HOROVOD_TPU_METRICS_FILE``)
    rendered by ``scripts/metrics_summary.py``.

Cost discipline: everything is OFF by default and every hot-path record
function begins with a module-level ``if not _enabled: return`` — the
whole subsystem costs one predicted-not-taken branch + a function call
(<1 µs) per site when disabled (tests/test_metrics.py asserts this).
Enabled, updates are dict lookups + float adds under per-family locks;
no I/O happens on the hot path (the JSONL writer runs at step
boundaries, the HTTP server in its own thread).
"""

from __future__ import annotations

import bisect
import contextlib
import json
import os
import re
import threading
import time
import urllib.request
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from . import flight as _flight

# ---------------------------------------------------------------------------
# module-level enable gate (the no-op fast path)
# ---------------------------------------------------------------------------

_enabled = False
_configured = False  # True when init()/configure() turned metrics on


def enabled() -> bool:
    """Whether telemetry is recording. Hot paths check this themselves;
    callers composing larger records (e.g. a stats dict) should gate on
    it to skip the assembly work too."""
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

# Latency histogram buckets (seconds): 50µs .. 10s, roughly 1-2.5-5 per
# decade — wide enough for host-side negotiation AND whole-step times.
LATENCY_BUCKETS: Tuple[float, ...] = (
    50e-6, 100e-6, 250e-6, 500e-6, 1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3,
    50e-3, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Fill-ratio buckets (dimensionless 0..1] for fusion-buffer utilization.
RATIO_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0,
)


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Counter:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self.value = 0.0
        self._lock = lock

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v


class _Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count", "_lock")

    def __init__(self, buckets: Sequence[float],
                 lock: threading.Lock) -> None:
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, v: float) -> None:
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, v)] += 1
            self.sum += v
            self.count += 1


class MetricFamily:
    """One named metric with a fixed label set; children keyed by the
    label-value tuple (the Prometheus data model)."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets) if buckets else LATENCY_BUCKETS
        self._lock = threading.Lock()
        self._children: Dict[tuple, object] = {}

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {key}"
            )
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    # children share the family lock: updates are
                    # read-modify-write sequences (value += v, bucket +
                    # sum + count), so concurrent recorders would lose
                    # increments without it
                    child = {
                        "counter": lambda: _Counter(self._lock),
                        "gauge": lambda: _Gauge(self._lock),
                        "histogram": lambda: _Histogram(
                            self._buckets, self._lock),
                    }[self.kind]()
                    self._children[key] = child
        return child

    # no-label conveniences
    def inc(self, v: float = 1.0) -> None:
        self.labels().inc(v)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    # -- rendering ---------------------------------------------------------

    def _labelstr(self, key: tuple, extra: str = "") -> str:
        parts = [
            f'{n}="{_escape_label(v)}"'
            for n, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            if self.kind in ("counter", "gauge"):
                lines.append(
                    f"{self.name}{self._labelstr(key)} {_fmt(child.value)}"
                )
            else:
                with self._lock:  # consistent (counts, sum, count) triple
                    counts = list(child.counts)
                    hsum, hcount = child.sum, child.count
                cum = 0
                for b, c in zip(child.buckets, counts):
                    cum += c
                    le = 'le="' + _fmt(b) + '"'
                    lines.append(
                        f"{self.name}_bucket{self._labelstr(key, le)} {cum}"
                    )
                cum += counts[-1]
                inf_labels = self._labelstr(key, 'le="+Inf"')
                lines.append(f"{self.name}_bucket{inf_labels} {cum}")
                lines.append(
                    f"{self.name}_sum{self._labelstr(key)} {_fmt(hsum)}"
                )
                lines.append(
                    f"{self.name}_count{self._labelstr(key)} {hcount}"
                )
        return lines

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            k = ",".join(key)
            if self.kind in ("counter", "gauge"):
                out[k] = child.value
            else:
                with self._lock:
                    out[k] = {"count": child.count, "sum": child.sum}
        return out


class MetricsRegistry:
    """Thread-safe family registry + pre-scrape collector hooks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}
        self._collectors: List[Callable[[], None]] = []

    def _family(self, name: str, kind: str, help: str,
                labelnames: Sequence[str] = (),
                buckets: Optional[Sequence[float]] = None) -> MetricFamily:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = MetricFamily(name, kind, help, labelnames, buckets)
                    self._families[name] = fam
        if fam.kind != kind or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name} re-registered with different "
                f"kind/labels ({fam.kind}/{fam.labelnames} vs "
                f"{kind}/{tuple(labelnames)})"
            )
        return fam

    def counter(self, name, help="", labelnames=()) -> MetricFamily:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> MetricFamily:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> MetricFamily:
        return self._family(name, "histogram", help, labelnames, buckets)

    def register_collector(self, fn: Callable[[], None]) -> None:
        """`fn` runs before every render/snapshot — the pull hook for
        sources that keep their own cumulative state (native runtime)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass  # a dead provider must not break the scrape

    def render(self) -> str:
        self.collect()
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        self.collect()
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        return {f.name: f.snapshot() for f in fams}

    def clear(self) -> None:
        with self._lock:
            self._families.clear()
            self._collectors.clear()


registry = MetricsRegistry()


PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def scrape() -> str:
    """Prometheus text exposition of the process-local registry."""
    return registry.render()


def exposition(
    pushed: Optional[Dict[str, bytes]] = None,
) -> Tuple[str, bytes]:
    """(content-type, body) for serving a scrape over HTTP — the one
    definition the standalone endpoint, the serving server and the
    rendezvous server all mount (runner/http/http_server.py).

    With ``pushed`` (rank label → exposition payload, as collected by
    the rendezvous server from worker ``PUT /metrics_push/<rank>``
    calls) the scrape is **cluster-aggregated**: this process's series
    stay unlabeled and every pushed series gains a ``rank="<r>"``
    label, so one endpoint answers for the whole world. A pod relay
    (multipod/relay.py) forwards its pod's pushes under
    ``<rank>@<pod>`` keys; those series additionally gain a
    ``pod="<pod>"`` label, so the aggregated scrape rolls up by pod
    with one PromQL ``sum by (pod)``."""
    if not pushed:
        return PROM_CONTENT_TYPE, scrape().encode()
    payloads: List[Tuple[str, str]] = [("", scrape())]
    for rank_label in sorted(pushed, key=lambda r: (len(r), r)):
        body = pushed[rank_label]
        text = (body.decode("utf-8", "replace")
                if isinstance(body, (bytes, bytearray)) else str(body))
        payloads.append((rank_label, text))
    return PROM_CONTENT_TYPE, merge_expositions(payloads).encode()


#: rendezvous KV scope worker metric pushes land in (the aggregation
#: source for the rendezvous /metrics mount)
METRICS_PUSH_SCOPE = "metrics_push"


def merge_expositions(payloads: Iterable[Tuple[str, str]]) -> str:
    """Merge Prometheus text payloads into one exposition, injecting a
    ``rank`` label into every sample of a non-empty-labeled payload
    (and a ``pod`` label when the payload key is ``<rank>@<pod>`` —
    the relay-forwarded form, multipod/relay.py). Families are
    regrouped so HELP/TYPE headers appear once, before all of a
    family's samples (what parsers and :func:`lint_exposition`
    require)."""
    help_: Dict[str, str] = {}
    type_: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    order: List[str] = []
    for rank_label, text in payloads:
        fam: Optional[str] = None
        for line in text.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                name, _, tail = line[7:].partition(" ")
                if not name:
                    continue
                target = help_ if line.startswith("# HELP ") else type_
                target.setdefault(name, tail)
                fam = name
                continue
            if not line.strip() or line.startswith("#"):
                continue
            key, _, val = line.rpartition(" ")
            if not key:
                continue
            name, brace, labels = key.partition("{")
            family = (
                fam if fam and name in (
                    fam, fam + "_bucket", fam + "_sum", fam + "_count")
                else name
            )
            if rank_label:
                rank_part, _, pod_part = str(rank_label).partition("@")
                extra = f'rank="{_escape_label(rank_part)}"'
                if pod_part:
                    extra += f',pod="{_escape_label(pod_part)}"'
                inner = labels[:-1] if brace else ""
                line = (
                    f"{name}{{"
                    + (inner + "," if inner else "")
                    + extra + f"}} {val}"
                )
            bucket = samples.get(family)
            if bucket is None:
                bucket = samples[family] = []
                order.append(family)
            bucket.append(line)
    out: List[str] = []
    for family in order:
        if family in help_:
            out.append(f"# HELP {family} {help_[family]}")
        if family in type_:
            out.append(f"# TYPE {family} {type_[family]}")
        out.extend(samples[family])
    return "\n".join(out) + ("\n" if out else "")


# -- exposition lint (test helper; docs/metrics.md) -------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"           # metric name
    r"(?:\{(.*)\})?"                          # optional label block
    r" (NaN|[+-]?Inf|-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)$"
)
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')
_PROM_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")


def _split_labels(block: str) -> List[str]:
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, buf, in_q, esc = [], [], False, False
    for ch in block:
        if esc:
            buf.append(ch)
            esc = False
        elif ch == "\\":
            buf.append(ch)
            esc = True
        elif ch == '"':
            buf.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts


def lint_exposition(text: str) -> List[str]:
    """Validate Prometheus text exposition; returns a list of problems
    (empty = parseable). Checks: sample-line grammar, label syntax,
    TYPE kinds, TYPE-before-samples, duplicate series, and histogram
    bucket monotonicity with a closing ``le="+Inf"``. Used by the
    regression tests that scrape /metrics under concurrent registry
    mutation — both the process-local and the rank-aggregated output
    must stay parseable at any instant."""
    errors: List[str] = []
    typed: Dict[str, str] = {}
    seen: set = set()
    hist: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            name, _, tail = line[7:].partition(" ")
            if not name:
                errors.append(f"line {i}: malformed comment header")
                continue
            if line.startswith("# TYPE "):
                if tail not in _PROM_KINDS:
                    errors.append(f"line {i}: unknown TYPE {tail!r}")
                if name in typed:
                    errors.append(f"line {i}: duplicate TYPE for {name}")
                typed[name] = tail
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {i}: unparseable sample: {line!r}")
            continue
        name, labels, val = m.groups()
        label_parts = _split_labels(labels) if labels else []
        for part in label_parts:
            if not _LABEL_RE.match(part):
                errors.append(f"line {i}: bad label {part!r}")
        key = (name, labels or "")
        if key in seen:
            errors.append(f"line {i}: duplicate series {name}{{{labels}}}")
        seen.add(key)
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed:
                family = name[: -len(suffix)]
                break
        if family not in typed:
            errors.append(
                f"line {i}: sample {name} precedes its TYPE header")
        if typed.get(family) == "histogram" and name == family + "_bucket":
            le, rest = None, []
            for part in label_parts:
                if part.startswith('le="'):
                    le = part[4:-1]
                else:
                    rest.append(part)
            if le is None:
                errors.append(f"line {i}: histogram bucket missing le=")
            else:
                hist.setdefault((family, ",".join(rest)), []).append(
                    (float("inf") if le == "+Inf" else float(le),
                     float(m.group(3)))
                )
    for (family, series), buckets in hist.items():
        buckets.sort(key=lambda b: b[0])
        if not buckets or buckets[-1][0] != float("inf"):
            errors.append(
                f'{family}{{{series}}}: histogram lacks le="+Inf"')
        cum = -1.0
        for le, v in buckets:
            if v < cum:
                errors.append(
                    f"{family}{{{series}}}: bucket counts not "
                    f"cumulative at le={le}")
                break
            cum = v
    return errors


# ---------------------------------------------------------------------------
# per-step aggregation
# ---------------------------------------------------------------------------

class StepStats:
    """Accumulates per-interval telemetry between ``begin_step`` /
    ``end_step`` and emits one JSONL record per step: step time,
    collective count/bytes by (op, dtype), fusion fill ratio, cache hit
    rate, negotiation latency, eager queue depth, elastic transitions —
    the live analog of replaying a timeline after the run."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._log_fh = None
        self._log_path = ""
        self.step = 0
        self._t0: Optional[float] = None
        self._last_native: Dict[str, float] = {}
        self._reset_interval()

    def _reset_interval(self) -> None:
        self.collectives: Dict[str, List[float]] = {}  # op/dtype -> [n, B]
        self.neg_count = 0
        self.neg_sum = 0.0
        self.fusion_plans = 0
        self.fusion_buckets = 0
        self.fusion_fill_sum = 0.0
        self.grad_bytes = 0
        self.wire_logical = 0
        self.wire_sent = 0
        self.overlap_window = None  # staged-scheduler pin (0..1)
        self.fsdp_param_bytes = None  # per-device resident param bytes
        self.fsdp_gather_bytes = 0    # forward all-gather bytes
        self.fsdp_regather_bytes = 0  # backward re-gather bytes
        self.fsdp_offload_bytes = 0   # stage carries parked in host RAM
        self.mfu = None             # model-FLOPs utilization (0..1)
        self.attribution = None     # sampled device attribution dict
        self.queue_depth = 0
        self.elastic_events: List[str] = []
        self.retries: Dict[str, int] = {}       # point -> count
        self.retry_giveups: Dict[str, int] = {}

    # -- accumulation hooks (called by the module record_* functions) ------

    def add_collective(self, op: str, dtype: str, nbytes: int) -> None:
        with self._lock:
            ent = self.collectives.setdefault(f"{op}/{dtype}", [0, 0])
            ent[0] += 1
            ent[1] += int(nbytes)

    def add_negotiation(self, seconds: float) -> None:
        with self._lock:
            self.neg_count += 1
            self.neg_sum += seconds

    def add_fusion(self, n_buckets: int, fill_sum: float) -> None:
        with self._lock:
            self.fusion_plans += 1
            self.fusion_buckets += n_buckets
            self.fusion_fill_sum += fill_sum

    def add_grad_bytes(self, nbytes: int) -> None:
        with self._lock:
            self.grad_bytes += int(nbytes)

    def add_wire(self, logical: int, sent: int) -> None:
        with self._lock:
            self.wire_logical += int(logical)
            self.wire_sent += int(sent)

    def set_overlap_window(self, frac: float) -> None:
        with self._lock:
            self.overlap_window = float(frac)

    def add_fsdp(self, param_bytes: int, gather_bytes: int,
                 regather_bytes: int = 0, offload_bytes: int = 0) -> None:
        with self._lock:
            self.fsdp_param_bytes = int(param_bytes)
            self.fsdp_gather_bytes += int(gather_bytes)
            self.fsdp_regather_bytes += int(regather_bytes)
            self.fsdp_offload_bytes += int(offload_bytes)

    def set_mfu(self, mfu: float) -> None:
        with self._lock:
            self.mfu = float(mfu)

    def set_attribution(self, attribution: dict) -> None:
        """Latest sampled-step device attribution (utils/prof.py). The
        sample parses asynchronously, so it lands in the record of the
        step interval during which parsing finished — the record's
        ``attribution.sampled_step`` names the step actually
        measured."""
        with self._lock:
            self.attribution = dict(attribution)

    def add_elastic_event(self, kind: str) -> None:
        with self._lock:
            self.elastic_events.append(kind)

    def add_retry(self, point: str) -> None:
        with self._lock:
            self.retries[point] = self.retries.get(point, 0) + 1

    def add_retry_giveup(self, point: str) -> None:
        with self._lock:
            self.retry_giveups[point] = (
                self.retry_giveups.get(point, 0) + 1
            )

    def set_queue_depth(self, n: int) -> None:
        self.queue_depth = int(n)

    # -- step boundary ------------------------------------------------------

    def emit_event(self, kind: str, payload: dict) -> None:
        """Write one out-of-band event line to the JSONL: decision-trail
        records (the autotuner's trial/pin/reject blocks) that must not
        wait for a training-step boundary to flush. Event lines carry
        ``{"event": kind, kind: payload}`` instead of the step fields;
        scripts/metrics_summary.py separates them from step records."""
        with self._lock:
            if self._log_fh is None:
                return
            rec = {"event": kind, "time_unix": time.time(),
                   kind: dict(payload)}
            self._log_fh.write(json.dumps(rec) + "\n")
            self._log_fh.flush()

    def open_log(self, path: str) -> None:
        with self._lock:
            if self._log_fh is not None:
                self._log_fh.close()
            self._log_path = path
            self._log_fh = open(path, "a")

    def close_log(self) -> None:
        with self._lock:
            if self._log_fh is not None:
                self._log_fh.close()
                self._log_fh = None
                self._log_path = ""

    def begin_step(self) -> None:
        self._t0 = time.perf_counter()

    def end_step(self, extra: Optional[dict] = None) -> dict:
        """Close the interval: compute the record, emit JSONL, feed the
        step-level registry series, reset accumulators."""
        now = time.perf_counter()
        dt = (now - self._t0) if self._t0 is not None else 0.0
        self._t0 = None
        native = _native_stats_snapshot()
        with self._lock:
            self.step += 1
            coll = {
                k: {"count": int(v[0]), "bytes": int(v[1])}
                for k, v in sorted(self.collectives.items())
            }
            n_coll = sum(v[0] for v in self.collectives.values())
            record = {
                "step": self.step,
                "time_unix": time.time(),
                "step_time_s": dt,
                "collectives": coll,
                "negotiation": {
                    "count": self.neg_count, "sum_s": self.neg_sum,
                },
                "fusion": {
                    "plans": self.fusion_plans,
                    "buckets": self.fusion_buckets,
                    "fill_ratio_mean": (
                        self.fusion_fill_sum / self.fusion_buckets
                        if self.fusion_buckets else 0.0
                    ),
                },
                "grad_bytes": self.grad_bytes,
                "queue_depth": self.queue_depth,
                "elastic_events": list(self.elastic_events),
            }
            if self.wire_logical or self.wire_sent:
                record["wire"] = {
                    "logical_bytes": self.wire_logical,
                    "sent_bytes": self.wire_sent,
                }
            if self.overlap_window is not None:
                record["overlap_window_frac"] = self.overlap_window
            if self.fsdp_param_bytes is not None:
                record["fsdp"] = {
                    "hbm_param_bytes": self.fsdp_param_bytes,
                    "gather_bytes": self.fsdp_gather_bytes,
                    "regather_bytes": self.fsdp_regather_bytes,
                    "offload_bytes": self.fsdp_offload_bytes,
                }
            if self.mfu is not None:
                record["mfu"] = self.mfu
            if self.attribution is not None:
                record["attribution"] = self.attribution
            if self.retries:
                record["retries"] = dict(self.retries)
            if self.retry_giveups:
                record["retry_giveups"] = dict(self.retry_giveups)
            if _pod_label:
                # federation view: the pod this process belongs to
                # (multipod/topology.py) — scripts/metrics_summary.py
                # rolls step records up per pod on it
                record["pod"] = _pod_label
            if native:
                delta = {
                    k: native[k] - self._last_native.get(k, 0.0)
                    for k in ("cache_hits", "bytes_negotiated",
                              "stall_warnings")
                    if k in native
                }
                hits = delta.get("cache_hits", 0.0)
                record["native"] = {
                    **{k: int(v) for k, v in delta.items()},
                    # hit RATE relative to collectives issued this step;
                    # the native cache has no per-lookup counter, so this
                    # is the closest well-defined live ratio
                    "cache_hit_rate": (
                        min(hits / n_coll, 1.0) if n_coll else 0.0
                    ),
                }
                if "cycles" in native:
                    record["native"]["coord_cycles"] = int(native["cycles"])
                self._last_native = native
            if extra:
                record.update(extra)
            # write under the lock: close_log (hvd.shutdown, possibly
            # another thread) also takes it, so the handle can't be
            # closed between the check and the write
            if self._log_fh is not None:
                self._log_fh.write(json.dumps(record) + "\n")
                self._log_fh.flush()
            self._reset_interval()
        if _enabled:
            registry.counter(
                "hvd_steps_total", "Completed training steps").inc()
            registry.histogram(
                "hvd_step_seconds", "Step wall time").observe(dt)
        obs = _step_observer
        if obs is not None:
            try:
                obs(record)
            except Exception:
                # a broken detector must never take down the step loop
                pass
        return record


step_stats = StepStats()


# -- step wrapper hook (the continuous profiler rides step()) ---------------
#
# utils/prof.py registers an object with begin_step()/end_step(token)
# here, so ``with hvd.metrics.step():`` is the single user-visible step
# boundary for BOTH per-step stats and sampled device profiling — no
# second context manager to adopt. None (the default) costs one load +
# is-None check per step.

_step_wrapper = None

# health/ rides the same slots: the step observer receives each
# completed step's record dict (AFTER the JSONL write), the serving
# observer each serving latency sample. None (default) costs one load
# + is-None check — the monitor's entire disabled-path budget.
_step_observer = None
_serving_observer = None


def set_step_wrapper(wrapper) -> None:
    """Install/remove (None) the step wrapper. ``wrapper.begin_step()``
    runs before the step body (returning an opaque token),
    ``wrapper.end_step(token)`` after it but BEFORE the StepStats
    record closes — anything it pushes into ``step_stats`` lands in
    the current step's JSONL record."""
    global _step_wrapper
    _step_wrapper = wrapper


def set_step_observer(fn) -> None:
    """Install/remove (None) the step-record observer: ``fn(record)``
    runs after each StepStats record closes, outside the stats lock.
    The health monitor's detector feed (horovod_tpu/health)."""
    global _step_observer
    _step_observer = fn


def set_serving_observer(fn) -> None:
    """Install/remove (None) the serving-latency observer:
    ``fn(kind, slo, seconds)`` with kind in ttft | tpot | queue_wait |
    request. The health monitor's SLO burn-rate feed."""
    global _serving_observer
    _serving_observer = fn


@contextlib.contextmanager
def step(extra: Optional[dict] = None):
    """Mark one training step: ``with hvd.metrics.step(): step_fn(...)``.
    No-ops entirely when metrics are disabled, no step log is open and
    no step wrapper (sampled profiler) is installed."""
    # snapshot both gates once: a concurrent enable()/disable()/reset()
    # mid-step must not split a begin from its end (lost JSONL record /
    # bogus zero-length step)
    w = _step_wrapper
    en = _enabled
    if not en and w is None:
        yield step_stats
        return
    token = w.begin_step() if w is not None else None
    if en:
        step_stats.begin_step()
    try:
        yield step_stats
    finally:
        if w is not None:
            w.end_step(token)
        if en:
            step_stats.end_step(extra)


# ---------------------------------------------------------------------------
# hot-path record functions (each begins with the no-op fast path)
# ---------------------------------------------------------------------------

def record_collective(op: str, dtype: str, nbytes: int) -> None:
    """One issued collective (eager/native dispatch site)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_collectives_total",
        "Collectives issued, by op and dtype", ("op", "dtype"),
    ).labels(op, dtype).inc()
    registry.counter(
        "hvd_collective_bytes_total",
        "Payload bytes of issued collectives, by op and dtype",
        ("op", "dtype"),
    ).labels(op, dtype).inc(nbytes)
    step_stats.add_collective(op, dtype, nbytes)


def record_negotiation_latency(seconds: float) -> None:
    """Enqueue → negotiated-batch-received latency for one tensor."""
    if not _enabled:
        return
    registry.histogram(
        "hvd_negotiation_seconds",
        "Enqueue-to-negotiated latency in the eager runtime",
    ).observe(seconds)
    step_stats.add_negotiation(seconds)


def record_batch_execution(op: str, n_tensors: int, nbytes: int,
                           seconds: float) -> None:
    """One negotiated fused batch executed by the data plane."""
    if not _enabled:
        return
    registry.histogram(
        "hvd_batch_execution_seconds",
        "Fused-batch execution wall time, by op", ("op",),
    ).labels(op).observe(seconds)
    registry.counter(
        "hvd_fused_tensors_total",
        "Tensors carried by executed fused batches", ("op",),
    ).labels(op).inc(n_tensors)
    registry.counter(
        "hvd_fused_batch_bytes_total",
        "Bytes carried by executed fused batches", ("op",),
    ).labels(op).inc(nbytes)


def record_fusion_plan(n_tensors: int, n_buckets: int, threshold: int,
                       bucket_bytes: Sequence[int] = ()) -> None:
    """One (compile-time) fusion plan: bucket count + fill ratios."""
    if not _enabled:
        return
    registry.counter(
        "hvd_fusion_plans_total", "Fusion plans computed").inc()
    registry.counter(
        "hvd_fusion_buckets_total", "Fusion buckets produced"
    ).inc(n_buckets)
    registry.counter(
        "hvd_fusion_tensors_total", "Tensors entering fusion plans"
    ).inc(n_tensors)
    fill_sum = 0.0
    hist = registry.histogram(
        "hvd_fusion_fill_ratio",
        "Bucket bytes / fusion threshold per produced bucket",
        buckets=RATIO_BUCKETS,
    )
    for b in bucket_bytes:
        r = min(b / threshold, 1.0) if threshold else 0.0
        hist.observe(r)
        fill_sum += r
    step_stats.add_fusion(n_buckets, fill_sum)


def record_grad_reduction(nbytes: int, n_buckets: int) -> None:
    """One executed gradient reduction (io_callback from the compiled
    step — fires per real step, not per trace)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_grad_reduced_bytes_total",
        "Gradient bytes moved by executed reductions").inc(nbytes)
    registry.counter(
        "hvd_grad_reductions_total", "Executed gradient reductions").inc()
    step_stats.add_grad_bytes(nbytes)


def record_wire_bytes(logical: int, sent: int) -> None:
    """One compressed-data-plane transfer (docs/compression.md): what
    the payload occupies at logical precision vs what actually moves
    under the HOROVOD_COMPRESSION wire (payload + scales). The two
    counters are equal on the uncompressed plane; their ratio is the
    live compression factor scripts/metrics_summary.py reports and
    scripts/compression_check.py gates on."""
    if not _enabled:
        return
    registry.counter(
        "hvd_wire_bytes_logical_total",
        "Collective payload bytes at logical precision").inc(int(logical))
    registry.counter(
        "hvd_wire_bytes_sent_total",
        "Collective payload bytes on the compressed wire").inc(int(sent))
    step_stats.add_wire(int(logical), int(sent))


def record_fused_collective(surface: str) -> None:
    """One fused Pallas computation-collective lowering
    (ops/pallas_collectives.py). Recorded at TRACE time — a breadcrumb
    of which fused surfaces this process compiled, not a per-step
    counter: the per-step wire/step accounting is unchanged by fusion
    (the fused path moves the same bytes), so those gauges keep
    reporting through the existing hvd_step_* / hvd_wire_* families."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_fused_collectives_enabled",
        "1 when the fused Pallas collective backend is selected").set(1)
    registry.counter(
        "hvd_fused_collective_lowerings_total",
        "Fused computation-collective kernels lowered, by surface",
        labelnames=("surface",)).labels(surface=surface).inc()


def record_overlap_window(frac: float) -> None:
    """The backward-interleaved scheduler's per-step overlap pin
    (ops/overlap.py): the fraction of backward compute the staged
    schedule forces after the first gradient collective — the lower
    bound any correct scheduler must grant the overlap window. Only
    recorded when HOROVOD_OVERLAP_SCHEDULE is active; its absence in
    the JSONL marks an unscheduled run (docs/overlap.md)."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_overlap_window_frac",
        "Backward fraction pinned after the first gradient collective "
        "by the overlap schedule").set(float(frac))
    step_stats.set_overlap_window(frac)


def record_fsdp_step(param_bytes: int, gather_bytes: int,
                     regather_bytes: int = 0,
                     offload_bytes: int = 0) -> None:
    """One executed fully-sharded-parameter step (optim/fsdp.py,
    io_callback from the compiled step): the per-device parameter bytes
    RESIDENT in HBM (the sharded footprint — under FSDP ~1/world of
    the replicated size; the durable memory win) and the full-precision
    parameter bytes the forward all-gathers re-materialized this step
    (the recurring wire rent paid for it). Their ratio per step is
    ~world: FSDP trades gather bandwidth for resident HBM. Regather
    mode (HOROVOD_FSDP_REGATHER) pays the rent twice —
    ``regather_bytes`` counts the backward re-issued gathers that cap
    within-step peak liveness — and ``offload_bytes`` counts
    stage-boundary activation carries parked in host RAM under
    HOROVOD_FSDP_OFFLOAD (docs/fsdp.md)."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_hbm_param_bytes",
        "Per-device parameter bytes resident in HBM (sharded "
        "footprint under FSDP; replicated size otherwise)").set(
            float(param_bytes))
    registry.counter(
        "hvd_fsdp_gather_bytes_total",
        "Full-precision parameter bytes materialized by FSDP forward "
        "all-gathers").inc(float(gather_bytes))
    if regather_bytes:
        registry.counter(
            "hvd_fsdp_regather_bytes_total",
            "Full-precision parameter bytes re-materialized by FSDP "
            "backward re-gathers (regather mode)").inc(
                float(regather_bytes))
    if offload_bytes:
        registry.counter(
            "hvd_fsdp_offload_bytes_total",
            "Stage-boundary activation bytes offloaded to host RAM "
            "per step (HOROVOD_FSDP_OFFLOAD)").inc(float(offload_bytes))
    step_stats.add_fsdp(param_bytes, gather_bytes, regather_bytes,
                        offload_bytes)


def record_mfu(mfu: float) -> None:
    """Model-FLOPs utilization for the step just closed: declared model
    FLOPs / (step time x chips x peak chip FLOP/s) — utils/mfu.py peak
    tables, computed by the continuous profiler (utils/prof.py) once
    ``hvd.prof.set_step_flops`` declared the model's per-step cost."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_mfu",
        "Model-FLOPs utilization of the last completed step").set(
            float(mfu))
    step_stats.set_mfu(mfu)


def record_step_attribution(attribution: dict) -> None:
    """One sampled-step device attribution (utils/prof.py →
    utils/xplane.attribute): where the step's wall time went —
    compute, EXPOSED collective wire (collective time not hidden under
    compute), idle. ``measured_overlap_frac`` is the measured twin of
    the structural ``hvd_overlap_window_frac`` pin (docs/overlap.md):
    structural says how much overlap the schedule permits, this says
    how much the device actually achieved."""
    if not _enabled:
        return
    if "compute_frac" in attribution:
        registry.gauge(
            "hvd_step_compute_frac",
            "Compute fraction of the last sampled step's wall time",
        ).set(float(attribution["compute_frac"]))
    if "exposed_wire_frac" in attribution:
        registry.gauge(
            "hvd_step_exposed_wire_frac",
            "Exposed (un-overlapped) collective fraction of the last "
            "sampled step's wall time",
        ).set(float(attribution["exposed_wire_frac"]))
    if "idle_frac" in attribution:
        registry.gauge(
            "hvd_step_idle_frac",
            "Device-idle fraction of the last sampled step's wall "
            "time").set(float(attribution["idle_frac"]))
    overlap = attribution.get("measured_overlap_frac")
    # -1 = the sampled window held no collectives (overlap undefined);
    # leaving the previous sample's value would pair a stale overlap
    # with this sample's fresh compute/exposed/idle gauges
    registry.gauge(
        "hvd_overlap_window_measured_frac",
        "Measured overlapped share of collective time in the last "
        "sampled step (1.0 = wire fully hidden under compute; -1 = no "
        "collectives in the sample; the measured twin of "
        "hvd_overlap_window_frac)",
    ).set(-1.0 if overlap is None else float(overlap))
    step_stats.set_attribution(attribution)


def record_autotune_trial(dimension: str, step_s: Optional[float],
                          mfu: Optional[float] = None,
                          error: Optional[str] = None,
                          overrides: Optional[dict] = None) -> None:
    """One autotuner candidate measured (or failed) by the closed-loop
    tuner (ops/autotune.py): counts into
    ``hvd_autotune_trials_total{dimension}`` (errors additionally into
    ``hvd_autotune_trial_errors_total``) and lands as an ``autotune``
    event line in the StepStats JSONL — the decision trail
    scripts/metrics_summary.py renders as the sweep table."""
    if not _enabled:
        return
    registry.counter(
        "hvd_autotune_trials_total",
        "Autotune candidates measured, by sweep dimension",
        ("dimension",),
    ).labels(dimension).inc()
    if error is not None:
        registry.counter(
            "hvd_autotune_trial_errors_total",
            "Autotune candidates that failed to compile/run, by "
            "dimension", ("dimension",),
        ).labels(dimension).inc()
    payload = {"kind": "trial", "dimension": dimension}
    if overrides:
        payload["overrides"] = {k: v for k, v in overrides.items()}
    if step_s is not None:
        payload["step_s"] = float(step_s)
    if mfu is not None:
        payload["mfu"] = float(mfu)
    if error is not None:
        payload["error"] = error
    step_stats.emit_event("autotune", payload)


def record_autotune_pin(dimension: str, config: dict,
                        step_s: Optional[float],
                        accepted: bool = True,
                        source: str = "sweep") -> None:
    """One per-dimension agreement outcome (pin when the dimension
    improved on the incumbent, reject when it kept it) or a
    warm-start/final pin: ``hvd_autotune_best_step_s`` tracks the
    agreed best step time and ``hvd_autotune_dimension{dimension=<knob>}``
    carries every pinned knob's numeric value (strings enumerate per
    ops/autotune._ENUM_VALUES). ``step_s`` None = no candidate of the
    dimension measured successfully (all failed): the gauge keeps its
    last value and the JSONL event carries null — a bare ``Infinity``
    token would make the line unparseable to RFC-8259 readers."""
    if not _enabled:
        return
    from ..ops.autotune import _numeric

    if step_s is not None and step_s == step_s and step_s not in (
            float("inf"), float("-inf")):
        registry.gauge(
            "hvd_autotune_best_step_s",
            "Agreed best measured step seconds of the autotune sweep "
            "(the warm-start entry's recorded time on cache pins)",
        ).set(float(step_s))
    else:
        step_s = None
    gauge = registry.gauge(
        "hvd_autotune_dimension",
        "Pinned autotune knob values, by knob (strings enumerate: "
        "overlap off/stage/double=0/1/2, compression "
        "none/fp16/bf16/int8/int8-raw=0..4)", ("dimension",))
    for k, v in config.items():
        gauge.labels(k).set(_numeric(k, v))
    step_stats.emit_event("autotune", {
        "kind": "pin" if accepted else "reject",
        "dimension": dimension,
        "config": {k: v for k, v in config.items()},
        "step_s": step_s,
        "source": source,
    })


def record_timeline_activity(activity: str, seconds: float) -> None:
    """Bridge: a closed timeline span (utils/timeline.py) lands in a
    latency histogram keyed by its activity name."""
    if not _enabled:
        return
    registry.histogram(
        "hvd_timeline_activity_seconds",
        "Host-side timeline phase durations, by activity", ("activity",),
    ).labels(activity).observe(seconds)


def record_retry(point: str) -> None:
    """One backed-off retry of a control-plane call (utils/retry.py),
    labeled by call point (http.put, checkpoint.save, ...)."""
    _flight.record("retry", point)  # flight recorder has its own gate
    if not _enabled:
        return
    registry.counter(
        "hvd_retries_total",
        "Control-plane retries, by call point", ("point",),
    ).labels(point).inc()
    step_stats.add_retry(point)


def record_retry_giveup(point: str) -> None:
    """A retried call that exhausted its attempts/deadline and
    re-raised."""
    _flight.record("retry_giveup", point)
    if not _enabled:
        return
    registry.counter(
        "hvd_retry_giveups_total",
        "Control-plane retry give-ups, by call point", ("point",),
    ).labels(point).inc()
    step_stats.add_retry_giveup(point)


def record_fault(point: str, action: str) -> None:
    """One injected fault fired (utils/faults.py), by injection point
    and action — lets chaos runs prove the faults actually happened."""
    _flight.record("fault", point, action=action)
    if not _enabled:
        return
    registry.counter(
        "hvd_faults_injected_total",
        "Injected faults fired, by point and action",
        ("point", "action"),
    ).labels(point, action).inc()


def record_stall_abort() -> None:
    """A stalled collective converted into HorovodInternalError by the
    negotiation watchdog (HOROVOD_STALL_ABORT_S)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_stall_aborts_total",
        "Collectives aborted by the stall watchdog").inc()


def record_recovery_rung(rung: str) -> None:
    """One state recovery resolved by the layered recovery ladder
    (elastic/replication.py), labeled by the rung that supplied the
    restored snapshot: peer / emergency / orbax / local / none."""
    _flight.record("recovery", rung)
    if not _enabled:
        return
    registry.counter(
        "hvd_recovery_rung_total",
        "State recoveries, by ladder rung (peer/emergency/orbax/"
        "local/none)", ("rung",),
    ).labels(rung).inc()
    step_stats.add_elastic_event(f"recovery:{rung}")


def record_replication(nbytes: int, n_partners: int) -> None:
    """One committed snapshot shipped to ring partners by the async
    replicator (elastic/replication.py)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_replication_snapshots_total",
        "Committed snapshots replicated to ring partners").inc()
    registry.counter(
        "hvd_replication_bytes_total",
        "Snapshot payload bytes shipped to ring partners",
    ).inc(nbytes * max(n_partners, 1))


def record_replication_error() -> None:
    """A snapshot replication attempt that could not reach any ring
    partner (best-effort: training continues)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_replication_errors_total",
        "Snapshot replications that reached no ring partner").inc()


def record_elastic_event(kind: str) -> None:
    """An elastic lifecycle transition (reset, hosts-updated, round,
    blacklist, ...)."""
    _flight.record("elastic", kind)
    if not _enabled:
        return
    registry.counter(
        "hvd_elastic_events_total",
        "Elastic lifecycle transitions, by event", ("event",),
    ).labels(kind).inc()
    step_stats.add_elastic_event(kind)


def set_queue_depth(n: int) -> None:
    """Pending tensors in the eager runtime's input table."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_eager_queue_depth",
        "Tensors enqueued and awaiting negotiation/execution").set(n)
    step_stats.set_queue_depth(n)


# ---------------------------------------------------------------------------
# inference-serving record functions (serving/ — engine, batcher,
# server, replica dispatch). Same discipline as the training sites:
# every function starts with the disabled fast path.
# ---------------------------------------------------------------------------

def record_serving_request(seconds: float, code: int) -> None:
    """One completed front-end request (server.py), by HTTP status."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_requests_total",
        "Serving requests completed, by HTTP status", ("code",),
    ).labels(str(code)).inc()
    registry.histogram(
        "hvd_serving_request_seconds",
        "End-to-end serving request latency, by HTTP status", ("code",),
    ).labels(str(code)).observe(seconds)


def record_serving_queue_wait(seconds: float,
                              slo: str = "standard") -> None:
    """Admission-to-dispatch wait of one request in the dynamic
    batcher's queue, by SLO class (serving/scheduler.py names the
    class; the one-shot predict batcher is all ``standard``)."""
    if not _enabled:
        return
    registry.histogram(
        "hvd_serving_queue_wait_seconds",
        "Request wait in the dynamic-batching queue, by SLO class",
        ("slo",),
    ).labels(slo).observe(seconds)
    obs = _serving_observer
    if obs is not None:
        obs("queue_wait", slo, seconds)


def record_serving_ttft(seconds: float, slo: str = "standard") -> None:
    """Time-to-first-token: request admission to first emitted token
    (prefill complete), by SLO class — ROADMAP item 3's scoreboard
    series; the health burn-rate rules consume it."""
    if not _enabled:
        return
    registry.histogram(
        "hvd_serving_ttft_seconds",
        "Time to first token per request, by SLO class", ("slo",),
    ).labels(slo).observe(seconds)
    obs = _serving_observer
    if obs is not None:
        obs("ttft", slo, seconds)


def record_serving_tpot(seconds: float, slo: str = "standard") -> None:
    """Time-per-output-token: one decode iteration's wall time billed
    to each live sequence it advanced, by SLO class."""
    if not _enabled:
        return
    registry.histogram(
        "hvd_serving_tpot_seconds",
        "Time per output token for live sequences, by SLO class",
        ("slo",),
    ).labels(slo).observe(seconds)
    obs = _serving_observer
    if obs is not None:
        obs("tpot", slo, seconds)


def record_serving_batch(bucket: int, n_real: int) -> None:
    """One executed inference batch: the chosen padded bucket and how
    many real examples it carried (the rest is padding waste)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_batches_total",
        "Inference batches executed, by padded bucket", ("bucket",),
    ).labels(str(bucket)).inc()
    registry.counter(
        "hvd_serving_examples_total",
        "Real examples served through executed batches").inc(n_real)
    registry.counter(
        "hvd_serving_padding_examples_total",
        "Padding examples added to reach the bucket size",
    ).inc(max(bucket - n_real, 0))
    registry.histogram(
        "hvd_serving_batch_fill_ratio",
        "Real examples / padded bucket size per executed batch",
        buckets=RATIO_BUCKETS,
    ).observe(n_real / bucket if bucket else 0.0)


def record_serving_compile(bucket: int, seconds: float) -> None:
    """One bucket executable AOT-compiled by the inference engine."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_compiles_total",
        "Bucket executables AOT-compiled, by bucket", ("bucket",),
    ).labels(str(bucket)).inc()
    registry.histogram(
        "hvd_serving_compile_seconds",
        "AOT compile wall time per bucket executable",
    ).observe(seconds)


def set_serving_inflight(n: int, replica: str = "") -> None:
    """Requests currently executing, per replica ('' = this process)."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_serving_inflight",
        "In-flight serving requests, by replica", ("replica",),
    ).labels(replica).set(n)


def record_serving_failover(replica: str) -> None:
    """A replica dropped from dispatch after a failed request (the
    request itself is retried on another replica)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_failovers_total",
        "Replicas ejected from dispatch after a failure", ("replica",),
    ).labels(replica).inc()


# -- autoregressive decode (serving/decode.py + serving/scheduler.py) --------

def record_decode_prefill(bucket: int, seconds: float) -> None:
    """One prompt prefilled into a claimed slot, by prompt-length
    bucket."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_decode_prefills_total",
        "Prompts prefilled into cache slots, by prompt-length bucket",
        ("bucket",),
    ).labels(str(bucket)).inc()
    registry.histogram(
        "hvd_serving_decode_prefill_seconds",
        "Prefill executable wall time per admitted prompt",
    ).observe(seconds)


def record_decode_iteration(slots: int, seconds: float) -> None:
    """One decode iteration executed (every slot advances one
    position; callers ignore inactive slots' outputs)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_decode_iterations_total",
        "Decode iterations executed").inc()
    registry.histogram(
        "hvd_serving_decode_iteration_seconds",
        "Decode-iteration executable wall time",
    ).observe(seconds)


def record_decode_tokens(n: int) -> None:
    """Tokens actually delivered to live sequences this iteration
    (excludes inactive-slot ride-along outputs)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_decode_tokens_total",
        "Tokens generated for live sequences").inc(n)


def set_decode_slots(total: int, occupied: int, queued: int) -> None:
    """Slot occupancy + queued prefills after a scheduler iteration —
    the live signals the replica autoscaler scales on
    (docs/generation.md)."""
    if not _enabled:
        return
    g = registry.gauge(
        "hvd_serving_decode_slots",
        "Decode cache slots, by state", ("state",))
    g.labels("total").set(total)
    g.labels("occupied").set(occupied)
    registry.gauge(
        "hvd_serving_decode_queued_prefills",
        "Requests admitted but waiting for a free slot").set(queued)
    registry.gauge(
        "hvd_serving_decode_slot_occupancy",
        "Occupied fraction of decode cache slots").set(
            occupied / total if total else 0.0)


def record_decode_eviction(reason: str) -> None:
    """One sequence leaving its slot (or the queue), by reason:
    eos / length / deadline / shed / drain."""
    _flight.record("decode_evict", reason)
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_decode_evictions_total",
        "Sequences evicted from decode, by reason", ("reason",),
    ).labels(reason).inc()


def record_autoscale(action: str) -> None:
    """One autoscaler decision acted on (grow / shrink)."""
    _flight.record("autoscale", action)
    if not _enabled:
        return
    registry.counter(
        "hvd_serving_autoscale_events_total",
        "Replica autoscaler actions, by direction", ("action",),
    ).labels(action).inc()


def set_serving_replicas(n: int) -> None:
    """Live replicas currently in dispatch rotation (front door)."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_serving_replicas",
        "Replicas in the dispatch rotation").set(n)


# -- fleet-health monitor (horovod_tpu/health, docs/health.md) ---------------

def set_alert_active(rule: str, active: bool) -> None:
    """1 while the named health SLO rule fires, 0 once it clears."""
    if not _enabled:
        return
    registry.gauge(
        "hvd_alert_active",
        "1 while the named health rule fires, by rule", ("rule",),
    ).labels(rule).set(1.0 if active else 0.0)


def record_health_anomaly(cls: str) -> None:
    """One classified detector anomaly (straggler-host / slow-link /
    input-bound / compute-regression / queue-saturation)."""
    if not _enabled:
        return
    registry.counter(
        "hvd_health_anomalies_total",
        "Detector anomalies, by classified cause", ("cause",),
    ).labels(cls).inc()


def record_health_incident(rule: str, state: str) -> None:
    """One alert transition (fire or clear) written to the incident
    log, by rule and transition."""
    if not _enabled:
        return
    registry.counter(
        "hvd_health_incidents_total",
        "Health alert transitions, by rule and state",
        ("rule", "state"),
    ).labels(rule, state).inc()


# ---------------------------------------------------------------------------
# native runtime stats bridge (pull model)
# ---------------------------------------------------------------------------

_native_provider: Optional[Callable[[], dict]] = None


def set_native_stats_provider(fn: Optional[Callable[[], dict]]) -> None:
    """The eager runtime registers its cumulative-stats snapshot here
    (ops/eager_runtime.py); gauges update on every scrape."""
    global _native_provider
    _native_provider = fn
    if fn is not None:
        registry.register_collector(_collect_native)


def _native_stats_snapshot() -> Dict[str, float]:
    fn = _native_provider
    if fn is None:
        return {}
    try:
        return {k: float(v) for k, v in fn().items()}
    except Exception:
        return {}


_NATIVE_GAUGES = {
    "cache_hits": ("hvd_cache_hits_total",
                   "Response-cache hits (native runtime, cumulative)"),
    "bytes_negotiated": ("hvd_bytes_negotiated_total",
                         "Tensor bytes negotiated (cumulative)"),
    "stall_warnings": ("hvd_stall_warnings_total",
                       "Stall-inspector warnings (cumulative)"),
    "queue_depth": ("hvd_eager_queue_depth",
                    "Tensors enqueued and awaiting negotiation/execution"),
    "fast_path_hits": (
        "hvd_eager_fast_path_hits_total",
        "Eager collectives that bypassed negotiation via the "
        "steady-state plan cache (cumulative)"),
    "fast_path_steps": (
        "hvd_eager_fast_path_steps_total",
        "Whole steps executed off a cached plan (cumulative)"),
    "fast_path_activations": (
        "hvd_eager_fast_path_activations_total",
        "Plans frozen after steady-state warmup (cumulative)"),
    "fast_path_invalidations": (
        "hvd_eager_fast_path_invalidations_total",
        "Cached plans dropped (deviation/churn/fault, cumulative)"),
    "fast_path_active": (
        "hvd_eager_fast_path_active",
        "1 while a frozen plan is live, 0 otherwise"),
    "negotiation_bypassed_bytes": (
        "hvd_eager_negotiation_bypassed_bytes_total",
        "Tensor bytes whose negotiation the plan cache skipped "
        "(cumulative; the fast-path analog of "
        "hvd_bytes_negotiated_total)"),
    "cycles": ("hvd_coord_cycles_total",
               "Coordinator negotiation cycles (rank 0)"),
    "busy_cycles": ("hvd_coord_busy_cycles_total",
                    "Coordinator cycles that produced responses (rank 0)"),
    "wait_us": ("hvd_coord_wait_seconds_total",
                "Coordinator wall time blocked on worker frames (rank 0)"),
    "work_us": ("hvd_coord_work_seconds_total",
                "Coordinator CPU work per cycle, summed (rank 0)"),
    "bytes_rx": ("hvd_coord_bytes_rx_total",
                 "Control-plane bytes received by the coordinator"),
    "bytes_tx": ("hvd_coord_bytes_tx_total",
                 "Control-plane bytes sent by the coordinator"),
    "cache_hit_positions": ("hvd_coord_cache_hit_positions_total",
                            "Cache-hit positions in coordinator cycles"),
    "responses": ("hvd_coord_responses_total",
                  "Responses emitted by the coordinator"),
}


def _collect_native() -> None:
    if not _enabled:
        return
    stats = _native_stats_snapshot()
    for key, (name, help) in _NATIVE_GAUGES.items():
        if key in stats:
            v = stats[key]
            if key in ("wait_us", "work_us"):
                v = v / 1e6
            registry.gauge(name, help).set(v)


# ---------------------------------------------------------------------------
# standalone HTTP endpoint (per-worker; the rendezvous server mounts the
# same scrape under /metrics — runner/http/http_server.py)
# ---------------------------------------------------------------------------

_http_server = None
_http_thread = None


def start_http_server(port: int = 0) -> int:
    """Serve ``GET /metrics`` on a dedicated port; returns the bound
    port. Idempotent per process."""
    global _http_server, _http_thread
    if _http_server is not None:
        return _http_server.server_address[1]
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            if self.path.split("?", 1)[0].rstrip("/") in ("", "/metrics"):
                ctype, body = exposition()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                body = b"not found"
                self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        def log_message(self, *args):
            pass

    _http_server = ThreadingHTTPServer(("0.0.0.0", port), _Handler)
    _http_thread = threading.Thread(
        target=_http_server.serve_forever, daemon=True, name="hvd-metrics",
    )
    _http_thread.start()
    return _http_server.server_address[1]


def stop_http_server() -> None:
    global _http_server, _http_thread
    if _http_server is not None:
        _http_server.shutdown()
        _http_server.server_close()
        _http_server = None
        _http_thread = None


# ---------------------------------------------------------------------------
# worker → rendezvous metrics push (the aggregation feed). Each worker
# PUTs its exposition under /metrics_push/<rank> at most once per
# HOROVOD_METRICS_PUSH_INTERVAL_S; the rendezvous /metrics mount merges
# the pushed payloads into one rank-labeled scrape (docs/metrics.md).
# ---------------------------------------------------------------------------

_push_thread: Optional[threading.Thread] = None
_push_stop: Optional[threading.Event] = None
_push_policy = None
_push_outage = None

# pod label of this process under a multipod topology ("" = single
# pod); stamps step records and names this pod in docs/telemetry
_pod_label = ""


def set_pod_label(label: str) -> None:
    global _pod_label
    _pod_label = str(label or "")


def pod_label() -> str:
    return _pod_label


def _push_degradation():
    """Lazy (import-cycle-safe) bounded policy + outage tracker for the
    push loop: a rendezvous outage costs one quick in-interval retry
    and ONE warning, not a warning per interval — the next interval's
    push is the real retry ladder (docs/recovery.md)."""
    global _push_policy, _push_outage
    if _push_policy is None:
        import logging

        from . import retry as _retry

        _push_policy = _retry.RetryPolicy(
            max_attempts=2, base_delay_s=0.1, max_delay_s=0.25)
        _push_outage = _retry.Outage(
            logging.getLogger("horovod_tpu.metrics"),
            "metrics push to the rendezvous store")
    return _push_policy, _push_outage


def push_once(addr: str, port: int, rank: int) -> bool:
    """One exposition PUT to the rendezvous store. Best-effort under a
    bounded RetryPolicy with log-spam suppression: a dead driver must
    never stall a worker, and a driver outage warns once (utils/
    retry.Outage), not once per push interval."""
    body = scrape().encode()
    policy, outage = _push_degradation()

    def _do() -> None:
        req = urllib.request.Request(
            f"http://{addr}:{port}/{METRICS_PUSH_SCOPE}/{rank}",
            data=body, method="PUT",
        )
        with urllib.request.urlopen(req, timeout=2.0):
            pass

    try:
        policy.call(_do, point="metrics.push")
        outage.success()
        return True
    except Exception as e:
        outage.failure(e)
        return False


def start_metrics_push(addr: str, port: int, rank: int,
                       interval_s: float = 5.0) -> None:
    """Start (or restart) the background push loop: one immediate push,
    then one per interval, plus a final flush on stop so short-lived
    workers still publish their last state."""
    global _push_thread, _push_stop
    stop_metrics_push()
    stop = threading.Event()

    def loop():
        push_once(addr, port, rank)
        while not stop.wait(max(interval_s, 0.05)):
            push_once(addr, port, rank)
        push_once(addr, port, rank)

    t = threading.Thread(target=loop, daemon=True,
                         name="hvd-metrics-push")
    t.start()
    _push_thread, _push_stop = t, stop


def stop_metrics_push() -> None:
    global _push_thread, _push_stop
    if _push_thread is not None:
        _push_stop.set()
        _push_thread.join(timeout=5)
        _push_thread = None
        _push_stop = None


def http_port() -> Optional[int]:
    return _http_server.server_address[1] if _http_server else None


# ---------------------------------------------------------------------------
# lifecycle wiring (core/basics.py calls these)
# ---------------------------------------------------------------------------

def configure(knobs) -> None:
    """Turn telemetry on per the knobs (HOROVOD_METRICS /
    HOROVOD_TPU_METRICS_FILE / HOROVOD_METRICS_PORT). A knob-less world
    leaves any manual ``enable()`` untouched."""
    global _configured
    want = bool(
        getattr(knobs, "metrics_enabled", False)
        or getattr(knobs, "metrics_file", "")
        or getattr(knobs, "metrics_port", 0)
    )
    if not want:
        return
    _configured = True
    enable()
    if getattr(knobs, "metrics_file", ""):
        step_stats.open_log(knobs.metrics_file)
    if getattr(knobs, "metrics_port", 0):
        start_http_server(knobs.metrics_port)
    # launcher-spawned worker: feed the rendezvous server's aggregated
    # /metrics (the driver process itself has no rank env and does not
    # push — its registry is the unlabeled series of the merge).
    # Under a multipod topology the push targets the pod's RELAY, not
    # the root — the relay batches the pod's expositions into one
    # upward PUT so the root sees O(pods) pushers (multipod/relay.py).
    interval = float(
        getattr(knobs, "metrics_push_interval_s", 0.0) or 0.0)
    try:
        from ..multipod.relay import push_endpoint

        endpoint = push_endpoint()
    except Exception:
        endpoint = None
    try:
        # separate guard: a malformed multipod env (bad pod id, a pod
        # count that doesn't divide the world) must cost the pod
        # label, never the push loop itself
        from ..multipod.topology import pod_topology_from_env

        topo = pod_topology_from_env()
        if topo is not None:
            set_pod_label(topo.pod_label())
    except Exception:
        pass
    rank = (os.environ.get("HVD_TPU_RANK")
            or os.environ.get("HOROVOD_RANK"))
    if interval > 0 and endpoint is not None and rank is not None:
        try:
            start_metrics_push(
                endpoint[0], endpoint[1], int(rank), interval)
        except ValueError:
            pass


def on_shutdown() -> None:
    """hvd.shutdown(): flush/close the step log and endpoint; disable
    only if configure() was what enabled us."""
    global _configured
    stop_metrics_push()  # joins after a final flush
    step_stats.close_log()
    stop_http_server()
    set_native_stats_provider(None)
    if _configured:
        _configured = False
        disable()


def reset() -> None:
    """Test hook: clear every family, provider and accumulator and
    return to the disabled state."""
    global _configured, _push_policy, _push_outage
    _push_policy = _push_outage = None
    set_pod_label("")
    set_step_wrapper(None)
    set_step_observer(None)
    set_serving_observer(None)
    on_shutdown()
    disable()
    _configured = False
    registry.clear()
    step_stats.close_log()
    step_stats.step = 0
    step_stats._last_native = {}
    step_stats._reset_interval()
