from .mlp import MLP, MnistNet  # noqa: F401
from .moe import MoeMlp  # noqa: F401
from .resnet import ResNet, ResNet50, ResNet101, ResNet152  # noqa: F401
from .inception import InceptionV3  # noqa: F401
from .vgg import VGG16  # noqa: F401
from .transformer import (  # noqa: F401
    BERT_BASE,
    BERT_LARGE,
    GPT2_LARGE,
    GPT2_MEDIUM,
    GPT2_SMALL,
    LLAMA2_7B,
    LLAMA3_8B,
    Bert,
    GPT2,
    Llama,
    Transformer,
    TransformerConfig,
    causal_lm_loss,
    mlm_loss,
)
