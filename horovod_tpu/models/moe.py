"""Mixture-of-Experts layer with expert parallelism over the mesh.

The reference provides the EP *primitive* — alltoall with uneven splits
(/root/reference/horovod/common/operations.cc:1858, SURVEY.md §2.5 row
"Alltoall (EP building block)") — but no MoE layer; users were expected to
build one on top. Here it is first-class, TPU-first:

* top-k token routing with an auxiliary load-balancing loss (the standard
  switch/mixtral recipe);
* **dense path** (no `ep` axis bound): every device computes all experts —
  correct at any scale, optimal single-chip;
* **expert-parallel path** (`ep` axis bound inside shard_map): experts are
  sharded over the ep axis and tokens reach their experts via
  `lax.all_to_all` over ICI — the XLA-native form of the reference's
  alltoall-based EP. Capacity-factor dropping keeps shapes static for XLA.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..core import basics


class MoeMlp(nn.Module):
    """Top-k routed expert MLP (SwiGLU experts).

    Args mirror TransformerConfig naming; `ep_axis` names the mesh axis
    experts shard over when bound (num_experts must divide by its size).
    """

    hidden_size: int
    mlp_dim: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    ep_axis: str = "ep"
    dtype: Any = jnp.bfloat16
    router_aux_weight: float = 0.01

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        """[tokens, hidden] -> ([tokens, hidden], aux_loss)."""
        t, h = x.shape
        e, k = self.num_experts, self.top_k

        router = nn.Dense(e, dtype=jnp.float32, name="router")
        logits = router(x.astype(jnp.float32))           # [t, e]
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = lax.top_k(probs, k)        # [t, k]
        gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

        # load-balancing aux loss (Switch Transformer eq. 4)
        me = jnp.mean(probs, axis=0)                     # [e]
        ce = jnp.mean(
            jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32), axis=0
        )
        aux = self.router_aux_weight * e * jnp.sum(me * ce)

        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(),
            (e, h, 2 * self.mlp_dim), jnp.float32,
        ).astype(self.dtype)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(),
            (e, self.mlp_dim, h), jnp.float32,
        ).astype(self.dtype)

        ep = self._ep_size()
        if ep > 1:
            y = self._expert_parallel(x, gate_idx, gate_vals, w_in, w_out, ep)
        else:
            y = self._dense(x, gate_idx, gate_vals, w_in, w_out)
        return y.astype(x.dtype), aux

    # ---------------------------------------------------------------- dense

    def _dense(self, x, gate_idx, gate_vals, w_in, w_out):
        """All experts on every device: one einsum over the expert dim."""
        xc = x.astype(self.dtype)
        up = jnp.einsum("th,ehm->tem", xc, w_in)          # [t, e, 2m]
        g, u = jnp.split(up, 2, axis=-1)
        act = jax.nn.silu(g) * u
        per_expert = jnp.einsum("tem,emh->teh", act, w_out)  # [t, e, h]
        mask = jax.nn.one_hot(
            gate_idx, self.num_experts, dtype=self.dtype
        )                                                  # [t, k, e]
        weights = jnp.einsum(
            "tke,tk->te", mask, gate_vals.astype(self.dtype)
        )
        return jnp.einsum("teh,te->th", per_expert, weights)

    # ------------------------------------------------------ expert parallel

    def _expert_parallel(self, x, gate_idx, gate_vals, w_in, w_out, ep):
        """Capacity-bucketed dispatch via all_to_all over the ep axis.

        Each device holds num_experts/ep experts (its shard of w_in/w_out
        is selected by ep rank). Token shards are dispatched: every device
        builds [e, capacity, h] buckets, all_to_all rotates the expert dim
        so device j receives the buckets for its experts from every peer,
        computes, and the reverse all_to_all returns results.
        """
        t, h = x.shape
        e, k = self.num_experts, self.top_k
        local_e = e // ep
        capacity = int(self.capacity_factor * k * t / e) + 1

        # position of each (token, k) within its expert's bucket
        flat_idx = gate_idx.reshape(-1)                    # [t*k]
        onehot = jax.nn.one_hot(flat_idx, e, dtype=jnp.int32)
        pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot  # 1-based
        pos = jnp.sum(pos_in_expert, axis=-1) - 1            # [t*k]
        keep = pos < capacity                                 # drop overflow

        xc = x.astype(self.dtype)
        tok = jnp.repeat(jnp.arange(t), k)
        buckets = jnp.zeros((e, capacity, h), self.dtype)
        buckets = buckets.at[
            jnp.where(keep, flat_idx, 0),
            jnp.where(keep, pos, 0),
        ].add(jnp.where(keep[:, None], xc[tok], 0))

        # [e, c, h] -> [ep, local_e, c, h]; all_to_all over ep axis swaps
        # the leading ep dim with the device dim (ICI all-to-all)
        buckets = buckets.reshape(ep, local_e, capacity, h)
        recv = lax.all_to_all(
            buckets, self.ep_axis, split_axis=0, concat_axis=0, tiled=False
        )                                  # [ep(src), local_e, c, h]

        my = lax.axis_index(self.ep_axis)
        w_in_l = lax.dynamic_slice_in_dim(w_in, my * local_e, local_e, 0)
        w_out_l = lax.dynamic_slice_in_dim(w_out, my * local_e, local_e, 0)
        up = jnp.einsum("slch,lhm->slcm", recv, w_in_l)
        g, u = jnp.split(up, 2, axis=-1)
        act = jax.nn.silu(g) * u
        out = jnp.einsum("slcm,lmh->slch", act, w_out_l)

        back = lax.all_to_all(
            out, self.ep_axis, split_axis=0, concat_axis=0, tiled=False
        )                                  # [ep, local_e, c, h] expert-major
        back = back.reshape(e, capacity, h)

        gathered = back[
            jnp.where(keep, flat_idx, 0), jnp.where(keep, pos, 0)
        ]                                  # [t*k, h]
        gathered = jnp.where(keep[:, None], gathered, 0)
        weighted = gathered * gate_vals.reshape(-1, 1).astype(self.dtype)
        return jnp.zeros((t, h), self.dtype).at[tok].add(weighted)

    def _ep_size(self) -> int:
        sizes = basics.bound_axis_sizes()
        return sizes.get(self.ep_axis, 1)
