"""Transformer model family: GPT-2, BERT-Large, Llama.

Benchmark vehicles from BASELINE.json configs: BERT-Large pretraining
(tokens/sec/chip), Adasum on Llama-2-7B, elastic GPT-2. The reference
repo has no transformer implementations of its own (it wraps torchvision /
keras / user models) — these are TPU-first implementations built for this
framework's benchmarks and examples.

TPU-first choices:
  * bfloat16 activations/weights (LM head included) with float32 layernorm; logits upcast to float32 inside the loss
  * shapes padded to MXU tiles (head_dim multiples of 128 recommended)
  * pluggable attention: `attention_fn` lets the parallel layer swap in
    ring attention (parallel/ring_attention.py) or Ulysses all-to-all
    (parallel/ulysses.py) without touching model code
  * optional per-block remat (`jax.checkpoint`) for HBM-bound configs
  * params stay plain arrays; tensor/FSDP sharding rules live externally
    in parallel/sharding.py (path-pattern → PartitionSpec over dp/fsdp/tp
    axes) so pjit shards them and XLA inserts the collectives.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None = MHA
    hidden_size: int = 768
    mlp_ratio: float = 4.0
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16
    # architecture switches
    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    position: str = "learned"  # "learned" | "rope" | "none"
    activation: str = "gelu"  # "gelu" | "swiglu"
    causal: bool = True
    tie_embeddings: bool = True
    remat: bool = False
    rope_theta: float = 10000.0
    layernorm_epsilon: float = 1e-5
    # pallas single-pass norm kernels (ops/pallas_layernorm.py); XLA's
    # standalone layernorm fusions measured ~9x off the HBM floor on the
    # BERT-L bench (docs/benchmarks.md)
    fused_norm: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def mlp_dim(self) -> int:
        return int(self.hidden_size * self.mlp_ratio)


# -- named configs ----------------------------------------------------------

GPT2_SMALL = TransformerConfig(
    vocab_size=50257, num_layers=12, num_heads=12, hidden_size=768,
    max_seq_len=1024,
)
GPT2_MEDIUM = dataclasses.replace(
    GPT2_SMALL, num_layers=24, num_heads=16, hidden_size=1024
)
GPT2_LARGE = dataclasses.replace(
    GPT2_SMALL, num_layers=36, num_heads=20, hidden_size=1280
)
BERT_BASE = TransformerConfig(
    vocab_size=30522, num_layers=12, num_heads=12, hidden_size=768,
    max_seq_len=512, causal=False,
)
BERT_LARGE = dataclasses.replace(
    BERT_BASE, num_layers=24, num_heads=16, hidden_size=1024
)
LLAMA2_7B = TransformerConfig(
    vocab_size=32000, num_layers=32, num_heads=32, hidden_size=4096,
    mlp_ratio=11008 / 4096, max_seq_len=4096, norm="rmsnorm",
    position="rope", activation="swiglu", tie_embeddings=False,
)
LLAMA3_8B = TransformerConfig(
    vocab_size=128256, num_layers=32, num_heads=32, num_kv_heads=8,
    hidden_size=4096, mlp_ratio=14336 / 4096, max_seq_len=8192,
    norm="rmsnorm", position="rope", activation="swiglu",
    tie_embeddings=False, rope_theta=500000.0,
)


# -- building blocks --------------------------------------------------------

class RMSNorm(nn.Module):
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        xf = x.astype(jnp.float32)
        scale = self.param(
            "scale", nn.initializers.ones, (x.shape[-1],), jnp.float32
        )
        y = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.epsilon
        )
        return (y * scale).astype(self.dtype)


def _norm(cfg: TransformerConfig, name: str):
    if cfg.fused_norm:
        from ..ops.pallas_layernorm import FusedLayerNorm

        return FusedLayerNorm(
            epsilon=cfg.layernorm_epsilon, dtype=cfg.dtype,
            param_dtype=jnp.float32, kind=cfg.norm, name=name)
    if cfg.norm == "rmsnorm":
        return RMSNorm(epsilon=cfg.layernorm_epsilon, dtype=cfg.dtype,
                       name=name)
    return nn.LayerNorm(epsilon=cfg.layernorm_epsilon, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name=name)


def rope_frequencies(head_dim: int, max_len: int, theta: float):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(max_len)
    freqs = np.outer(t, inv)  # [T, D/2]
    return jnp.asarray(np.cos(freqs)), jnp.asarray(np.sin(freqs))


def apply_rope(x, cos, sin, positions):
    """x: [B, T, H, D]; positions: [B, T] absolute positions (so sequence-
    parallel shards pass their global offsets)."""
    c = cos[positions][:, :, None, :]  # [B, T, 1, D/2]
    s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def cached_attention(q, k, v, valid):
    """Attention of new-token queries over a KV cache slice.

    q ``[B, T, H, D]`` (the T tokens being appended this call — the
    whole prompt at prefill, one token at decode); k/v ``[B, KH, M, D]``
    (the cache layout's per-layer slice, already containing the new
    rows); ``valid`` ``[B, T, M]`` bool — cache position j is
    attendable by query t iff ``j <= position(t)``, which is both the
    causal mask and the "written yet" mask (rows above a slot's length
    hold stale bytes from the slot's previous occupant).

    float32 softmax accumulation like :func:`dot_product_attention`;
    masked positions get -1e30 so stale-but-finite cache rows
    contribute exactly zero probability.
    """
    B, T, H, D = q.shape
    KH = k.shape[1]
    if KH != H:  # GQA: repeat kv heads
        rep = H // KH
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bthd,bhmd->bhtm", q, k).astype(jnp.float32) * scale
    logits = jnp.where(valid[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhtm,bhmd->bthd", probs, v)


def dot_product_attention(q, k, v, *, causal: bool, mask=None):
    """Default attention: q,k,v [B, T, H, D] -> [B, T, H, D].

    float32 softmax accumulation on bf16 inputs (TPU-stable). Swappable via
    `attention_fn` for ring/Ulysses sequence parallelism.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # GQA: repeat kv heads
    if k.shape[2] != H:
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        cm = jnp.tril(jnp.ones((Tq, Tk), dtype=bool))
        logits = jnp.where(cm[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class Attention(nn.Module):
    cfg: TransformerConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions, mask=None, kv_cache=None, layer=0):
        cfg = self.cfg
        B, T, _ = x.shape
        H, KH, D = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        dense = functools.partial(
            nn.DenseGeneral, dtype=cfg.dtype, param_dtype=jnp.float32,
            use_bias=cfg.norm == "layernorm",
        )
        q = dense(features=(H, D), name="query",
                  kernel_init=nn.initializers.xavier_uniform())(x)
        k = dense(features=(KH, D), name="key",
                  kernel_init=nn.initializers.xavier_uniform())(x)
        v = dense(features=(KH, D), name="value",
                  kernel_init=nn.initializers.xavier_uniform())(x)
        if cfg.position == "rope":
            cos, sin = rope_frequencies(D, cfg.max_seq_len, cfg.rope_theta)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
        if kv_cache is not None:
            # autoregressive serving path (serving/decode.py): the
            # new tokens' K/V append into the slotted cache (quantized
            # there when the cache is int8 — rows are quantized once,
            # on write, never re-quantized) and attention runs over
            # the full cache slice under the position-validity mask
            if mask is not None:
                raise ValueError(
                    "kv_cache decoding derives its own validity mask "
                    "from positions; an explicit padding mask is not "
                    "composable with it")
            appender = getattr(kv_cache, "append_attend", None)
            if appender is not None:
                # fused append+attend (serving/decode.py): one kernel
                # per batch row under the fused-collectives knob, the
                # exact update + cached_attention lowering otherwise
                out = appender(layer, q, k, v, positions)
            else:
                k_full, v_full, valid = kv_cache.update(
                    layer, k, v, positions)
                out = cached_attention(q, k_full, v_full, valid)
        elif self.attention_fn is None:
            attn = functools.partial(
                dot_product_attention, causal=cfg.causal)
            out = attn(q, k, v, mask=mask)
        else:
            attn = self.attention_fn
            if mask is not None:
                raise ValueError(
                    "a custom attention_fn (flash/ring/Ulysses) takes only "
                    "(q, k, v) and would silently drop the padding mask; "
                    "pre-mask the inputs or use the default attention"
                )
            out = attn(q, k, v)
        out = nn.DenseGeneral(
            features=cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
            param_dtype=jnp.float32, use_bias=cfg.norm == "layernorm",
            name="out",
            kernel_init=nn.initializers.xavier_uniform(),
        )(out)
        return out


class Mlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = functools.partial(
            nn.Dense, dtype=cfg.dtype, param_dtype=jnp.float32,
            use_bias=cfg.norm == "layernorm",
        )
        if cfg.activation == "swiglu":
            gate = dense(cfg.mlp_dim, name="gate",
                         kernel_init=nn.initializers.xavier_uniform())(x)
            up = dense(cfg.mlp_dim, name="up",
                       kernel_init=nn.initializers.xavier_uniform())(x)
            h = nn.silu(gate) * up
        else:
            h = dense(cfg.mlp_dim, name="fc1",
                      kernel_init=nn.initializers.xavier_uniform())(x)
            h = nn.gelu(h)
        return dense(cfg.hidden_size, name="fc2",
                     kernel_init=nn.initializers.xavier_uniform())(h)


class Block(nn.Module):
    cfg: TransformerConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions, mask=None, kv_cache=None, layer=0):
        cfg = self.cfg
        y = _norm(cfg, "ln_attn")(x)
        x = x + Attention(cfg, attention_fn=self.attention_fn,
                          name="attn")(y, positions, mask,
                                       kv_cache=kv_cache, layer=layer)
        y = _norm(cfg, "ln_mlp")(x)
        x = x + Mlp(cfg, name="mlp")(y)
        return x


class Transformer(nn.Module):
    """Decoder/encoder stack with LM head; covers GPT-2 (causal + learned
    pos), BERT (bidirectional) and Llama (causal + rope/rms/swiglu)."""

    cfg: TransformerConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens, positions=None, mask=None,
                 return_hidden=False, kv_cache=None):
        """``kv_cache`` opens the autoregressive serving path: a
        duck-typed cache carrier (``update(layer, k, v, positions) ->
        (k_full, v_full, valid)``, serving/decode.SlottedKVCache) whose
        buffers the caller threads through its compiled step. With it,
        ``tokens`` are the NEW tokens only (the whole prompt at
        prefill, one token per sequence at decode) and ``positions``
        their absolute positions; attention runs over the cache, not
        the ``tokens`` window. ``None`` (every training/one-shot path)
        is byte-identical to the pre-cache model."""
        cfg = self.cfg
        B, T = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        emb = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="tok_emb",
            embedding_init=nn.initializers.normal(0.02),
        )
        x = emb(tokens)
        if cfg.position == "learned":
            pos_emb = self.param(
                "pos_emb",
                nn.initializers.normal(0.02),
                (cfg.max_seq_len, cfg.hidden_size),
                jnp.float32,
            )
            x = x + pos_emb[positions].astype(cfg.dtype)

        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=())
        for i in range(cfg.num_layers):
            if kv_cache is None:
                # training/one-shot path: exact pre-cache call shape so
                # remat'd and jitted programs lower identically
                x = block(cfg, attention_fn=self.attention_fn,
                          name=f"block_{i}")(x, positions, mask)
            else:
                x = block(cfg, attention_fn=self.attention_fn,
                          name=f"block_{i}")(x, positions, mask,
                                             kv_cache=kv_cache, layer=i)
        x = _norm(cfg, "ln_final")(x)
        if return_hidden:
            # pre-head activations for the fused LM-head cross-entropy
            # (ops/fused_cross_entropy.py) — the [B, T, V] logits are
            # never materialized on that path. Initialize with the
            # default return_hidden=False so head params exist.
            return x
        # LM head matmul stays in the model compute dtype (bf16 on the
        # MXU fast path — an f32 [B,T,H]x[H,V] here is the single
        # largest matmul in the model at a fraction of peak); the loss
        # fns upcast the logits to f32 for logsumexp stability.
        if cfg.tie_embeddings:
            logits = emb.attend(x)
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=jnp.float32, name="lm_head",
                kernel_init=nn.initializers.normal(0.02),
            )(x)
        return logits


# -- task heads / losses ----------------------------------------------------

def _gather_nll(lg, targets):
    """Per-position cross-entropy via gather: logsumexp(lg) - lg[target].
    One pass over the [B, T, V] logits instead of materializing a
    [B, T, V] float32 one-hot AND a log_softmax copy — at BERT/GPT vocab
    sizes those intermediates are hundreds of MB of pure HBM traffic."""
    lse = jax.scipy.special.logsumexp(lg, axis=-1)
    tgt = jnp.take_along_axis(lg, targets[..., None], axis=-1)[..., 0]
    return lse - tgt


def causal_lm_loss(logits, tokens, ignore_index: int = -1):
    """Next-token cross-entropy; returns (loss, n_tokens). float32."""
    targets = tokens[:, 1:]
    lg = logits[:, :-1].astype(jnp.float32)
    valid = targets != ignore_index
    # out-of-range ids (sentinels, padding artifacts) must not index the
    # gather — one_hot gave them a zero row, i.e. zero contribution
    in_range = (targets >= 0) & (targets < lg.shape[-1])
    nll = _gather_nll(lg, jnp.where(in_range, targets, 0))
    nll = jnp.where(valid & in_range, nll, 0.0)
    n = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / n, n


def mlm_loss(logits, labels, mask_positions):
    """BERT masked-LM loss: `labels` at `mask_positions` (bool [B,T])."""
    lg = logits.astype(jnp.float32)
    in_range = (labels >= 0) & (labels < lg.shape[-1])
    nll = _gather_nll(lg, jnp.where(in_range, labels, 0))
    nll = jnp.where(mask_positions & in_range, nll, 0.0)
    n = jnp.maximum(jnp.sum(mask_positions), 1)
    return jnp.sum(nll) / n, n


def GPT2(cfg: TransformerConfig = GPT2_SMALL, **kw) -> Transformer:
    return Transformer(cfg, **kw)


def Bert(cfg: TransformerConfig = BERT_LARGE, **kw) -> Transformer:
    return Transformer(cfg, **kw)


def Llama(cfg: TransformerConfig = LLAMA2_7B, **kw) -> Transformer:
    return Transformer(cfg, **kw)
