"""VGG-16 — the reference's bandwidth-bound scaling model.

BASELINE.md row 3: 68% scaling efficiency for VGG-16 at 512 GPUs
(reference docs/benchmarks.rst:8-13) — VGG's 138M parameters (124M in
the fc layers alone) make it the gradient-allreduce stress test of the
benchmark trio; the reproduction vehicle is tf_cnn_benchmarks
`--model vgg16`. TPU-first flax implementation: NHWC, bfloat16 compute,
f32 params; the classifier keeps the original 4096-wide fc stack because
those dense gradients ARE the benchmark (they dominate wire traffic).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# channels per conv, "M" = 2x2 max-pool (the 13-conv "D" configuration)
_VGG16_CFG: Sequence = (
    64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
    512, 512, 512, "M", 512, 512, 512, "M",
)


class VGG16(nn.Module):
    """With nonzero `dropout`, training calls must supply the stream:
    `model.apply(vars, x, train=True, rngs={"dropout": key})` — flax
    raises otherwise. The synthetic benchmark trains with dropout=0."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for spec in _VGG16_CFG:
            if spec == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(spec, (3, 3), padding="SAME",
                            dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)  # 7x7x512 = 25088
        for width in (4096, 4096):
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
            if self.dropout and train:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32)(x)
