"""ResNet v1.5 family (ResNet-50/101/152) — the synthetic-benchmark model.

Reference vehicle: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py (torchvision /
keras ResNet50; BASELINE.md rows 1-4 are ResNet/Inception/VGG scaling).

TPU-first choices: NHWC layout (TPU conv native), bfloat16 compute with
float32 batch-norm statistics and parameters, v1.5 stride placement
(stride on the 3x3, like torchvision), SyncBatchNorm optional via
horovod_tpu.optim.sync_batch_norm (the reference ships hvd.SyncBatchNorm,
torch/sync_batch_norm.py:40).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x, block: int = 2):
    """[B, H, W, C] → [B, H/b, W/b, C·b²] (pixel-shuffle inverse)."""
    B, H, W, C = x.shape
    if H % block or W % block:
        raise ValueError(
            f"space_to_depth needs H and W divisible by {block}; "
            f"got {H}x{W} (pad or resize the input)"
        )
    x = x.reshape(B, H // block, block, W // block, block, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        B, H // block, W // block, C * block * block
    )


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    norm_cls: Optional[ModuleDef] = None  # override e.g. with SyncBatchNorm
    # "conv" = the paper's 7x7/s2 stem; "space_to_depth" rewrites it as
    # a 2x2 pixel-unshuffle + 4x4/s1 conv on 12 channels — equivalent
    # downsampling with an 8x8 effective footprint (the MLPerf transform
    # zero-pads the 7x7 kernel to 8x8), and the MXU sees 12 input
    # channels instead of 3 (a 3-channel conv leaves >95% of the lanes
    # idle)
    stem: str = "conv"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        if self.norm_cls is not None:
            norm = functools.partial(self.norm_cls, use_running_average=not train)
        else:
            norm = functools.partial(
                nn.BatchNorm,
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), (1, 1), name="conv_init")(x)
        elif self.stem == "conv":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r}: expected 'conv' or "
                "'space_to_depth'"
            )
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3])
