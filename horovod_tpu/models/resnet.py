"""ResNet v1.5 family (ResNet-50/101/152) — the synthetic-benchmark model.

Reference vehicle: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py (torchvision /
keras ResNet50; BASELINE.md rows 1-4 are ResNet/Inception/VGG scaling).

TPU-first choices: NHWC layout (TPU conv native), bfloat16 compute with
float32 batch-norm statistics and parameters, v1.5 stride placement
(stride on the 3x3, like torchvision), SyncBatchNorm optional via
horovod_tpu.optim.sync_batch_norm (the reference ships hvd.SyncBatchNorm,
torch/sync_batch_norm.py:40).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class ChannelDot(nn.Module):
    """1x1 convolution expressed as a channel matmul (`dot_general` over
    the trailing axis). Numerically identical to nn.Conv(k=(1,1)); on
    TPU it lowers to the dot path whose prologue/epilogue fusions
    pipeline differently from conv_general_dilated — selectable via
    ResNet(one_by_one="dot") to pick whichever benches faster."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(batch_axis=(), in_axis=-2,
                                         out_axis=-1),
            (1, 1, x.shape[-1], self.features), jnp.float32)
        if self.strides != (1, 1):
            x = x[:, ::self.strides[0], ::self.strides[1], :]
        y = jax.lax.dot_general(
            x.astype(self.dtype),
            kernel.reshape(x.shape[-1], self.features).astype(self.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
        )
        return y


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    # 1x1 convolutions factory: (features, strides) -> module; defaults
    # to `conv` with a (1, 1) kernel (see ResNet.one_by_one)
    conv1x1: Optional[ModuleDef] = None
    # fused BN+relu(+residual) epilogues (pallas kernels); when set,
    # `norm` must be a FusedBatchNorm factory and `act` is folded in
    fused_bn: bool = False

    def _c1(self, features, strides=(1, 1), name=None):
        if self.conv1x1 is not None:
            return self.conv1x1(features, strides, name=name)
        return self.conv(features, (1, 1), strides, name=name)

    @nn.compact
    def __call__(self, x):
        residual = x
        if self.fused_bn:
            y = self._c1(self.filters)(x)
            y = self.norm(activation="relu")(y)
            y = self.conv(self.filters, (3, 3), self.strides)(y)
            y = self.norm(activation="relu")(y)
            y = self._c1(self.filters * 4)(y)
            if residual.shape[-1] != self.filters * 4 or self.strides != (
                    1, 1):
                residual = self._c1(
                    self.filters * 4, self.strides,
                    name="conv_proj")(residual)
                residual = self.norm(name="norm_proj")(residual)
            return self.norm(scale_init=nn.initializers.zeros,
                             activation="relu")(y, residual=residual)
        y = self._c1(self.filters)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self._c1(self.filters * 4)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self._c1(
                self.filters * 4, self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x, block: int = 2):
    """[B, H, W, C] → [B, H/b, W/b, C·b²] (pixel-shuffle inverse)."""
    B, H, W, C = x.shape
    if H % block or W % block:
        raise ValueError(
            f"space_to_depth needs H and W divisible by {block}; "
            f"got {H}x{W} (pad or resize the input)"
        )
    x = x.reshape(B, H // block, block, W // block, block, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        B, H // block, W // block, C * block * block
    )


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    norm_cls: Optional[ModuleDef] = None  # override e.g. with SyncBatchNorm
    # "conv" = the paper's 7x7/s2 stem; "space_to_depth" rewrites it as
    # a 2x2 pixel-unshuffle + 4x4/s1 conv on 12 channels — equivalent
    # downsampling with an 8x8 effective footprint (the MLPerf transform
    # zero-pads the 7x7 kernel to 8x8), and the MXU sees 12 input
    # channels instead of 3 (a 3-channel conv leaves >95% of the lanes
    # idle)
    stem: str = "conv"
    # pallas fused BN+relu(+residual) epilogues instead of
    # flax.linen.BatchNorm (ops/pallas_batchnorm.py) — the BN statistics
    # passes are the measured CNN bottleneck (docs/benchmarks.md)
    fused_bn: bool = False
    # "conv" lowers 1x1 convs via conv_general_dilated; "dot" via a
    # channel matmul (ChannelDot) whose TPU fusion pipeline differs
    one_by_one: str = "conv"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        if self.fused_bn and self.norm_cls is not None:
            raise ValueError(
                "fused_bn=True conflicts with norm_cls: the fused pallas "
                "epilogues replace the norm layer entirely")
        fused = self.fused_bn
        if self.norm_cls is not None:
            norm = functools.partial(self.norm_cls, use_running_average=not train)
        elif fused:
            from ..ops.pallas_batchnorm import FusedBatchNorm

            norm = functools.partial(
                FusedBatchNorm,
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )
        else:
            norm = functools.partial(
                nn.BatchNorm,
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), (1, 1), name="conv_init")(x)
        elif self.stem == "conv":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r}: expected 'conv' or "
                "'space_to_depth'"
            )
        if fused:
            x = norm(name="bn_init", activation="relu")(x)
        else:
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        if self.one_by_one == "dot":
            conv1x1 = functools.partial(ChannelDot, dtype=self.dtype)
        elif self.one_by_one == "conv":
            conv1x1 = None
        else:
            raise ValueError(
                f"unknown one_by_one {self.one_by_one!r}: expected "
                "'conv' or 'dot'")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                    conv1x1=conv1x1,
                    fused_bn=fused,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3])
