"""MNIST MLP/ConvNet — the smoke-test model family.

Reference vehicle: /root/reference/examples/pytorch/pytorch_mnist.py
(BASELINE.json configs[0]): a 2-conv + 2-fc net trained with
hvd.DistributedOptimizer. Implemented in flax.linen with NHWC layout and
bf16-friendly defaults (TPU conv/matmul native layout).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Plain MLP for quick numerics tests."""

    features: Sequence[int] = (128, 64, 10)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


class MnistNet(nn.Module):
    """The reference MNIST model shape (pytorch_mnist.py Net: conv 10/20 +
    fc 50/10), NHWC + bf16-compute variant."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        x = nn.Dense(10, dtype=self.dtype)(x)
        return x
