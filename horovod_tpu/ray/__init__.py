"""Ray integration: RayExecutor mapping actors to horovod_tpu slots.

Reference: /root/reference/horovod/ray/runner.py:168 (`RayExecutor`) +
Coordinator (:45): placement-group actors become slots; the coordinator
collects actor hostnames, computes SlotInfo, pushes env, then
start/execute/run drive the user function. Elastic variant
(ray/elastic.py:150) plugs Ray cluster state in as host discovery.

Import is gated: ray is an optional dependency.
"""

from __future__ import annotations

import socket
from typing import Any, Callable, List, Optional

from ..runner.util.hosts import HostInfo, get_host_assignments


def _require_ray():
    try:
        import ray

        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray requires ray (pip install ray); for local "
            "multi-process runs use horovod_tpu.runner.run()"
        ) from e


class RayExecutor:
    """Launch `num_workers` Ray actors as horovod_tpu slots
    (reference ray/runner.py:168)."""

    def __init__(self, num_workers: int = 1, cpus_per_worker: int = 1,
                 use_gpu: bool = False, settings=None):
        self._ray = _require_ray()
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self._workers: List[Any] = []

    def start(self, extra_env_vars: Optional[dict] = None) -> None:
        ray = self._ray

        @ray.remote
        class Worker:
            def __init__(self):
                self._env = {}

            def hostname(self):
                return socket.gethostname()

            def set_env(self, env):
                import os

                os.environ.update({k: str(v) for k, v in env.items()})

            def execute(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

        self._workers = [
            Worker.options(num_cpus=self.cpus_per_worker).remote()
            for _ in range(self.num_workers)
        ]
        hostnames = ray.get([w.hostname.remote() for w in self._workers])
        counts: dict = {}
        for h in hostnames:
            counts[h] = counts.get(h, 0) + 1
        hosts = [HostInfo(h, c) for h, c in counts.items()]
        slots = get_host_assignments(hosts, self.num_workers,
                                     self.num_workers)
        by_host: dict = {}
        coordinator = hostnames[0]
        env_sets = []
        for w, hostname in zip(self._workers, hostnames):
            i = by_host.get(hostname, 0)
            by_host[hostname] = i + 1
            slot = next(
                s for s in slots
                if s.hostname == hostname and s.local_rank == i
            )
            env = {
                "HOROVOD_RANK": slot.rank, "HOROVOD_SIZE": slot.size,
                "HOROVOD_LOCAL_RANK": slot.local_rank,
                "HOROVOD_LOCAL_SIZE": slot.local_size,
                "HOROVOD_CROSS_RANK": slot.cross_rank,
                "HOROVOD_CROSS_SIZE": slot.cross_size,
                "HVD_TPU_PROCESS_ID": slot.rank,
                "HVD_TPU_NUM_PROCESSES": slot.size,
                "HVD_TPU_COORDINATOR_ADDRESS": f"{coordinator}:9099",
            }
            env.update(extra_env_vars or {})
            env_sets.append(w.set_env.remote(env))
        ray.get(env_sets)

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        ray = self._ray
        kwargs = kwargs or {}
        return ray.get([
            w.execute.remote(fn, *args, **kwargs) for w in self._workers
        ])

    execute = run

    def shutdown(self) -> None:
        for w in self._workers:
            self._ray.kill(w)
        self._workers = []


class ElasticRayExecutor:
    def __init__(self, *a, **kw):
        _require_ray()
        raise NotImplementedError(
            "elastic Ray jobs: plug RayHostDiscovery (ray cluster state) "
            "into horovod_tpu.runner.elastic.HostManager (reference "
            "ray/elastic.py:39 maps onto runner/elastic/discovery.py)"
        )
