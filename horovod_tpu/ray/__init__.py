"""Ray integration: RayExecutor mapping actors to horovod_tpu slots.

Reference: /root/reference/horovod/ray/runner.py:168 (`RayExecutor`) +
Coordinator (:45): placement-group actors become slots; the coordinator
collects actor hostnames, computes SlotInfo, pushes env, then
start/execute/run drive the user function. Elastic variant
(ray/elastic.py:150) plugs Ray cluster state in as host discovery.

Import is gated: ray is an optional dependency.
"""

from __future__ import annotations

import socket
import time
import traceback
from typing import Any, Callable, List, Optional

from ..runner.util.hosts import HostInfo, get_host_assignments
from ..utils.logging import LOGGER


def _require_ray():
    try:
        import ray

        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray requires ray (pip install ray); for local "
            "multi-process runs use horovod_tpu.runner.run()"
        ) from e


class RayExecutor:
    """Launch `num_workers` Ray actors as horovod_tpu slots
    (reference ray/runner.py:168)."""

    def __init__(self, num_workers: int = 1, cpus_per_worker: int = 1,
                 use_gpu: bool = False, settings=None):
        self._ray = _require_ray()
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self._workers: List[Any] = []

    def start(self, extra_env_vars: Optional[dict] = None) -> None:
        ray = self._ray

        @ray.remote
        class Worker:
            def __init__(self):
                self._env = {}

            def hostname(self):
                return socket.gethostname()

            def set_env(self, env):
                import os

                os.environ.update({k: str(v) for k, v in env.items()})

            def execute(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

        self._workers = [
            Worker.options(num_cpus=self.cpus_per_worker).remote()
            for _ in range(self.num_workers)
        ]
        hostnames = ray.get([w.hostname.remote() for w in self._workers])
        counts: dict = {}
        for h in hostnames:
            counts[h] = counts.get(h, 0) + 1
        hosts = [HostInfo(h, c) for h, c in counts.items()]
        slots = get_host_assignments(hosts, self.num_workers,
                                     self.num_workers)
        by_host: dict = {}
        coordinator = hostnames[0]
        env_sets = []
        for w, hostname in zip(self._workers, hostnames):
            i = by_host.get(hostname, 0)
            by_host[hostname] = i + 1
            slot = next(
                s for s in slots
                if s.hostname == hostname and s.local_rank == i
            )
            env = {
                "HOROVOD_RANK": slot.rank, "HOROVOD_SIZE": slot.size,
                "HOROVOD_LOCAL_RANK": slot.local_rank,
                "HOROVOD_LOCAL_SIZE": slot.local_size,
                "HOROVOD_CROSS_RANK": slot.cross_rank,
                "HOROVOD_CROSS_SIZE": slot.cross_size,
                "HVD_TPU_PROCESS_ID": slot.rank,
                "HVD_TPU_NUM_PROCESSES": slot.size,
                "HVD_TPU_COORDINATOR_ADDRESS": f"{coordinator}:9099",
            }
            env.update(extra_env_vars or {})
            env_sets.append(w.set_env.remote(env))
        ray.get(env_sets)

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        ray = self._ray
        kwargs = kwargs or {}
        return ray.get([
            w.execute.remote(fn, *args, **kwargs) for w in self._workers
        ])

    execute = run

    def shutdown(self) -> None:
        for w in self._workers:
            self._ray.kill(w)
        self._workers = []


class RayHostDiscovery:
    """Host discovery backed by Ray cluster state (reference
    ray/elastic.py:39): every alive node contributes
    floor(CPU / cpus_per_slot) slots (bounded by GPUs when use_gpu).
    Plugs into runner/elastic/discovery.HostManager unchanged."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = max(1, cpus_per_slot)
        self.gpus_per_slot = max(1, gpus_per_slot)

    def find_available_hosts_and_slots(self) -> dict:
        ray = _require_ray()
        hosts: dict = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            res = node.get("Resources", {}) or {}
            slots = int(res.get("CPU", 0) // self.cpus_per_slot)
            if self.use_gpu:
                slots = min(
                    slots, int(res.get("GPU", 0) // self.gpus_per_slot)
                )
            # keyed by node IP: Ray's built-in `node:<ip>` resource pins
            # actors to it without node-id plumbing
            addr = node.get("NodeManagerAddress") or node.get(
                "NodeManagerHostname"
            )
            if slots > 0 and addr:
                hosts[addr] = hosts.get(addr, 0) + slots
        return hosts


# One remote-class export per process, not per slot per round: Ray
# pickles and registers every @ray.remote class with the GCS, so
# defining it inside _execute_slot would re-export identical bytes for
# each slot of each elastic round.
_SLOT_WORKER_CLS = None


def _slot_worker_cls(ray):
    global _SLOT_WORKER_CLS
    if _SLOT_WORKER_CLS is None:
        class _SlotWorker:
            def ping(self):
                # scheduling probe: resolves as soon as the actor is
                # placed and running on its node
                return True

            def execute(self, fn, env, args, kwargs):
                import os

                os.environ.update({k: str(v) for k, v in env.items()})
                return fn(*args, **kwargs)

        _SLOT_WORKER_CLS = ray.remote(max_restarts=0)(_SlotWorker)
    return _SLOT_WORKER_CLS


class ElasticRayExecutor:
    """Elastic training on a dynamic Ray cluster (reference
    ray/elastic.py:150): the elastic driver's discovery is Ray cluster
    state, its slots are Ray actors pinned to the discovered nodes, and
    failed nodes are blacklisted while training resumes on the rest."""

    def __init__(self, min_np: int = 1, max_np: Optional[int] = None,
                 cpus_per_slot: int = 1, use_gpu: bool = False,
                 override_discovery=None, env: Optional[dict] = None,
                 elastic_timeout_s: float = 600.0, reset_limit: int = 0):
        self._ray = _require_ray()
        self._discovery = override_discovery or RayHostDiscovery(
            use_gpu=use_gpu, cpus_per_slot=cpus_per_slot
        )
        self._min_np = min_np
        self._max_np = max_np  # None = unbounded (scale to the cluster)
        self._env = dict(env or {})
        self._timeout_s = elastic_timeout_s
        self._reset_limit = reset_limit
        self._host_manager = None
        self._results: dict = {}
        self._last_error: Optional[BaseException] = None

    def start(self) -> None:
        from ..runner.elastic.discovery import HostManager

        self._host_manager = HostManager(self._discovery)

    def _execute_slot(self, fn, args, kwargs, env, slot, events):
        """Run `fn` in a Ray actor pinned to the slot's node; the round
        abort event kills the actor (classified ABORTED, like a launcher
        SIGTERM). Returns (exit_code, result_or_None)."""
        ray = self._ray
        # Scheduling deadline only: the node:<ip> pin is a resource no
        # node may provide (e.g. discovery fell back to a hostname key,
        # or the node died after discovery) — without a deadline the
        # actor stays pending forever and the round barrier never
        # completes. Once the ping resolves the actor is placed, and
        # execution runs as long as training needs (a wall-clock cap on
        # fn would kill every legitimately long job).
        sched_deadline = time.monotonic() + self._timeout_s
        scheduled = False
        try:
            actor = _slot_worker_cls(ray).options(
                resources={f"node:{slot.hostname}": 0.001}
            ).remote()
            ping = actor.ping.remote()
            ref = actor.execute.remote(fn, env, args, kwargs)
            while True:
                done, _ = ray.wait([ref], timeout=0.5)
                if done:
                    return 0, ray.get(done[0])
                if events and any(e.is_set() for e in events):
                    ray.kill(actor)
                    # signal-like: round abort, not this slot's failure
                    return -15, None
                if not scheduled:
                    pdone, _ = ray.wait([ping], timeout=0)
                    if pdone:
                        scheduled = True
                    elif time.monotonic() > sched_deadline:
                        ray.kill(actor)
                        raise TimeoutError(
                            f"slot {slot.rank}: no Ray node provides "
                            f"node:{slot.hostname} after "
                            f"{self._timeout_s}s — actor unschedulable"
                        )
        except Exception as e:
            self._last_error = e
            LOGGER.error(
                "elastic Ray slot %d on %s failed:\n%s",
                slot.rank, slot.hostname, traceback.format_exc(),
            )
            return 1, None

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        from ..runner.elastic.driver import ElasticDriver
        from ..runner.elastic.settings import ElasticSettings

        if self._host_manager is None:
            self.start()
        kwargs = kwargs or {}
        # results keyed by (round, rank): slots that finished inside a
        # later-aborted round must not leak into the final return
        self._results = {}
        self._last_error = None

        def exec_fn(command, env, slot, events):
            # late binding: exec_fn only runs inside driver.run(), after
            # `driver` below is bound
            round_id = driver._registry.round
            code, value = self._execute_slot(
                fn, args, kwargs, env, slot, events
            )
            if code == 0:
                self._results[(round_id, slot.rank)] = value
            return code

        driver = ElasticDriver(
            self._host_manager,
            ElasticSettings(
                min_np=self._min_np, max_np=self._max_np,
                timeout_s=self._timeout_s, reset_limit=self._reset_limit,
            ),
            command=["<ray-actor>"],  # exec_fn ignores it
            env=self._env,
            exec_fn=exec_fn,
        )
        rc = driver.run()
        if rc != 0:
            raise RuntimeError(
                "elastic Ray job failed"
            ) from self._last_error
        final_round = driver._registry.round
        final = {
            rank: v for (rid, rank), v in self._results.items()
            if rid == final_round
        }
        return [final[r] for r in sorted(final)]

    def shutdown(self) -> None:
        self._host_manager = None
