"""Preemption-safe shutdown: SIGTERM/SIGINT → commit + emergency
checkpoint + a distinct "host going away" exit code.

TPU preemption (maintenance events, spot reclaim) delivers SIGTERM with
a short grace window. Without a handler the worker dies mid-step: every
step since the last manual ``state.commit()`` is lost, and the elastic
driver blacklists the host — wrong twice over, because a preempted host
was healthy and often comes back. This module closes both gaps:

* the handler snapshots the elastic state (``state.save()`` — commit
  minus the host-update interrupt, which must not fire inside a signal
  handler),
* rank 0 writes an *emergency checkpoint* — the committed snapshot
  serialized to disk (``HOROVOD_EMERGENCY_CHECKPOINT`` or an explicit
  path) so a fully-preempted job restarts from it instead of step 0,
* the process exits with :data:`PREEMPTED_EXIT_CODE`, which the
  elastic driver treats like a launcher abort: terminal for the round
  barrier, but the host is NOT blacklisted (reference semantics: only
  *failing* hosts are excluded, runner/elastic/driver.py).

``@hvd.elastic.run`` installs the handler automatically (knob
``HOROVOD_PREEMPTION``, default on); scripts outside the elastic
wrapper call :func:`install` themselves.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import signal
import threading
import time
from typing import Callable, Optional, Tuple

from ..utils import faults

LOG = logging.getLogger("horovod_tpu.elastic")

# Distinct from ordinary failures (1..~120) and shell signal codes
# (128+N): the elastic driver maps this to ABORTED, not FAILURE.
PREEMPTED_EXIT_CODE = 83

# format 2: the snapshot pickle rides inside the envelope with an
# embedded sha256 so a torn/corrupted file is *detected* instead of
# silently restoring garbage; format-1 files (pre-checksum) still load.
_EMERGENCY_FORMAT = 2


def emergency_save(state, path: str) -> str:
    """Serialize the state's committed snapshot to ``path`` atomically
    (tmp + rename) with an embedded checksum.

    The snapshot is host data by construction (ObjectState deep-copies,
    TpuState device_get's), so a plain pickle is safe inside a signal
    grace window — no orbax async machinery to flush, no device sync.
    Returns the written path.
    """
    state.save()
    saved_bytes = pickle.dumps(
        state._saved, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.sha256(saved_bytes).hexdigest()
    # digest first, corrupt second: an `emergency.payload:corrupt`
    # rule simulates on-disk damage, which the embedded sha256 must
    # catch on restore (utils/faults.py)
    saved_bytes = faults.corrupt("emergency.payload", saved_bytes)
    payload = {
        "format": _EMERGENCY_FORMAT,
        "time_unix": time.time(),
        "epoch": int(getattr(state, "_commit_count", 0)),
        "sha256": digest,
        "saved_pickle": saved_bytes,
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def emergency_read(path: str) -> Tuple[int, dict]:
    """Load and checksum-verify an emergency snapshot: returns
    ``(commit_epoch, saved_dict)``. Raises ``ValueError`` on an unknown
    format or a checksum mismatch, ``OSError``/``pickle`` errors on a
    missing or truncated file — the recovery ladder catches all of
    these and falls through to the next rung (elastic/replication.py).
    """
    with open(path, "rb") as f:
        payload = pickle.load(f)
    fmt = payload.get("format")
    if fmt == 1:  # pre-checksum files: no integrity to verify
        return 0, payload["saved"]
    if fmt != _EMERGENCY_FORMAT:
        raise ValueError(
            f"unknown emergency checkpoint format in {path}: {fmt!r}"
        )
    saved_bytes = payload["saved_pickle"]
    digest = hashlib.sha256(saved_bytes).hexdigest()
    if digest != payload.get("sha256"):
        raise ValueError(
            f"emergency checkpoint {path} failed checksum verification "
            f"(stored {payload.get('sha256')!r}, computed {digest!r})"
        )
    return int(payload.get("epoch", 0)), pickle.loads(saved_bytes)


def emergency_restore(state, path: str) -> None:
    """Load an emergency snapshot into ``state`` and restore it. The
    snapshot's keys must be attributes the state already registered —
    restarting with a differently-shaped state is a real error. Raises
    on a corrupt/truncated file; inside the recovery ladder that raise
    becomes a warning and a fall-through to the next rung."""
    epoch, saved = emergency_read(path)
    unknown = [k for k in saved if k not in state._known]
    if unknown:
        raise ValueError(
            f"emergency checkpoint {path} carries unregistered state "
            f"attributes {unknown}; registered: {state._known}"
        )
    state._saved = saved
    state.restore()
    if epoch:
        state._commit_count = max(
            int(getattr(state, "_commit_count", 0)), epoch)


def _is_rank0() -> bool:
    return int(os.environ.get(
        "HOROVOD_RANK", os.environ.get("HVD_TPU_RANK", "0")) or 0) == 0


class PreemptionHandler:
    """Installable SIGTERM/SIGINT handler. One per process; re-install
    just updates the state/path it commits."""

    def __init__(self) -> None:
        # RLock: the handler runs on the main thread and may interrupt
        # install()/uninstall() mid-critical-section — a plain Lock
        # would self-deadlock
        self._lock = threading.RLock()
        self._installed_signals: dict = {}
        self._state = None
        self._checkpoint_path: Optional[str] = None
        self._on_preempt: Optional[Callable[[], None]] = None
        self._exit: Callable[[int], None] = os._exit
        self._fired = False

    @property
    def installed(self) -> bool:
        return bool(self._installed_signals)

    def install(
        self,
        state=None,
        checkpoint_path: Optional[str] = None,
        signals=(signal.SIGTERM,),
        on_preempt: Optional[Callable[[], None]] = None,
        exit_fn: Optional[Callable[[int], None]] = None,
    ) -> bool:
        """Arm the handler. Returns False when signal handlers cannot
        be installed from this thread (signal.signal is main-thread
        only) — callers degrade to unhandled-signal behavior."""
        with self._lock:
            self._state = state
            self._checkpoint_path = checkpoint_path or None
            self._on_preempt = on_preempt
            if exit_fn is not None:
                self._exit = exit_fn
            for sig in signals:
                if sig in self._installed_signals:
                    continue
                try:
                    prev = signal.signal(sig, self._handle)
                except ValueError:  # not the main thread
                    return False
                self._installed_signals[sig] = prev
            self._fired = False
            return True

    def uninstall(self) -> None:
        with self._lock:
            for sig, prev in self._installed_signals.items():
                try:
                    signal.signal(sig, prev)
                except ValueError:
                    pass
            self._installed_signals = {}
            self._state = None
            self._checkpoint_path = None
            self._on_preempt = None
            self._exit = os._exit
            self._fired = False

    # ------------------------------------------------------------ handler

    def _handle(self, signum, frame) -> None:
        # idempotent: the platform may deliver SIGTERM repeatedly
        # during the grace window
        with self._lock:
            if self._fired:
                return
            self._fired = True
            state = self._state
            path = self._checkpoint_path
            on_preempt = self._on_preempt
            exit_fn = self._exit
        LOG.warning(
            "received signal %d: committing elastic state and exiting "
            "with preemption code %d", signum, PREEMPTED_EXIT_CODE,
        )
        # NO metrics recording here: the handler interrupts the main
        # thread, which may hold the registry/StepStats locks mid-
        # record (they are not reentrant) — taking them again would
        # deadlock away the whole grace window. The driver records
        # worker_preempted when it sees the exit code.
        try:
            if state is not None:
                if path and _is_rank0():
                    emergency_save(state, path)  # save()s internally
                else:
                    state.save()
            if on_preempt is not None:
                on_preempt()
        except Exception as e:
            # the exit code must still say "preempted": a failed
            # emergency write is worse logging, not a worker failure
            LOG.error("preemption commit failed: %s", e)
        # flight recorder (utils/flight.py): the last control-plane
        # moments ship to the driver before we exit. Signal-safe by
        # design — flight takes none of the metrics/StepStats locks,
        # only its own dump lock, which record() never holds. After
        # the state commit: the snapshot is the priority inside the
        # grace window, the black box rides in second.
        try:
            from ..utils import flight

            flight.record("preempt", signum=signum)
            flight.dump("preemption")
        except Exception:
            pass
        exit_fn(PREEMPTED_EXIT_CODE)


handler = PreemptionHandler()


def install(state=None, checkpoint_path: Optional[str] = None,
            **kwargs) -> bool:
    """Arm the process-wide preemption handler (see
    :class:`PreemptionHandler.install`)."""
    return handler.install(state=state, checkpoint_path=checkpoint_path,
                           **kwargs)


def uninstall() -> None:
    handler.uninstall()
