"""Elastic fault-tolerant training (hvd.elastic.* namespace).

Reference: /root/reference/horovod/common/elastic.py (State/run),
runner/elastic/ (driver, discovery, registration). Implemented in
state.py / driver.py / discovery.py here.
"""

from .state import ObjectState, State, TpuState, run  # noqa: F401
