"""Elastic fault-tolerant training (hvd.elastic.* namespace).

Reference: /root/reference/horovod/common/elastic.py (State/run),
runner/elastic/ (driver, discovery, registration). Implemented in
state.py / driver.py / discovery.py here.
"""

from . import preemption  # noqa: F401
from . import replication  # noqa: F401
from .preemption import PREEMPTED_EXIT_CODE  # noqa: F401
from .state import ObjectState, State, TpuState, run  # noqa: F401
