"""Elastic state: commit / restore / sync across world changes.

Reference: /root/reference/horovod/common/elastic.py:26 (`State`: commit,
check_host_updates, sync, restore; `ObjectState`), torch/elastic/state.py:27
(`TorchState` with per-handler save/restore/sync), and the `run_fn` wrapper
(common/elastic.py:151) that catches `HorovodInternalError` (restore +
reinit) and `HostsUpdatedInterrupt` (commit already done; resync).

TPU-native form: state lives as pytrees on the controller; `commit()`
snapshots to host memory (device_get — the analog of TorchState's
deep-copied state dicts), `restore()` puts the snapshot back, `sync()`
broadcasts from the coordinator after a world change and bumps the global
epoch so compiled collectives re-specialize to the new mesh.
"""

from __future__ import annotations

import copy
import os
import queue
import threading
from typing import Any, Callable, Dict, List, Optional

import jax

from ..core.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..utils import faults
from . import replication


class _HostUpdateFlag:
    """Worker-side mailbox the driver's notification client sets when the
    host set changes (reference: WorkerNotificationManager,
    runner/elastic/worker.py). Single-controller tests set it directly."""

    def __init__(self) -> None:
        self._updated = threading.Event()
        self._timestamp = 0

    def signal(self) -> None:
        self._timestamp += 1
        self._updated.set()

    def consume(self) -> bool:
        was = self._updated.is_set()
        self._updated.clear()
        return was


host_update_flag = _HostUpdateFlag()


class State:
    """Base elastic state (common/elastic.py:26)."""

    def __init__(self, **kwargs: Any) -> None:
        self._reset_callbacks: List[Callable] = []
        self._commit_count = 0

    def register_reset_callbacks(self, callbacks: List[Callable]) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def commit(self) -> None:
        """Snapshot state and surface pending host updates
        (common/elastic.py:60: save + check_host_updates)."""
        # the in-worker chaos hook: `worker:kill:rank=R:step=N` dies at
        # this rank's Nth commit — the deterministic mid-training
        # worker death chaos tests are built on (utils/faults.py)
        self._commit_count += 1
        if faults.enabled():
            faults.inject(
                "worker",
                rank=int(os.environ.get("HOROVOD_RANK", "0") or 0),
                step=self._commit_count,
            )
        self.save()
        # async peer replication (elastic/replication.py): hand the
        # committed snapshot to the background replicator. A single
        # predicted branch when HOROVOD_REPLICATION is off; a dict-
        # reference stash + notify when on — never a network round
        # trip on the commit path.
        replication.on_commit(self)
        self.check_host_updates()

    def check_host_updates(self) -> None:
        if host_update_flag.consume():
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


class ObjectState(State):
    """Elastic state of plain python attributes (common/elastic.py:118):
    snapshot by deepcopy, sync by coordinator broadcast_object."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._known = list(kwargs)
        self.save()

    def register(self, name: str) -> None:
        """Track an attribute added after construction (used by the
        elastic callbacks to attach batch/epoch cursors to an existing
        state)."""
        if name not in self._known:
            self._known.append(name)
            self._saved[name] = copy.deepcopy(getattr(self, name))

    def save(self) -> None:
        self._saved = {k: copy.deepcopy(getattr(self, k)) for k in self._known}

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        from ..optim.functions import broadcast_object

        values = {k: getattr(self, k) for k in self._known}
        values = broadcast_object(values, root_rank=0)
        for k, v in values.items():
            setattr(self, k, v)
        self.save()


class TpuState(ObjectState):
    """Elastic state of jax pytrees (params / optimizer state / step),
    the TorchState analog (torch/elastic/state.py:27).

    Pytree attributes are snapshotted with `jax.device_get` (host copy —
    survives device failure) and synced by coordinator broadcast so a
    resized slice starts from identical state.
    """

    def __init__(self, **kwargs: Any) -> None:
        self._tree_keys = [
            k for k, v in kwargs.items() if _is_pytree_of_arrays(v)
        ]
        super().__init__(**kwargs)

    def register(self, name: str) -> None:
        if name not in self._known:
            v = getattr(self, name)
            if _is_pytree_of_arrays(v):
                self._tree_keys.append(name)
                self._known.append(name)
                self._saved[name] = jax.device_get(v)
            else:
                super().register(name)

    def save(self) -> None:
        self._saved = {}
        for k in self._known:
            v = getattr(self, k)
            if k in self._tree_keys:
                self._saved[k] = jax.device_get(v)
            else:
                self._saved[k] = copy.deepcopy(v)

    def restore(self) -> None:
        for k, v in self._saved.items():
            if k in self._tree_keys:
                setattr(self, k, jax.device_put(v))
            else:
                setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        from ..optim.functions import broadcast_object
        from ..optim import broadcast_parameters

        for k in self._known:
            v = getattr(self, k)
            if k in self._tree_keys:
                setattr(self, k, broadcast_parameters(v, root_rank=0))
            else:
                setattr(self, k, broadcast_object(v, root_rank=0))
        self.save()


def _is_pytree_of_arrays(v: Any) -> bool:
    leaves = jax.tree_util.tree_leaves(v)
    return bool(leaves) and all(hasattr(l, "dtype") for l in leaves)


def run(func: Callable) -> Callable:
    """Elastic run wrapper (common/elastic.py:151 run_fn).

    ``@hvd.elastic.run`` around a `train(state, ...)` function: on
    `HorovodInternalError` restore committed state, re-init the world and
    retry; on `HostsUpdatedInterrupt` just re-sync and continue. The world
    re-init path asks the runtime to rebuild its mesh (slice resize).
    """

    def wrapper(state: State, *args: Any, **kwargs: Any):
        from ..core import basics
        from ..core.state import global_state
        from ..utils import metrics

        knobs = global_state().knobs
        if knobs.preemption_enabled:
            # preemption-safe shutdown (elastic/preemption.py): SIGTERM
            # commits this state, rank 0 writes the emergency snapshot,
            # and the exit code tells the driver not to blacklist.
            # Installable only from the main thread — elsewhere we
            # degrade to unhandled-signal behavior.
            from . import preemption

            preemption.install(
                state=state,
                checkpoint_path=knobs.emergency_checkpoint or None,
            )
        if knobs.recovery_ladder:
            # layered recovery (elastic/replication.py): a restarted
            # rank adopts the freshest verified committed snapshot —
            # surviving-peer replica → emergency pickle → orbax — so a
            # respawn resumes from the last commit instead of step 0.
            # No-ops quietly when no source is configured/available.
            replication.run_recovery_ladder(
                state,
                emergency_path=knobs.emergency_checkpoint or None,
                orbax_restore=getattr(state, "orbax_restore", None),
            )
        reset_limit = knobs.reset_limit
        resets = 0
        notify_needed = False
        while True:
            try:
                if notify_needed:
                    state.on_reset()
                    notify_needed = False
                if resets:  # re-sync after a world change, not first entry
                    metrics.record_elastic_event("sync")
                state.sync()
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                metrics.record_elastic_event("reset")
                # the survivor's own RAM is the top (implicit) ladder
                # rung — record it so recovery telemetry is complete
                metrics.record_recovery_rung("local")
                state.restore()
                _reinitialize()
                notify_needed = True
            except HostsUpdatedInterrupt as e:
                metrics.record_elastic_event("hosts_updated")
                if not e.skip_sync:
                    _reinitialize()
                notify_needed = True
            resets += 1
            if reset_limit and resets >= reset_limit:
                raise RuntimeError(
                    f"elastic reset limit {reset_limit} reached"
                )

    return wrapper


def _reinitialize() -> None:
    """Tear down and re-init on the (possibly resized) device world —
    the analog of elastic.py:171-173 (shutdown + re-init Horovod)."""
    from ..core import basics

    basics.shutdown()
    basics.init()
