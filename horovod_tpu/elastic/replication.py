"""Async peer-replicated snapshots + the layered recovery ladder.

The elastic layer (state.py, the reference's common/elastic.py) keeps
committed snapshots in each worker's OWN host memory: perfect for the
survivor that catches ``HorovodInternalError``, useless for the rank
that died. Every recovery path above that used to funnel through two
fragile artifacts — a rank-0-only emergency pickle that exists only if
SIGTERM was delivered, and periodic orbax checkpoints that can be
minutes stale. This module closes the gap with two pieces
(docs/recovery.md):

**Replication** (``HOROVOD_REPLICATION=1``): every ``State.commit()``
hands the freshly committed snapshot to a background replicator thread
that ships it — pickled, chunked (``HOROVOD_REPLICATION_CHUNK_BYTES``),
sha256-checksummed and stamped with the commit epoch — to the in-memory
:class:`ReplicaStore` of ``HOROVOD_REPLICATION_PARTNERS`` ring-partner
ranks over the existing runner HTTP plane (the same scope/key KV
surface the rendezvous server speaks). Strictly off the training
critical path: the commit hook is a dict-reference hand-off under a
condition variable, the replicator coalesces to the newest pending
snapshot when it falls behind, and with replication disabled
``on_commit`` is a single predicted branch (the metrics-registry
no-op discipline, asserted by tests/test_recovery.py). A small
manifest (epoch, checksum, holders) is mirrored to the rendezvous KV
scope ``replication`` so recovery can locate replicas after the owner
died — and so the driver's ``--rendezvous-state-dir`` snapshot carries
them across a driver restart.

**Recovery ladder** (:func:`run_recovery_ladder`, called by
``hvd.elastic.run`` on entry): a restarted rank restores from the
freshest *verified* source —

    surviving-peer replica  →  emergency snapshot  →  orbax checkpoint

with checksum verification at each rung and automatic fall-through on
corruption, truncation or staleness (the peer/emergency rungs compare
commit epochs and the fresher verified snapshot wins). The chosen rung
lands in ``hvd_recovery_rung_total{rung=...}`` and the flight recorder;
a survivor's in-RAM restore records rung ``local`` from the run wrapper.

Fault points: ``replication.send`` (per-partner transport),
``replication.payload`` (``corrupt`` action — flips bytes in the
serialized snapshot so the checksum rungs are testable, utils/faults.py).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils import faults, retry

LOG = logging.getLogger("horovod_tpu.elastic")

#: scope on each worker's ReplicaStore holding partners' snapshots
REPLICA_SCOPE = "replica"
#: rendezvous KV scope: rank -> JSON list of that rank's store addresses
STORE_SCOPE = "replica_store"
#: rendezvous KV scope: rank -> manifest copy (epoch/sha256/holders)
MANIFEST_SCOPE = "replication"

DEFAULT_CHUNK_BYTES = 1 << 20
DEFAULT_DUTY_CYCLE = 0.02  # replication's bounded share of host CPU

_TIMEOUT_S = 5.0

# ---------------------------------------------------------------------------
# module state (the no-op fast path)
# ---------------------------------------------------------------------------

_enabled = False
_configured = False
_replicator: Optional["Replicator"] = None
_store: Optional["ReplicaStore"] = None
# replica payloads survive configure/shutdown cycles (elastic
# _reinitialize tears the runtime down and back up in-process; partners'
# replicas must not be lost to that round trip)
_backing: Dict[str, Dict[str, bytes]] = {}


def enabled() -> bool:
    return _enabled


def store() -> Optional["ReplicaStore"]:
    return _store


def replicator() -> Optional["Replicator"]:
    return _replicator


# ---------------------------------------------------------------------------
# raw HTTP verbs (own bounded policy — replication is best-effort and
# must not ride the control plane's 5-attempt ladder or its http.*
# fault points; chaos specs target replication.send instead)
# ---------------------------------------------------------------------------


def _route(addr: str, port: int, scope: str, key: str):
    """Shard-aware target resolution: when (addr, port) names a
    configured sharded root (HOROVOD_ROOT_ADDRS), the request must land
    on (scope, key)'s ring owner, or it bounces 421 NotOwner. Peer
    replica stores and unsharded roots pass through unchanged."""
    try:
        from ..runner.http.http_client import resolve_owner

        return resolve_owner(addr, port, scope, key)
    except Exception:
        return addr, port


def _http_put(addr: str, port: int, scope: str, key: str,
              value: bytes) -> None:
    addr, port = _route(addr, port, scope, key)
    req = urllib.request.Request(
        f"http://{addr}:{port}/{scope}/{key}", data=value, method="PUT"
    )
    with urllib.request.urlopen(req, timeout=_TIMEOUT_S):
        pass


def _http_get(addr: str, port: int, scope: str,
              key: str) -> Optional[bytes]:
    addr, port = _route(addr, port, scope, key)
    try:
        with urllib.request.urlopen(
                f"http://{addr}:{port}/{scope}/{key}",
                timeout=_TIMEOUT_S) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


# ---------------------------------------------------------------------------
# replica store (runs inside every worker)
# ---------------------------------------------------------------------------


class ReplicaStore:
    """In-worker HTTP KV store holding ring partners' snapshot chunks.

    Reuses the runner's :class:`KVStoreServer` (scope/key byte store) so
    the replication plane speaks the exact protocol the rendezvous
    already does — same client, same fault points, same ops story.
    """

    def __init__(self, backing: Optional[Dict] = None):
        from ..runner.http.http_server import KVStoreServer

        self._kv = KVStoreServer(store=backing)
        self.port = self._kv.start_server()

    def addresses(self) -> List[Tuple[str, int]]:
        from ..runner.util.network import get_local_host_addresses

        # most-routable address first (get_local_host_addresses lists
        # loopback first): a cross-host fetcher must not dial its OWN
        # loopback before the real NIC
        return [(a, self.port)
                for a in reversed(get_local_host_addresses())]

    @property
    def data(self) -> Dict[str, Dict[str, bytes]]:
        return self._kv.store

    @property
    def lock(self):
        return self._kv.lock

    def shutdown(self) -> None:
        self._kv.shutdown_server()


# ---------------------------------------------------------------------------
# replicator (background thread; one per worker process)
# ---------------------------------------------------------------------------


class Replicator:
    """Ships committed snapshots to ring partners, asynchronously.

    ``submit`` is the whole critical-path cost: stash a reference to the
    committed dict (commit rebinds ``state._saved`` to a fresh dict, so
    the reference is stable) and notify. The thread pickles, chunks,
    checksums and PUTs; when commits outpace it, only the newest pending
    snapshot is shipped — a replica is only useful if it is the
    freshest one.
    """

    def __init__(self, rank: int, size: int, partners: Sequence[int],
                 rendezvous: Tuple[str, int],
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 duty_cycle: float = DEFAULT_DUTY_CYCLE,
                 push: Optional[Tuple[str, int]] = None):
        self.rank = int(rank)
        self.size = int(size)
        self.partners = list(partners)
        # where control-plane WRITES (the manifest mirror) go: the pod
        # relay when one is configured (multipod/relay.py) — it batches
        # the pod's manifests into one upward PUT — else the root.
        # Reads (partner store lookups) always go to the root, which
        # holds the cluster-global view.
        self._push = push or rendezvous
        self.chunk_bytes = max(int(chunk_bytes), 1024)
        # adaptive rate control: after a ship that took T seconds the
        # thread idles >= T*(1/d - 1) before the next one, bounding
        # replication's share of this host's CPU at ~d even when the
        # box has no spare core for the background work (the
        # eager_path_bench overhead gate). On idle-core hosts T is
        # milliseconds and the gap is noise; under contention the
        # replica lag grows instead of the step time.
        self.duty_cycle = min(max(float(duty_cycle), 0.001), 1.0)
        self._rendezvous = rendezvous
        self._cond = threading.Condition()
        self._pending: Optional[Tuple[int, Dict[str, Any]]] = None
        self._stop = False
        self._stop_ev = threading.Event()
        # record_metrics=False: replication is best-effort by design —
        # a dead partner during a respawn window would otherwise spray
        # hvd_retry_giveups_total, which the chaos gates assert means
        # "a control-plane call died". Replication failures have their
        # own accounting (stats, hvd_replication_errors_total, the
        # outage tracker's one-warning discipline).
        self._policy = retry.RetryPolicy(
            max_attempts=2, base_delay_s=0.05, max_delay_s=0.25,
            record_metrics=False)
        self._outage = retry.Outage(LOG, "snapshot replication")
        self._addr_cache: Dict[int, List[Tuple[str, int]]] = {}
        self.stats = {
            "submitted": 0, "replicated": 0, "coalesced": 0,
            "errors": 0, "last_epoch": 0, "busy_s": 0.0,
        }
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="hvd-replicator")
        self._thread.start()

    # ------------------------------------------------------------- hot path

    def submit(self, epoch: int, saved: Dict[str, Any]) -> None:
        with self._cond:
            if self._pending is not None:
                self.stats["coalesced"] += 1
            self._pending = (int(epoch), saved)
            self.stats["submitted"] += 1
            self._cond.notify()

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block until the pending snapshot (if any) has shipped — a
        test/shutdown convenience, never called on the training path."""
        deadline = retry.Deadline(timeout_s)
        while not deadline.expired():
            with self._cond:
                if self._pending is None and not self._busy:
                    return True
            time.sleep(0.01)
        return False

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._stop_ev.set()
        self._thread.join(timeout=5)

    # ----------------------------------------------------------- background

    _busy = False

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait(timeout=1.0)
                if self._stop:
                    return
                epoch, saved = self._pending
                self._pending = None
                self._busy = True
            t0 = time.monotonic()
            try:
                self._replicate(epoch, saved)
            except Exception as e:  # never let the thread die
                self.stats["errors"] += 1
                self._outage.failure(e)
            finally:
                self._busy = False
            took = time.monotonic() - t0
            self.stats["busy_s"] += took
            # duty-cycle gap (see __init__); newer commits coalesce
            # into _pending while we idle, so the next ship is always
            # the freshest snapshot
            gap = took * (1.0 / self.duty_cycle - 1.0)
            if gap > 0 and self._stop_ev.wait(timeout=gap):
                return

    def _partner_addrs(self, partner: int,
                       refresh: bool = False) -> List[Tuple[str, int]]:
        if not refresh and partner in self._addr_cache:
            return self._addr_cache[partner]
        addr, port = self._rendezvous
        raw = _http_get(addr, port, STORE_SCOPE, f"rank_{partner}")
        addrs = (
            [tuple(a) for a in json.loads(raw.decode())] if raw else []
        )
        if addrs:
            self._addr_cache[partner] = addrs
        else:
            self._addr_cache.pop(partner, None)
        return addrs

    def _serialize(self, epoch: int, saved: Dict[str, Any],
                   ) -> Tuple[List[memoryview], List[int], str]:
        """(parts, sizes, sha256-of-true-payload).

        Steady state serializes with pickle protocol 5 and OUT-OF-BAND
        buffers: the envelope is a few hundred bytes and every array
        leaf becomes a zero-copy memoryview, so the replicator thread
        never holds the GIL for a multi-megabyte ``pickle.dumps`` —
        hashing and socket sends both release it, which is what keeps
        replication off the training critical path on a busy host
        (eager_path_bench replication A/B). With fault injection armed
        (or for objects that refuse out-of-band pickling) it falls
        back to one flat pickle so a ``replication.payload:corrupt``
        rule sees a single payload to damage.
        """
        obj = {
            "epoch": epoch,
            "rank": self.rank,
            "time_unix": time.time(),
            "saved": saved,
        }
        parts: Optional[List[memoryview]] = None
        if not faults.enabled():
            try:
                buffers: List[pickle.PickleBuffer] = []
                envelope = pickle.dumps(
                    obj, protocol=5, buffer_callback=buffers.append)
                parts = [memoryview(envelope)] + [
                    b.raw().cast("B") for b in buffers
                ]
            except Exception:
                parts = None
        if parts is not None:
            h = hashlib.sha256()
            for p in parts:
                h.update(p)
            return parts, [p.nbytes for p in parts], h.hexdigest()
        whole = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        # digest the TRUE payload first, then pass the wire bytes
        # through the chaos hook: a `replication.payload:corrupt` rule
        # simulates damage in transit/storage, which the recovery
        # ladder must reject by checksum mismatch (utils/faults.py)
        digest = hashlib.sha256(whole).hexdigest()
        whole = faults.corrupt(
            "replication.payload", whole, rank=self.rank, epoch=epoch)
        return [memoryview(whole)], [len(whole)], digest

    def _replicate(self, epoch: int, saved: Dict[str, Any]) -> None:
        from ..utils import metrics as _metrics

        parts, sizes, digest = self._serialize(epoch, saved)
        nbytes = sum(sizes)
        chunks: List[memoryview] = []
        for part in parts:
            for i in range(0, part.nbytes, self.chunk_bytes):
                chunks.append(part[i:i + self.chunk_bytes])
        if not chunks:
            chunks = [memoryview(b"")]
        # two alternating slots so a crash mid-write never tears the
        # last complete replica; the manifest (written last) names the
        # live slot and the checksum rejects any torn read regardless
        slot = epoch % 2
        manifest = {
            "epoch": epoch,
            "rank": self.rank,
            "slot": slot,
            "nchunks": len(chunks),
            "nbytes": nbytes,
            "sizes": sizes,
            "sha256": digest,
            "time_unix": time.time(),
        }
        manifest_bytes = json.dumps(manifest).encode()
        shipped: List[int] = []
        for partner in self.partners:
            try:
                faults.inject(
                    "replication.send", rank=self.rank, partner=partner,
                    epoch=epoch,
                )
                addrs = self._partner_addrs(partner)
                if not addrs:
                    raise ConnectionError(
                        f"rank {partner} has no registered replica store"
                    )
                try:
                    self._send_to(addrs, slot, chunks, manifest_bytes)
                except Exception:
                    # the partner may have respawned on a new port:
                    # refresh its registration once and re-try
                    addrs = self._partner_addrs(partner, refresh=True)
                    if not addrs:
                        raise
                    self._send_to(addrs, slot, chunks, manifest_bytes)
                shipped.append(partner)
            except Exception as e:
                self.stats["errors"] += 1
                self._outage.failure(e)
        if shipped:
            self._outage.success()
            self.stats["replicated"] += 1
            self.stats["last_epoch"] = epoch
            manifest["holders"] = shipped
            try:
                addr, port = self._push
                self._policy.call(
                    _http_put, addr, port, MANIFEST_SCOPE,
                    f"rank_{self.rank}", json.dumps(manifest).encode(),
                    point="replication.manifest",
                )
            except Exception as e:
                self._outage.failure(e)
            _metrics.record_replication(nbytes, len(shipped))
        else:
            _metrics.record_replication_error()

    def _send_to(self, addrs: List[Tuple[str, int]], slot: int,
                 chunks: List[memoryview], manifest_bytes: bytes) -> None:
        import http.client

        last: Optional[Exception] = None
        for a, p in addrs:
            conn = None
            try:
                # ONE keep-alive connection for the whole snapshot:
                # a multi-chunk send must not pay a TCP handshake per
                # megabyte (the KV server speaks HTTP/1.1)
                def _open():
                    return http.client.HTTPConnection(
                        a, p, timeout=_TIMEOUT_S)

                conn = _open()

                def _put(key: str, body) -> None:
                    nonlocal conn
                    try:
                        conn.request(
                            "PUT", f"/{REPLICA_SCOPE}/{key}", body=body)
                        resp = conn.getresponse()
                        resp.read()
                    except Exception:
                        # a dropped keep-alive poisons the connection
                        # object; rebuild it for the retry
                        try:
                            conn.close()
                        except Exception:
                            pass
                        conn = _open()
                        raise
                    if resp.status != 200:
                        raise ConnectionError(
                            f"replica PUT {key} -> {resp.status}")

                for i, chunk in enumerate(chunks):
                    self._policy.call(
                        _put, f"{self.rank}/s{slot}/c{i}", chunk,
                        point="replication.send",
                    )
                self._policy.call(
                    _put, f"{self.rank}/manifest", manifest_bytes,
                    point="replication.send",
                )
                return
            except Exception as e:
                last = e
            finally:
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
        raise last if last else ConnectionError("no replica addresses")


# ---------------------------------------------------------------------------
# commit hook (the training-path entry; single predicted branch when off)
# ---------------------------------------------------------------------------


def on_commit(state) -> None:
    """Called by ``State.commit()`` after the snapshot is saved. Hands
    the committed dict to the background replicator — a reference stash
    + notify, nothing else, so the training critical path pays only
    this call when enabled and one predicted branch when disabled."""
    if not _enabled:
        return
    rep = _replicator
    if rep is None:
        return
    rep.submit(int(getattr(state, "_commit_count", 0)), state._saved)


# ---------------------------------------------------------------------------
# recovery ladder
# ---------------------------------------------------------------------------


def ring_partners(rank: int, size: int, k: int) -> List[int]:
    """The k ranks after ``rank`` on the ring (self excluded)."""
    return [
        (rank + j) % size
        for j in range(1, min(max(k, 0), size - 1) + 1)
        if (rank + j) % size != rank
    ]


def fetch_replica(
    for_rank: int, rendezvous: Tuple[str, int],
) -> Optional[Tuple[int, Dict[str, Any]]]:
    """The freshest checksum-verified replica of ``for_rank`` from any
    surviving holder, or None. Holder list comes from the replication
    manifest mirrored to the rendezvous KV; each holder's store address
    from its registration. Verification failures (corrupt chunks, torn
    slots, missing stores) are warnings that try the next holder."""
    addr, port = rendezvous
    raw = _http_get(addr, port, MANIFEST_SCOPE, f"rank_{for_rank}")
    if raw is None:
        return None
    try:
        manifest = json.loads(raw.decode())
    except ValueError:
        LOG.warning("unparseable replication manifest for rank %d",
                    for_rank)
        return None
    holders = manifest.get("holders", [])
    best: Optional[Tuple[int, Dict[str, Any]]] = None
    for holder in holders:
        try:
            reg = _http_get(addr, port, STORE_SCOPE, f"rank_{holder}")
            if reg is None:
                continue
            for a, p in [tuple(x) for x in json.loads(reg.decode())]:
                got = _fetch_from_store(a, p, for_rank)
                if got is None:
                    continue
                if best is None or got[0] > best[0]:
                    best = got
                break
        except Exception as e:
            LOG.warning(
                "replica fetch for rank %d from holder %s failed: %s",
                for_rank, holder, e,
            )
    return best


def _fetch_from_store(
    addr: str, port: int, for_rank: int,
) -> Optional[Tuple[int, Dict[str, Any]]]:
    raw = _http_get(addr, port, REPLICA_SCOPE, f"{for_rank}/manifest")
    if raw is None:
        return None
    m = json.loads(raw.decode())
    slot, nchunks = m["slot"], m["nchunks"]
    parts: List[bytes] = []
    for i in range(nchunks):
        chunk = _http_get(
            addr, port, REPLICA_SCOPE, f"{for_rank}/s{slot}/c{i}")
        if chunk is None:
            LOG.warning(
                "replica of rank %d at %s:%d is missing chunk %d/%d",
                for_rank, addr, port, i, nchunks,
            )
            return None
        parts.append(chunk)
    payload = b"".join(parts)
    digest = hashlib.sha256(payload).hexdigest()
    if digest != m.get("sha256") or len(payload) != m.get("nbytes"):
        LOG.warning(
            "replica of rank %d at %s:%d failed checksum verification "
            "(epoch %s); falling through",
            for_rank, addr, port, m.get("epoch"),
        )
        return None
    sizes = m.get("sizes") or [len(payload)]
    if len(sizes) == 1:
        obj = pickle.loads(payload)
    else:
        # out-of-band wire format: envelope pickle + raw array buffers
        # (Replicator._serialize); split the verified stream back by
        # the manifest's sizes
        view = memoryview(payload)
        offset = sizes[0]
        envelope = bytes(view[:offset])
        buffers = []
        for s in sizes[1:]:
            buffers.append(view[offset:offset + s])
            offset += s
        obj = pickle.loads(envelope, buffers=buffers)
    return int(obj.get("epoch", 0)), obj["saved"]


def _install(state, saved: Dict[str, Any], epoch: int,
             rung: str) -> bool:
    """Adopt a verified snapshot into ``state``. A snapshot whose keys
    the state never registered is treated like corruption: warn and let
    the ladder fall through."""
    unknown = [k for k in saved if k not in state._known]
    if unknown:
        LOG.warning(
            "%s snapshot carries unregistered state attributes %s "
            "(registered: %s); falling through", rung, unknown,
            state._known,
        )
        return False
    state._saved = dict(saved)
    state.restore()
    state._commit_count = max(
        int(getattr(state, "_commit_count", 0)), int(epoch))
    return True


def _rendezvous_from_env() -> Optional[Tuple[str, int]]:
    addr = (os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
            or os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR"))
    port = (os.environ.get("HVD_TPU_RENDEZVOUS_PORT")
            or os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT"))
    if not addr or not port:
        return None
    try:
        return addr, int(port)
    except ValueError:
        return None


def _env_rank() -> int:
    for name in ("HVD_TPU_RANK", "HOROVOD_RANK"):
        v = os.environ.get(name)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def run_recovery_ladder(
    state,
    emergency_path: Optional[str] = None,
    orbax_restore=None,
    rendezvous: Optional[Tuple[str, int]] = None,
    rank: Optional[int] = None,
) -> Optional[str]:
    """Restore ``state`` from the freshest verified source and return
    the rung that supplied it (``"peer"`` / ``"emergency"`` /
    ``"orbax"``), or None when no source yielded a verified snapshot
    (the state keeps its fresh-constructed values).

    The peer and emergency rungs are compared by commit epoch — the
    fresher *verified* snapshot wins, with the peer rung breaking ties
    (it is the per-commit source). The orbax rung
    (``state.orbax_restore`` or the ``orbax_restore`` callable, e.g.
    built by ``checkpoint.orbax_rung``) is the last resort: orbax
    checkpoints carry their own integrity machinery but are the
    stalest source. Every outcome lands in
    ``hvd_recovery_rung_total{rung=...}`` and the flight recorder.
    """
    from ..utils import metrics as _metrics

    attempted = False
    candidates: List[Tuple[int, int, str, Dict[str, Any]]] = []

    rdv = rendezvous or _rendezvous_from_env()
    my_rank = _env_rank() if rank is None else int(rank)
    if rdv is not None and (_enabled or _configured or rendezvous):
        attempted = True
        try:
            got = fetch_replica(my_rank, rdv)
            if got is not None:
                # priority 0 beats 1 on epoch ties: the peer replica is
                # the per-commit source
                candidates.append((got[0], 0, "peer", got[1]))
        except Exception as e:
            LOG.warning("peer-replica rung failed: %s", e)

    if emergency_path and os.path.exists(emergency_path):
        attempted = True
        try:
            from . import preemption

            epoch, saved = preemption.emergency_read(emergency_path)
            candidates.append((epoch, 1, "emergency", saved))
        except Exception as e:
            LOG.warning(
                "emergency snapshot %s unusable (%s); falling through "
                "to the next recovery rung", emergency_path, e,
            )

    for epoch, _prio, rung, saved in sorted(
            candidates, key=lambda c: (-c[0], c[1])):
        if _install(state, saved, epoch, rung):
            LOG.warning(
                "recovered state from %s snapshot (commit epoch %d)",
                rung, epoch,
            )
            _metrics.record_recovery_rung(rung)
            return rung

    restore_fn = orbax_restore or getattr(state, "orbax_restore", None)
    if restore_fn is not None:
        attempted = True
        try:
            if restore_fn(state):
                state.save()
                LOG.warning("recovered state from orbax checkpoint")
                _metrics.record_recovery_rung("orbax")
                return "orbax"
        except Exception as e:
            LOG.warning("orbax rung failed: %s", e)

    if attempted:
        LOG.warning(
            "recovery ladder exhausted with no verified snapshot; "
            "starting from constructed state")
        _metrics.record_recovery_rung("none")
    return None


# ---------------------------------------------------------------------------
# lifecycle (core/basics.py calls configure/on_shutdown)
# ---------------------------------------------------------------------------


def configure(
    knobs=None,
    *,
    enabled_override: Optional[bool] = None,
    rank: Optional[int] = None,
    size: Optional[int] = None,
    partners: Optional[int] = None,
    chunk_bytes: Optional[int] = None,
    duty_cycle: Optional[float] = None,
    rendezvous_addr: Optional[str] = None,
    rendezvous_port: Optional[int] = None,
) -> bool:
    """Arm replication from the knob snapshot (hvd.init) or explicit
    overrides (tests, check scripts; env fallbacks for both). Starts
    the replica store, registers it in the rendezvous KV and spawns the
    replicator thread. Returns True when replication is live; False
    when disabled or the world cannot support it (size < 2, no
    rendezvous)."""
    global _enabled, _configured, _replicator, _store

    if knobs is None and enabled_override is None:
        from ..core.knobs import Knobs

        knobs = Knobs.from_env()
    want = (
        bool(getattr(knobs, "replication_enabled", False))
        if enabled_override is None else bool(enabled_override)
    )
    if not want:
        stop()
        return False

    my_rank = _env_rank() if rank is None else int(rank)
    world = (
        int(os.environ.get("HVD_TPU_SIZE")
            or os.environ.get("HOROVOD_SIZE") or 1)
        if size is None else int(size)
    )
    rdv: Optional[Tuple[str, int]]
    if rendezvous_addr is not None and rendezvous_port:
        rdv = (rendezvous_addr, int(rendezvous_port))
    else:
        rdv = _rendezvous_from_env()
    if world < 2 or rdv is None:
        LOG.warning(
            "replication requested but unusable here (world %d, "
            "rendezvous %s); disabled", world, rdv,
        )
        stop()
        return False

    k = int(partners if partners is not None
            else getattr(knobs, "replication_partners", 1) or 1)
    chunk = int(chunk_bytes if chunk_bytes is not None
                else getattr(knobs, "replication_chunk_bytes",
                             DEFAULT_CHUNK_BYTES))
    duty = float(duty_cycle if duty_cycle is not None
                 else getattr(knobs, "replication_duty_cycle",
                              DEFAULT_DUTY_CYCLE))
    stop()  # idempotent re-init (elastic _reinitialize path)
    _store = ReplicaStore(backing=_backing)
    # registration + manifest mirrors are WRITES: route them through
    # the pod relay when one is configured so the root sees one batched
    # PUT per pod instead of one per host (multipod/relay.py). Reads
    # (fetch_replica, partner lookups) stay on the root.
    try:
        from ..multipod.relay import push_endpoint

        push_ep = push_endpoint(root=rdv) or rdv
    except Exception:
        push_ep = rdv
    try:
        _http_put(
            push_ep[0], push_ep[1], STORE_SCOPE, f"rank_{my_rank}",
            json.dumps(_store.addresses()).encode(),
        )
    except Exception as e:
        LOG.warning(
            "could not register replica store with the rendezvous "
            "(%s); peers will not find this rank's store until the "
            "next registration", e,
        )
    _replicator = Replicator(
        my_rank, world, ring_partners(my_rank, world, k), rdv,
        chunk_bytes=chunk, duty_cycle=duty, push=push_ep,
    )
    _configured = True
    _enabled = True
    LOG.info(
        "snapshot replication armed: rank %d -> partners %s "
        "(chunk %d B)", my_rank, _replicator.partners, chunk,
    )
    return True


def stop() -> None:
    """Tear down the replicator thread and replica store. Replica
    payloads survive in the module backing dict, so an in-process
    re-init (elastic reset) does not lose partners' snapshots."""
    global _enabled, _replicator, _store
    _enabled = False
    if _replicator is not None:
        _replicator.stop()
        _replicator = None
    if _store is not None:
        _store.shutdown()
        _store = None


def on_shutdown() -> None:
    """hvd.shutdown(): stop threads if configure() armed us."""
    global _configured
    if _configured:
        _configured = False
        stop()


def reset() -> None:
    """Test hook: full teardown including the replica backing dict."""
    global _configured
    stop()
    _configured = False
    _backing.clear()
