"""horovod_tpu — a TPU-native distributed deep-learning training framework.

Capability rebuild of Horovod (reference: /root/reference, v0.25.0) with
the data plane as XLA collective HLOs over the TPU ICI/DCN mesh
(JAX / pjit / shard_map / Pallas) instead of NCCL/MPI/Gloo. SURVEY.md maps
every reference component to its location here.

Quick start (the reference's four-step recipe, README.rst:137-180,
translated)::

    import horovod_tpu as hvd
    hvd.init()                       # 1. topology discovery, mesh build
    # 2. shard the batch over the mesh (the "pin GPU" step is a no-op:
    #    XLA owns placement)
    # 3. wrap the optimizer — fuses + all-reduces gradients
    opt = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.size()))
    # 4. broadcast initial parameters from rank 0
    params = hvd.broadcast_parameters(params, root_rank=0)
"""

__version__ = "0.1.0"

from .core.basics import (  # noqa: F401
    ccl_built,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    dp_axis_names,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_rank_op,
    local_size,
    local_size_op,
    mesh,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    process_set_included_op,
    rank,
    rank_op,
    rocm_built,
    shutdown,
    size,
    size_op,
    xla_built,
    xla_enabled,
)
from .core.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
    HorovodTpuError,
    ProcessSetError,
)
from .core.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    get_process_set_by_id,
    global_process_set,
    remove_process_set,
)
from .ops import (  # noqa: F401
    Adasum,
    Average,
    IndexedSlices,
    Max,
    Min,
    OnlineTuner,
    Product,
    ReduceOp,
    SPMDStepTuner,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    dense_to_sparse,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    join,
    masked_allreduce,
    model_fingerprint,
    poll,
    reducescatter,
    reducescatter_async,
    sparse_allreduce,
    sparse_to_dense,
    synchronize,
)
from .optim import (  # noqa: F401
    Compression,
    DistributedGradientTape,
    DistributedOptimizer,
    FullyShardedOptimizer,
    Int8BlockCompressor,
    ShardedOptimizer,
    error_feedback_specs,
    fsdp_layout,
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    reshard_state,
    sharded_state_specs,
)

# Elastic + timeline live under their own namespaces, mirroring
# hvd.elastic.* and hvd.start_timeline in the reference. Metrics is the
# live-telemetry namespace (hvd.metrics.step(), hvd.metrics.scrape()).
from . import callbacks  # noqa: F401
from .ops import autotune  # noqa: F401  (hvd.autotune.OnlineTuner)
from .ops import overlap  # noqa: F401  (hvd.overlap.staged_value_and_grad)
from .optim import fsdp  # noqa: F401  (hvd.fsdp.shard_params / layout)
from .utils import faults  # noqa: F401
from .utils import metrics  # noqa: F401
from .utils import prof  # noqa: F401  (hvd.prof.set_step_flops, summary)
from .checkpoint import LoadedModel, load_model, save_model  # noqa: F401
from . import data  # noqa: F401
from . import elastic  # noqa: F401
from . import multipod  # noqa: F401  (hvd.multipod.pod_topology, LocalSGD)
from .sync_batch_norm import SyncBatchNorm  # noqa: F401


def start_timeline(filename: str, mark_cycles: bool = False) -> None:
    """Dynamic timeline start (reference: operations.cc:1048,
    basics.py:156)."""
    from .core.state import global_state

    st = global_state()
    if st.timeline is None:
        from .utils.timeline import Timeline

        st.timeline = Timeline(None)
    st.timeline.start(filename, mark_cycles=mark_cycles)


def stop_timeline() -> None:
    from .core.state import global_state

    st = global_state()
    if st.timeline is not None:
        st.timeline.stop()
