"""Launcher layer (L6): `hvdrun` CLI and the in-process `run()` API.

Reference surface: /root/reference/horovod/runner/ — `horovodrun`
(launch.py:286,583,676), `horovod.run()` (runner/__init__.py:94), gloo_run
slot spawning (gloo_run.py:242), HTTP rendezvous (http/http_server.py:192),
elastic driver (elastic/driver.py:69).

TPU-native shape: a slot is a *host process* (one JAX controller driving
all local chips), not a per-accelerator process. The launcher assigns
SlotInfo (rank/local_rank/cross_rank) for API parity, starts a rendezvous /
KV server for bootstrap, and points every worker at the JAX coordination
service (jax.distributed) instead of MPI/Gloo.
"""

from .launch import parse_args, run_commandline  # noqa: F401
from .api import run  # noqa: F401
from .util.hosts import (  # noqa: F401
    HostInfo,
    SlotInfo,
    get_host_assignments,
    parse_hosts,
)
