"""Launcher-owned process supervision for the control-plane tier.

``horovodrun`` used to make operators start root replicas and per-pod
relays by hand (docs/multipod.md pre-PR-17); now it spawns and OWNS
them: :class:`ProcessSupervisor` restarts a crashed child under
exponential backoff, counts *flaps* (exits within ``flap_window_s`` of
the start — the crash-loop signature), and reaps everything on
shutdown. A child that stays up past the flap window earns its backoff
back (the next crash restarts fast again).

The restart ladder deliberately mirrors utils/retry.RetryPolicy's
shape (base × multiplier^n, capped) but without jitter: supervision
backoff is asserted exactly in tests (tests/test_control_plane.py),
and unlike request retries there is no thundering-herd peer to
de-synchronize from — each launcher supervises only its own children.

Telemetry: ``hvd_supervisor_restarts_total{proc=...}`` and
``hvd_supervisor_flaps{proc=...}`` in the process registry
(utils/metrics.py), so a crash-looping relay surfaces on the root's
aggregated ``/metrics`` scrape without anyone tailing launcher logs.

Deterministic testing: ``clock``/``sleep``/``spawn`` are injectable
and :meth:`poll_once` is the entire supervision step — tests drive a
fake clock through crash/backoff/flap schedules with no real
subprocesses and no real time. The spawned children carry the
launcher's fault-spec environment, so ``root.replica:kill`` /
``relay.proc:kill`` rules (utils/faults.py) kill real children in the
CI gate (scripts/multipod_check.py) and this module restarts them.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

LOG = logging.getLogger("horovod_tpu.runner")

from ..utils import metrics as _metrics


def _default_spawn(argv: List[str], env: Dict[str, str]):
    return subprocess.Popen(argv, env=env)


class _Child:
    __slots__ = ("name", "argv", "env", "proc", "started_at",
                 "restarts", "flaps", "attempt", "restart_due",
                 "stopped")

    def __init__(self, name: str, argv: List[str],
                 env: Dict[str, str]):
        self.name = name
        self.argv = list(argv)
        self.env = dict(env)
        self.proc = None
        self.started_at: Optional[float] = None
        self.restarts = 0
        self.flaps = 0
        self.attempt = 0  # consecutive flappy exits → backoff exponent
        self.restart_due: Optional[float] = None
        self.stopped = False


class ProcessSupervisor:
    """Spawn, monitor, backoff-restart, and reap a set of child
    processes (the root replicas + pod relays tier).

    ``poll_interval_s`` is the monitor thread's cadence; everything
    else is per-child: a child that exits gets a restart scheduled
    ``base_delay_s × multiplier^attempt`` (capped at ``max_delay_s``)
    in the future, where ``attempt`` counts *consecutive flappy* exits
    — an exit after a run longer than ``flap_window_s`` resets the
    ladder. ``max_flaps`` (None = unlimited) abandons a child that
    crash-loops past the limit instead of burning CPU forever; the
    abandonment is visible in :meth:`stats` and the flap gauge.
    """

    def __init__(self, base_delay_s: float = 0.5,
                 max_delay_s: float = 10.0,
                 multiplier: float = 2.0,
                 flap_window_s: float = 5.0,
                 max_flaps: Optional[int] = None,
                 poll_interval_s: float = 0.2,
                 clock: Callable[[], float] = time.monotonic,
                 spawn: Callable = _default_spawn):
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.flap_window_s = float(flap_window_s)
        self.max_flaps = max_flaps
        self.poll_interval_s = float(poll_interval_s)
        self._clock = clock
        self._spawn = spawn
        self._children: Dict[str, _Child] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._m_restarts = _metrics.registry.counter(
            "hvd_supervisor_restarts_total",
            "supervised child restarts, by child name",
            ("proc",))
        self._m_flaps = _metrics.registry.gauge(
            "hvd_supervisor_flaps",
            "flappy exits (died within the flap window) per child",
            ("proc",))

    # -- child management ---------------------------------------------------

    def add(self, name: str, argv: List[str],
            env: Optional[Dict[str, str]] = None) -> None:
        """Register AND start one child. ``env`` defaults to this
        process's environment (fault specs and root-set exports ride
        along)."""
        child = _Child(name, argv,
                       dict(os.environ) if env is None else env)
        with self._lock:
            if name in self._children:
                raise ValueError(f"child {name!r} already supervised")
            self._children[name] = child
            self._start_child(child)

    def _start_child(self, child: _Child) -> None:
        child.proc = self._spawn(child.argv, child.env)
        child.started_at = self._clock()
        child.restart_due = None
        self._m_flaps.labels(child.name).set(child.flaps)

    # -- supervision step ---------------------------------------------------

    def poll_once(self) -> None:
        """One supervision step: detect exits, classify flaps,
        schedule + execute due restarts. The monitor thread calls this
        on a cadence; tests call it directly under a fake clock."""
        now = self._clock()
        with self._lock:
            for child in self._children.values():
                if child.stopped:
                    continue
                if child.proc is not None \
                        and child.proc.poll() is None:
                    continue  # running
                if child.proc is not None:
                    # just noticed the exit: classify + schedule
                    code = child.proc.returncode
                    ran_s = now - (child.started_at or now)
                    if ran_s < self.flap_window_s:
                        child.flaps += 1
                        child.attempt += 1
                    else:
                        child.attempt = 0  # healthy run: ladder resets
                    self._m_flaps.labels(child.name).set(child.flaps)
                    if self.max_flaps is not None \
                            and child.flaps > self.max_flaps:
                        LOG.error(
                            "supervised %s crash-looped past "
                            "max_flaps=%d (last exit %s); abandoning",
                            child.name, self.max_flaps, code)
                        child.proc = None
                        child.stopped = True
                        continue
                    delay = min(
                        self.max_delay_s,
                        self.base_delay_s
                        * self.multiplier ** max(child.attempt - 1, 0))
                    child.restart_due = now + delay
                    child.proc = None
                    LOG.warning(
                        "supervised %s exited (%s) after %.2fs; "
                        "restart in %.2fs (restart #%d, flaps %d)",
                        child.name, code, ran_s, delay,
                        child.restarts + 1, child.flaps)
                if child.restart_due is not None \
                        and now >= child.restart_due:
                    child.restarts += 1
                    self._m_restarts.labels(child.name).inc()
                    self._start_child(child)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # supervision must outlive hiccups
                LOG.warning("supervisor poll error: %s", e)

    def start(self) -> None:
        if self._monitor is None:
            self._stop.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="proc-supervisor")
            self._monitor.start()

    # -- teardown -----------------------------------------------------------

    def shutdown(self, term_timeout_s: float = 5.0) -> None:
        """Stop supervising and reap every child: SIGTERM, grace
        period, SIGKILL stragglers. Safe to call twice."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10)
            self._monitor = None
        with self._lock:
            children = list(self._children.values())
            for child in children:
                child.stopped = True
        for child in children:
            if child.proc is None or child.proc.poll() is not None:
                continue
            try:
                child.proc.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + term_timeout_s
        for child in children:
            if child.proc is None:
                continue
            remain = max(deadline - time.monotonic(), 0.01)
            try:
                child.proc.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                try:
                    child.proc.kill()
                    child.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                c.name: {
                    "alive": (c.proc is not None
                              and c.proc.poll() is None),
                    "restarts": c.restarts,
                    "flaps": c.flaps,
                    "abandoned": c.stopped and c.proc is None,
                    "pid": (c.proc.pid if c.proc is not None
                            else None),
                }
                for c in self._children.values()
            }

    def alive(self, name: str) -> bool:
        with self._lock:
            c = self._children.get(name)
            return bool(c and c.proc is not None
                        and c.proc.poll() is None)


def python_child_argv(module: str, *args: str) -> List[str]:
    """argv for a supervised ``python -m`` child using THIS
    interpreter — replicas and relays must import the same
    horovod_tpu."""
    return [sys.executable, "-m", module, *args]
