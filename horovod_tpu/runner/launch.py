"""`hvdrun` — the horovodrun-equivalent CLI.

Reference: /root/reference/horovod/runner/launch.py — parse_args (:286),
`_run_static` (:583), `_run_elastic` (:676), `run_controller` (:734). The
controller-selection matrix (gloo/mpi/jsrun) collapses on TPU: the data
plane is always XLA collectives and bootstrap is always the rendezvous
HTTP store + JAX coordination service, so the remaining choice is
static vs elastic.

Usage:
    hvdrun -np 4 -H host1:1,host2:1,host3:1,host4:1 python train.py
    hvdrun -np 8 --min-np 4 --max-np 12 --host-discovery-script ./d.sh \
        python train.py
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .util import config_parser
from .util.hosts import HostInfo, parse_host_files, parse_hosts


def parse_args(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu training job.",
    )
    p.add_argument("-v", "--version", action="store_true")
    p.add_argument("--check-build", dest="check_build",
                   action="store_true",
                   help="Print availability of frameworks, controllers "
                        "and ops, then exit (reference launch.py:110).")
    p.add_argument(
        "-np", "--num-proc", dest="np", type=int,
        help="Total number of worker processes (slots).",
    )
    p.add_argument(
        "-H", "--hosts", dest="hosts",
        help="Comma-separated host:slots list, e.g. h1:1,h2:1.",
    )
    p.add_argument(
        "-hostfile", "--hostfile", dest="hostfile",
        help="Hostfile with `host slots=N` lines.",
    )
    p.add_argument("--verbose", action="count", default=0)
    p.add_argument("--config-file", dest="config_file")

    # elastic (reference launch.py:676)
    p.add_argument("--min-np", dest="min_np", type=int)
    p.add_argument("--max-np", dest="max_np", type=int)
    p.add_argument(
        "--host-discovery-script", dest="host_discovery_script",
        help="Executable printing the current host:slots list, one per line.",
    )
    p.add_argument("--slots-per-host", dest="slots", type=int, default=1)
    p.add_argument("--elastic-timeout", dest="elastic_timeout", type=float)
    p.add_argument("--reset-limit", dest="reset_limit", type=int)
    p.add_argument(
        "--blacklist-cooldown-range", dest="cooldown_range", nargs=2,
        type=float, metavar=("MIN_S", "MAX_S"),
    )

    # runtime knobs → env (reference launch.py:286-580, config_parser)
    p.add_argument("--fusion-threshold-mb", dest="fusion_threshold_mb",
                   type=int)
    p.add_argument("--cycle-time-ms", dest="cycle_time_ms", type=float)
    p.add_argument("--cache-capacity", dest="cache_capacity", type=int)
    p.add_argument("--timeline-filename", dest="timeline_filename")
    p.add_argument("--timeline-mark-cycles", dest="timeline_mark_cycles",
                   action="store_true", default=None)
    p.add_argument("--autotune", dest="autotune", action="store_true",
                   default=None)
    p.add_argument("--autotune-bayes", dest="autotune_bayes",
                   action="store_true",
                   help="Bayesian (GP + expected-improvement) autotune "
                        "search instead of coordinate descent")
    p.add_argument("--autotune-log", dest="autotune_log")
    p.add_argument("--autotune-cache", dest="autotune_cache",
                   help="persistent warm-start cache for the "
                        "closed-loop OnlineTuner "
                        "(HOROVOD_AUTOTUNE_CACHE, docs/autotune.md): "
                        "winners persist per (model fingerprint, "
                        "topology); later runs and serving replicas "
                        "pin the cached configuration with zero "
                        "tuning compiles")
    p.add_argument("--autotune-mfu", dest="autotune_mfu",
                   choices=["0", "1"],
                   help="score autotune trials by measured hvd_mfu "
                        "when the continuous profiler is live "
                        "(HOROVOD_AUTOTUNE_MFU, default 1; the "
                        "step-time p50 via StepStats is always "
                        "recorded and is the fallback score)")
    p.add_argument("--autotune-wire", dest="autotune_wire",
                   choices=["0", "1"],
                   help="opt IN to the NUMERICS-CHANGING autotune "
                        "dimensions — wire dtype/block and eager "
                        "fast-path warmup K (HOROVOD_AUTOTUNE_WIRE, "
                        "default 0; int8 on the wire is lossy, so "
                        "the tuner never sweeps or warm-starts these "
                        "without explicit consent)")
    p.add_argument("--compression", dest="compression",
                   choices=["none", "fp16", "bf16", "int8", "int8-raw"],
                   help="compressed collective data plane "
                        "(HOROVOD_COMPRESSION, docs/compression.md): "
                        "cast wires halve gradient bytes, int8 "
                        "block-quantizes them ~4x with error feedback")
    p.add_argument("--compression-block", dest="compression_block",
                   type=int,
                   help="int8 quantization block (elements per scale, "
                        "HOROVOD_COMPRESSION_BLOCK, default 256)")
    p.add_argument("--overlap-schedule", dest="overlap_schedule",
                   choices=["off", "stage", "double"],
                   help="backward-interleaved collective scheduler "
                        "(HOROVOD_OVERLAP_SCHEDULE, docs/overlap.md): "
                        "'stage' issues each fusion bucket's "
                        "collective inside the backward, pinned before "
                        "the next segment's compute; 'double' also "
                        "defers optimizer consumption until the last "
                        "segment retires; default off")
    p.add_argument("--fsdp", dest="fsdp", choices=["0", "1"],
                   help="fully-sharded parameters / ZeRO-3 routing "
                        "(HOROVOD_FSDP, docs/fsdp.md): 1 (default) "
                        "routes FullyShardedOptimizer train steps "
                        "through the prefetch-interleaved FSDP path — "
                        "params + optimizer state ~1/world per chip; "
                        "0 disables routing (such a step then raises; "
                        "non-FSDP configs are untouched either way)")
    p.add_argument("--fsdp-prefetch", dest="fsdp_prefetch", type=int,
                   help="FSDP forward all-gather look-ahead in stages "
                        "(HOROVOD_FSDP_PREFETCH, default 1): bucket "
                        "k+1's parameter gather issues at segment k's "
                        "boundary and overlaps its compute; 0 "
                        "serializes gathers at their need boundaries")
    p.add_argument("--fsdp-regather", dest="fsdp_regather",
                   choices=["0", "1"],
                   help="FSDP backward re-gather policy "
                        "(HOROVOD_FSDP_REGATHER, docs/fsdp.md): 1 "
                        "(default) drops each gathered bucket at its "
                        "last forward use and re-issues the all-gather "
                        "at its backward-first-use boundary — "
                        "within-step peak param liveness capped at "
                        "sharded + one bucket working set, bitwise "
                        "equal to 0 (save gathered weights across the "
                        "whole step — the pre-regather lowering)")
    p.add_argument("--fsdp-offload", dest="fsdp_offload",
                   choices=["0", "1"],
                   help="FSDP host-RAM activation offload "
                        "(HOROVOD_FSDP_OFFLOAD, docs/fsdp.md): 1 parks "
                        "inter-stage carries in pinned host memory on "
                        "forward and prefetches each back one backward "
                        "segment ahead; bitwise no-op on values; "
                        "default 0")
    p.add_argument("--fsdp-offload-duty", dest="fsdp_offload_duty",
                   type=float,
                   help="fraction of eligible stage carries the "
                        "offload parks on the host "
                        "(HOROVOD_FSDP_OFFLOAD_DUTY, default 1.0): "
                        "earliest stages first — bound the host PCIe "
                        "duty cycle when full offload would not hide "
                        "under compute")
    p.add_argument("--fused-collectives", dest="fused_collectives",
                   choices=["0", "1"],
                   help="fused computation-collective Pallas backend "
                        "(HOROVOD_FUSED_COLLECTIVES, "
                        "docs/fused_collectives.md): 1 runs the int8 "
                        "wire's quantize/error-feedback/accumulate, "
                        "the bucket pack epilogue and the decode "
                        "KV-append+attention as Pallas kernels — "
                        "bitwise-identical values, fewer programs "
                        "around each collective; default 0")
    p.add_argument("--compression-wire-dtype",
                   dest="compression_wire_dtype",
                   choices=["bfloat16", "float16"])
    p.add_argument("--fp16-allreduce", dest="compression_wire_dtype",
                   action="store_const", const="bfloat16",
                   help="bf16-on-the-wire gradient compression (TPU-native "
                        "form of the reference's fp16 allreduce).")
    p.add_argument("--hierarchical-allreduce",
                   dest="hierarchical_allreduce", action="store_true",
                   default=None)
    p.add_argument("--hierarchical-allgather",
                   dest="hierarchical_allgather", action="store_true",
                   default=None)
    p.add_argument("--hierarchical-local-size",
                   dest="hierarchical_local_size", type=int,
                   help="ranks per inner (ICI) domain for hierarchical "
                        "collectives; 0 = auto (local device count)")
    p.add_argument("--stall-check-disable", dest="stall_check_disable",
                   action="store_true", default=None)
    p.add_argument("--stall-warning-time-seconds",
                   dest="stall_warning_time_seconds", type=float)
    p.add_argument("--stall-shutdown-time-seconds",
                   dest="stall_shutdown_time_seconds", type=float)
    p.add_argument("--stall-abort-seconds", dest="stall_abort_s",
                   type=float,
                   help="Negotiation watchdog: a collective making no "
                        "progress for this long raises "
                        "HorovodInternalError so elastic training "
                        "restores and retries (0 = off).")

    # fault tolerance / chaos (docs/faults.md)
    p.add_argument("--fault-spec", dest="fault_spec",
                   help="Fault-injection spec for workers, e.g. "
                        "'http.put:error:0.3:seed=7' (docs/faults.md).")
    p.add_argument("--retry-max-attempts", dest="retry_max_attempts",
                   type=int,
                   help="Control-plane retry attempts (default 5).")
    p.add_argument("--retry-base-delay", dest="retry_base_delay",
                   type=float,
                   help="First control-plane backoff in seconds "
                        "(default 0.1).")
    p.add_argument("--retry-max-delay", dest="retry_max_delay",
                   type=float,
                   help="Control-plane backoff cap in seconds "
                        "(default 2.0).")
    p.add_argument("--vanish-grace", dest="vanish_grace", type=float,
                   help="Seconds a host may drop out of discovery "
                        "before its worker is counted failed "
                        "(default 5).")
    p.add_argument("--spawn-join", dest="spawn_join", type=float,
                   help="Post-round spawn-thread join budget in "
                        "seconds (default 30).")
    p.add_argument("--no-preemption", dest="preemption",
                   action="store_const", const="0", default=None,
                   help="Disable the SIGTERM preemption handler in "
                        "workers (elastic/preemption.py).")
    p.add_argument("--emergency-checkpoint", dest="emergency_checkpoint",
                   help="Rank-0 emergency snapshot path written on "
                        "preemption (SIGTERM).")
    p.add_argument("--replication", dest="replication",
                   action="store_const", const="1", default=None,
                   help="Async peer snapshot replication: every "
                        "state.commit() ships the committed snapshot "
                        "to ring-partner ranks so a respawned worker "
                        "restores from a surviving peer instead of "
                        "stale disk state (docs/recovery.md).")
    p.add_argument("--replication-partners", dest="replication_partners",
                   type=int,
                   help="Ring partners each rank replicates its "
                        "snapshot to (default 1).")
    p.add_argument("--rendezvous-state-dir", dest="rendezvous_state_dir",
                   help="Directory for the rendezvous server's atomic "
                        "on-disk state snapshot; a restarted driver "
                        "pointed at the same directory resumes the "
                        "same job on the same port (docs/recovery.md).")

    # sharded root control plane (docs/control_plane.md)
    p.add_argument("--root-replicas", dest="root_replicas", type=int,
                   help="Shard the root KV tier across N supervised "
                        "replica processes with consistent-hash "
                        "routing, lease/fencing takeover, and "
                        "write-through ring backups; hvdrun spawns, "
                        "backoff-restarts and reaps them. Default 1 = "
                        "today's single root, bit-for-bit "
                        "(docs/control_plane.md).")
    p.add_argument("--root-state-dir", dest="root_state_dir",
                   help="Directory for the root replicas' persisted "
                        "state snapshots (default: a fresh temp dir); "
                        "a supervisor-restarted replica reloads its "
                        "store from here before re-pulling deltas "
                        "from peers.")
    p.add_argument("--root-lease-ttl", dest="root_lease_ttl",
                   type=float,
                   help="Replica lease TTL in seconds (default 3.0): "
                        "a silent replica is fenced and taken over "
                        "after this long.")
    p.add_argument("--root-heartbeat", dest="root_heartbeat",
                   type=float,
                   help="Replica lease heartbeat cadence in seconds "
                        "(default 0.5).")
    p.add_argument("--pod-relays", dest="pod_relays", type=int,
                   help="Spawn N launcher-supervised per-pod relay "
                        "processes (multipod/relay.py) targeting the "
                        "root tier, replacing the operator-run relays "
                        "of docs/multipod.md; crashed relays restart "
                        "under backoff with flap counting.")
    p.add_argument("--prof-every", dest="prof_every", type=int,
                   help="Continuous step profiler: sample every N-th "
                        "step with device tracing and export compute/"
                        "exposed-wire/idle attribution + hvd_mfu "
                        "(0 = off; docs/timeline.md).")
    p.add_argument("--prof-dir", dest="prof_dir",
                   help="Root directory for sampled profiler captures "
                        "(default <tmpdir>/hvd_prof/rank<r>); feed it "
                        "to scripts/trace_merge.py.")
    p.add_argument("--prof-duty-cycle", dest="prof_duty_cycle",
                   type=float,
                   help="Cap on the fraction of wall time the sampled "
                        "profiler may consume (default 0.02).")
    p.add_argument("--flight-recorder", dest="flight_recorder",
                   action="store_const", const="1", default=None,
                   help="Force the control-plane flight recorder on in "
                        "workers (default on; docs/flight.md).")
    p.add_argument("--no-flight-recorder", dest="flight_recorder",
                   action="store_const", const="0",
                   help="Disable the flight recorder (its record sites "
                        "become single predicted branches).")
    p.add_argument("--flight-dir", dest="flight_dir",
                   help="Directory for rank-local flight dumps "
                        "(default <tmpdir>/hvd_flight); dumps also "
                        "ship to the rendezvous server.")
    p.add_argument("--log-level", dest="log_level",
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    p.add_argument("--mesh", dest="mesh",
                   help='Mesh axis spec for workers, e.g. "dp=4,tp=2".')
    p.add_argument(
        "--network-interface", dest="nics",
        help="Comma-separated NICs to bind (recorded in env; XLA/DCN "
             "transport selection is automatic on TPU).",
    )

    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="Training command to run on every slot.")

    args = p.parse_args(argv)

    if args.config_file:
        full_argv = list(argv if argv is not None else sys.argv[1:])
        # only hvdrun's own flags count as explicit — the trainee command
        # captured by REMAINDER may contain identically-named flags
        own_argv = (
            full_argv[: len(full_argv) - len(args.command)]
            if args.command
            else full_argv
        )
        explicit = _explicit_dests(own_argv, p)
        config_parser.apply_config_file(args, args.config_file, explicit)
    return args


def _explicit_dests(argv, parser) -> set:
    """Dests the user set on the command line (beat the config file)."""
    explicit = set()
    for action in parser._actions:
        for opt in action.option_strings:
            if any(a == opt or a.startswith(opt + "=") for a in argv):
                explicit.add(action.dest)
    return explicit


def _reserve_ports(n: int) -> List[int]:
    """n distinct free ports, all reserved before any is handed out —
    the replica-id ↔ port mapping must be fixed before the first child
    spawns (HOROVOD_ROOT_ADDRS is positional)."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("0.0.0.0", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _advertise_addr(hosts: List[HostInfo]) -> str:
    """The address workers use to reach launcher-spawned control-plane
    processes: loopback for an all-local job, this host's name
    otherwise."""
    import socket

    names = {h.hostname for h in hosts}
    if names <= {"localhost", "127.0.0.1"}:
        return "127.0.0.1"
    return socket.gethostname()


def _wait_for_roots(roots: str, timeout_s: float = 20.0) -> None:
    """Block until every spawned replica answers /shard_map — workers
    must never race the tier's bind."""
    import urllib.request

    from .http.ring import parse_root_addrs
    from ..utils import retry as _retry

    deadline = _retry.Deadline(timeout_s)
    pending = list(parse_root_addrs(roots))
    while pending and not deadline.expired():
        addr, port = pending[0]
        try:
            with urllib.request.urlopen(
                    f"http://{addr}:{port}/shard_map", timeout=2.0):
                pass
            pending.pop(0)
        except Exception:
            import time as _time
            _time.sleep(0.1)
    if pending:
        raise TimeoutError(
            f"root replicas {pending} not serving within {timeout_s}s")


def _spawn_control_plane(args, env, hosts):
    """Spawn + supervise the control-plane tier hvdrun now owns
    (docs/control_plane.md): N sharded root replicas and per-pod
    relays, restarted under exponential backoff with flap counting
    (runner/supervisor.py), reaped on exit. Returns (supervisor|None,
    env) — env gains HOROVOD_ROOT_ADDRS / relay pointers for workers.
    With --root-replicas 1 and no relays, returns (None, env)
    untouched: today's single-root path, bit-for-bit."""
    n_roots = int(getattr(args, "root_replicas", 0) or 0)
    n_relays = int(getattr(args, "pod_relays", 0) or 0)
    if n_roots <= 1 and n_relays <= 0:
        return None, env
    import tempfile

    from ..core.knobs import Knobs
    from .supervisor import ProcessSupervisor, python_child_argv

    kb = Knobs.from_env()
    sup = ProcessSupervisor(
        base_delay_s=kb.supervisor_base_delay_seconds,
        max_delay_s=kb.supervisor_max_delay_seconds,
        flap_window_s=kb.supervisor_flap_window_seconds,
    )
    env = dict(env)
    addr = _advertise_addr(hosts)
    lease_ttl = (args.root_lease_ttl
                 if getattr(args, "root_lease_ttl", None)
                 else kb.root_lease_ttl_seconds)
    heartbeat = (args.root_heartbeat
                 if getattr(args, "root_heartbeat", None)
                 else kb.root_heartbeat_seconds)
    roots = None
    try:
        if n_roots > 1:
            ports = _reserve_ports(n_roots)
            roots = ",".join(f"{addr}:{p}" for p in ports)
            state_dir = (args.root_state_dir
                         or tempfile.mkdtemp(prefix="hvd_root_"))
            for i in range(n_roots):
                sup.add(
                    f"root.replica.{i}",
                    python_child_argv(
                        "horovod_tpu.runner.http.http_server",
                        "--replica-id", str(i),
                        "--roots", roots,
                        "--state-path",
                        os.path.join(state_dir, f"replica_{i}.pkl"),
                        "--lease-ttl", str(lease_ttl),
                        "--heartbeat-interval", str(heartbeat),
                        "--vnodes", str(kb.root_vnodes),
                    ))
            _wait_for_roots(roots)
            # the fleet-wide root-set contract: index = replica id;
            # http_client shard-routes any call aimed at these
            env["HOROVOD_ROOT_ADDRS"] = roots
        if n_relays > 0:
            relay_roots = roots
            if relay_roots is None:
                # single-root world: relays forward to the published
                # rendezvous address, exactly as operators did by hand
                raddr = env.get("HVD_TPU_RENDEZVOUS_ADDR") or env.get(
                    "HOROVOD_GLOO_RENDEZVOUS_ADDR")
                rport = env.get("HVD_TPU_RENDEZVOUS_PORT") or env.get(
                    "HOROVOD_GLOO_RENDEZVOUS_PORT")
                if not raddr or not rport:
                    raise ValueError(
                        "--pod-relays without --root-replicas needs a "
                        "published rendezvous address in the "
                        "environment")
                relay_roots = f"{raddr}:{rport}"
            rports = _reserve_ports(n_relays)
            for i in range(n_relays):
                sup.add(
                    f"relay.proc.pod{i}",
                    python_child_argv(
                        "horovod_tpu.multipod.relay",
                        "--pod-label", f"pod{i}",
                        "--roots", relay_roots,
                        "--port", str(rports[i]),
                    ))
            env["HOROVOD_RELAY_ADDRS"] = ",".join(
                f"pod{i}={addr}:{rports[i]}" for i in range(n_relays))
            if n_relays == 1:
                # single-pod: point every worker straight at it via the
                # existing relay discovery envs (multipod/relay.py)
                env["HOROVOD_RELAY_ADDR"] = addr
                env["HOROVOD_RELAY_PORT"] = str(rports[0])
    except Exception:
        sup.shutdown()
        raise
    sup.start()
    return sup, env


def _resolve_hosts(args) -> List[HostInfo]:
    if args.hostfile:
        return parse_hosts(parse_host_files(args.hostfile))
    if args.hosts:
        return parse_hosts(args.hosts)
    np = args.np or 1
    return [HostInfo("localhost", np)]


def is_elastic(args) -> bool:
    return bool(args.host_discovery_script or args.min_np or args.max_np)


def _run_static(args) -> int:
    from .exec_run import run_static

    hosts = _resolve_hosts(args)
    if args.np is None:
        args.np = sum(h.slots for h in hosts)
    env = config_parser.env_from_args(args, dict(os.environ))
    supervisor, env = _spawn_control_plane(args, env, hosts)
    try:
        codes = run_static(
            args.command, hosts, args.np, env=env,
            nics=args.nics.split(",") if args.nics else None,
        )
    finally:
        if supervisor is not None:
            supervisor.shutdown()
    # signal-killed workers report negative codes; any nonzero is failure
    failed = [c for c in codes if c != 0]
    return abs(failed[0]) if failed else (0 if codes else 1)


def _run_elastic(args) -> int:
    from .elastic.driver import ElasticDriver
    from .elastic.discovery import HostDiscoveryScript, HostManager
    from .elastic.settings import ElasticSettings

    if not args.host_discovery_script:
        raise ValueError(
            "elastic mode requires --host-discovery-script "
            "(reference launch.py:676)"
        )
    settings = ElasticSettings(
        min_np=args.min_np or args.np or 1,
        max_np=args.max_np,
        timeout_s=args.elastic_timeout or 600.0,
        reset_limit=args.reset_limit or 0,
        cooldown_range=tuple(args.cooldown_range)
        if args.cooldown_range else None,
        # None falls back to the HOROVOD_ELASTIC_* env knobs
        host_vanish_grace_s=args.vanish_grace,
        spawn_join_timeout_s=args.spawn_join,
    )
    discovery = HostDiscoveryScript(
        args.host_discovery_script, args.slots
    )
    env = config_parser.env_from_args(args, dict(os.environ))
    supervisor, env = _spawn_control_plane(
        args, env, _resolve_hosts(args))
    driver = ElasticDriver(
        HostManager(discovery, settings.cooldown_range),
        settings,
        command=args.command,
        env=env,
        nics=args.nics.split(",") if args.nics else None,
        rendezvous_state_dir=args.rendezvous_state_dir or None,
        control_supervisor=supervisor,
    )
    try:
        return driver.run()
    finally:
        if supervisor is not None:
            supervisor.shutdown()  # idempotent with driver.stop()


def _check_build() -> int:
    """Availability table (reference launch.py:110 check_build). On TPU
    the controller is the XLA coordination service and the tensor ops
    are XLA collectives — the table reports what this install can use."""
    import importlib.util

    from .. import __version__

    def have(mod: str) -> str:
        return "X" if importlib.util.find_spec(mod) is not None else " "

    def native() -> str:
        try:
            from .._native import build

            build()
            return "X"
        except Exception:
            return " "

    print(f"horovod_tpu v{__version__}:\n")
    print("Available Frameworks:")
    print(f"    [{have('jax')}] JAX")
    print(f"    [{have('flax')}] Flax")
    print(f"    [{have('torch')}] PyTorch")
    print("\nAvailable Controllers:")
    print(f"    [{have('jax')}] XLA coordination service (jax.distributed)")
    print(f"    [{native()}] Native eager control plane (libhvd_tpu_core)")
    print("\nAvailable Tensor Operations:")
    print(f"    [{have('jax')}] XLA collectives (ICI/DCN)")
    print(f"    [{native()}] Negotiated eager (XlaExecutor)")
    print("\nAvailable Integrations:")
    print(f"    [{have('pyspark')}] Spark")
    print(f"    [{have('ray')}] Ray")
    return 0


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    if args.check_build:
        return _check_build()
    if not args.command:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if is_elastic(args):
        return _run_elastic(args)
    return _run_static(args)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
