"""Host discovery + blacklist with cooldown/resurrection.

Reference: /root/reference/horovod/runner/elastic/discovery.py —
`HostDiscovery` ABC (:226), `HostDiscoveryScript` (:232, runs the user's
executable and parses host[:slots] lines), `HostManager` (:152, polls
discovery, diffs against current state), blacklist with cooldown backoff
and resurrection (:33-111).
"""

from __future__ import annotations

import random
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...utils import faults, retry
from ..util.hosts import HostInfo

# update classification (reference HostUpdateResult flags)
NO_UPDATE = 0
ADDED = 1
REMOVED = 2
MIXED = ADDED | REMOVED

DEFAULT_COOLDOWN_RANGE = (10.0, 60.0)
COOLDOWN_BACKOFF = 2.0
COOLDOWN_CAP_MULTIPLIER = 16.0


class HostDiscovery:
    """Pluggable discovery interface (reference discovery.py:226)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """hostname → slot count of every currently-available host."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Run a user executable printing `host[:slots]` per line
    (reference discovery.py:232)."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self._script, shell=True, timeout=60
        ).decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            host, _, slots = line.partition(":")
            hosts[host] = int(slots) if slots else self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """Test/static discovery: a settable host set (reference
    test_elastic_driver.py mock pattern, SURVEY.md §4.1)."""

    def __init__(self, hosts: Optional[Dict[str, int]] = None):
        self._lock = threading.Lock()
        self._hosts = dict(hosts or {})

    def set(self, hosts: Dict[str, int]) -> None:
        with self._lock:
            self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hosts)


class _BlacklistEntry:
    def __init__(self, cooldown_range: Optional[Tuple[float, float]]):
        self._range = cooldown_range
        self._failures = 0
        self._until: float = float("inf")  # no cooldown → forever

    def blacklist(self) -> None:
        self._failures += 1
        if self._range is None:
            self._until = float("inf")
            return
        lo, hi = self._range
        backoff = min(
            COOLDOWN_BACKOFF ** (self._failures - 1), COOLDOWN_CAP_MULTIPLIER
        )
        self._until = time.time() + random.uniform(lo, hi) * backoff

    @property
    def active(self) -> bool:
        return time.time() < self._until


class DiscoveredHosts:
    """Immutable snapshot of available hosts minus blacklisted ones
    (reference discovery.py DiscoveredHosts)."""

    def __init__(self, hosts: Dict[str, int], order: List[str]):
        self._hosts = dict(hosts)
        self._order = list(order)

    @property
    def available_hosts(self) -> set:
        return set(self._hosts)

    def count_available_slots(self) -> int:
        return sum(self._hosts.values())

    @property
    def host_assignment_order(self) -> List[str]:
        return list(self._order)

    def get_slots(self, host: str) -> int:
        return self._hosts.get(host, 0)

    def host_infos(self) -> List[HostInfo]:
        return [HostInfo(h, self._hosts[h]) for h in self._order]


class HostManager:
    """Tracks the live host set: polls discovery, classifies changes,
    manages the blacklist (reference discovery.py:152 `HostManager`)."""

    def __init__(
        self,
        discovery: HostDiscovery,
        cooldown_range: Optional[Tuple[float, float]] = None,
    ):
        self._discovery = discovery
        self._cooldown_range = cooldown_range
        self._lock = threading.Lock()
        self._blacklist: Dict[str, _BlacklistEntry] = {}
        self._order: List[str] = []  # stable assignment order
        self._current = DiscoveredHosts({}, [])

    @property
    def current_hosts(self) -> DiscoveredHosts:
        with self._lock:
            return self._current

    def blacklist(self, host: str) -> None:
        with self._lock:
            entry = self._blacklist.get(host)
            if entry is None:
                entry = _BlacklistEntry(self._cooldown_range)
                self._blacklist[host] = entry
            entry.blacklist()

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            entry = self._blacklist.get(host)
            return entry.active if entry else False

    def _poll_discovery(self) -> Dict[str, int]:
        """One discovery poll under the shared retry policy: a
        transiently-failing discovery script (busy cloud API, fork
        failure) retries with backoff before the caller's own
        warn-and-skip handling kicks in. The ``discovery.poll`` fault
        point supports ``error`` (raise, exercising this retry) and
        ``flap`` (one poll reports an empty host set — momentary
        total-vanish chaos)."""
        def _do() -> Dict[str, int]:
            if faults.inject("discovery.poll") == "flap":
                return {}
            return self._discovery.find_available_hosts_and_slots()

        return retry.default_policy().call(
            _do,
            point="discovery.poll",
            retryable=lambda e: isinstance(
                e, (OSError, subprocess.SubprocessError)
            ),
        )

    def update_available_hosts(self) -> int:
        """Poll discovery once; returns NO_UPDATE/ADDED/REMOVED/MIXED."""
        discovered = self._poll_discovery()
        with self._lock:
            usable = {
                h: s
                for h, s in discovered.items()
                if not (
                    self._blacklist.get(h) and self._blacklist[h].active
                )
            }
            prev = self._current
            # keep stable ordering: surviving hosts keep their position so
            # rank assignments stay put (reference driver.py:240)
            order = [h for h in self._order if h in usable]
            order += [h for h in usable if h not in order]
            self._order = order
            result = NO_UPDATE
            if usable.keys() - prev.available_hosts:
                result |= ADDED
            if prev.available_hosts - usable.keys():
                result |= REMOVED
            if result == NO_UPDATE and any(
                prev.get_slots(h) != s for h, s in usable.items()
            ):
                result = MIXED
            self._current = DiscoveredHosts(usable, order)
            return result
