"""Elastic job settings (reference runner/elastic/settings.py,
constants.py)."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

ELASTIC_TIMEOUT_SECS_DEFAULT = 600.0
DISCOVERY_INTERVAL_SECS = 1.0


@dataclasses.dataclass
class ElasticSettings:
    min_np: int
    max_np: Optional[int] = None
    timeout_s: float = ELASTIC_TIMEOUT_SECS_DEFAULT
    reset_limit: int = 0  # 0 = unlimited resets
    cooldown_range: Optional[Tuple[float, float]] = None
    discovery_interval_s: float = DISCOVERY_INTERVAL_SECS
    # seconds a round's host may be absent from discovery before its
    # hung worker is counted failed (driver vanish watchdog), and the
    # post-round spawn-thread join budget. None = the
    # HOROVOD_ELASTIC_VANISH_GRACE / HOROVOD_ELASTIC_SPAWN_JOIN knobs
    # (defaults 5.0 / 30.0) — the former hardcoded magic numbers.
    host_vanish_grace_s: Optional[float] = None
    spawn_join_timeout_s: Optional[float] = None

    def __post_init__(self):
        if self.min_np < 1:
            raise ValueError("min_np must be >= 1")
        if self.max_np is not None and self.max_np < self.min_np:
            raise ValueError("max_np must be >= min_np")
        from ...core.knobs import _env_float

        if self.host_vanish_grace_s is None:
            self.host_vanish_grace_s = _env_float(
                "ELASTIC_VANISH_GRACE", 5.0
            )
        if self.spawn_join_timeout_s is None:
            self.spawn_join_timeout_s = _env_float(
                "ELASTIC_SPAWN_JOIN", 30.0
            )
        if self.host_vanish_grace_s <= 0:
            raise ValueError("host_vanish_grace_s must be > 0")
        if self.spawn_join_timeout_s <= 0:
            raise ValueError("spawn_join_timeout_s must be > 0")
