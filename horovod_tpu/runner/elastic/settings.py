"""Elastic job settings (reference runner/elastic/settings.py,
constants.py)."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

ELASTIC_TIMEOUT_SECS_DEFAULT = 600.0
DISCOVERY_INTERVAL_SECS = 1.0


@dataclasses.dataclass
class ElasticSettings:
    min_np: int
    max_np: Optional[int] = None
    timeout_s: float = ELASTIC_TIMEOUT_SECS_DEFAULT
    reset_limit: int = 0  # 0 = unlimited resets
    cooldown_range: Optional[Tuple[float, float]] = None
    discovery_interval_s: float = DISCOVERY_INTERVAL_SECS

    def __post_init__(self):
        if self.min_np < 1:
            raise ValueError("min_np must be >= 1")
        if self.max_np is not None and self.max_np < self.min_np:
            raise ValueError("max_np must be >= min_np")
