"""Worker notification protocol: driver → workers on host-set changes.

Reference: /root/reference/horovod/runner/elastic/worker.py —
`WorkerNotificationService` runs inside each worker process; the driver
holds a `WorkerNotificationClient` per worker and pushes
`HostsUpdatedRequest` when discovery sees a change; the worker-side
`WorkerNotificationManager` flips the host-update flag that
`State.commit()/check_host_updates()` converts into a
`HostsUpdatedInterrupt` (common/elastic.py:57-99).

Workers register their service address in the rendezvous KV store under
scope `workers`, key `rank_{rank}` (the reference registers through the
driver's own service; the KV store is our single bootstrap channel).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from ...utils import faults
from ..http import http_client
from ..util.network import AckResponse, BasicClient, BasicService
from ..util.secret import ENV_SECRET, secret_from_env

WORKERS_SCOPE = "workers"
SERVICE_NAME = "worker-notification"


class HostsUpdatedRequest:
    def __init__(self, timestamp: int, update_result: int):
        self.timestamp = timestamp
        self.update_result = update_result


class WorkerNotificationService(BasicService):
    """In-worker TCP service receiving host-update pushes."""

    def __init__(self, key: bytes, manager: "WorkerNotificationManager"):
        super().__init__(SERVICE_NAME, key)
        self._manager = manager

    def _handle(self, req, client_address):
        if isinstance(req, HostsUpdatedRequest):
            self._manager.handle_hosts_updated(
                req.timestamp, req.update_result
            )
            return AckResponse()
        return super()._handle(req, client_address)


class WorkerNotificationClient(BasicClient):
    """Driver-side client to one worker's notification service."""

    def __init__(self, addresses: List[Tuple[str, int]], key: bytes,
                 timeout_s: float = 10.0):
        super().__init__(SERVICE_NAME, addresses, key, timeout_s=timeout_s)

    def notify_hosts_updated(self, timestamp: int, update_result: int) -> None:
        self.request(HostsUpdatedRequest(timestamp, update_result))


class WorkerNotificationManager:
    """Worker-side singleton: starts the service, registers its address,
    relays pushes into the elastic state flag
    (reference worker.py WorkerNotificationManager)."""

    def __init__(self) -> None:
        self._service: Optional[WorkerNotificationService] = None
        self._timestamp = 0

    def init(self) -> None:
        if self._service is not None:
            return
        rendezvous_addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
        if not rendezvous_addr:
            return  # not launched by hvdrun; notifications disabled
        key = secret_from_env()
        self._service = WorkerNotificationService(key, self)
        rank = os.environ.get("HVD_TPU_RANK", "0")
        port = int(os.environ["HVD_TPU_RENDEZVOUS_PORT"])
        payload = json.dumps(self._service.addresses()).encode()
        # the PUT itself retries transport failures (http_client); the
        # fault point lets chaos specs fail registration specifically
        faults.inject("worker.register", rank=rank)
        http_client.put(
            rendezvous_addr, port, WORKERS_SCOPE, f"rank_{rank}", payload
        )

    def handle_hosts_updated(self, timestamp: int, update_result: int) -> None:
        if timestamp <= self._timestamp:
            return
        self._timestamp = timestamp
        from ...elastic.state import host_update_flag

        host_update_flag.signal()

    def shutdown(self) -> None:
        if self._service is not None:
            self._service.shutdown()
            self._service = None


notification_manager = WorkerNotificationManager()


def get_worker_client(
    rendezvous_addr: str,
    rendezvous_port: int,
    rank: int,
    key: bytes,
    timeout_s: float = 10.0,
) -> Optional[WorkerNotificationClient]:
    """Driver-side: look up a worker's registered address and connect."""
    raw = http_client.get(
        rendezvous_addr, rendezvous_port, WORKERS_SCOPE, f"rank_{rank}"
    )
    if raw is None:
        return None
    addresses = [tuple(a) for a in json.loads(raw.decode())]
    try:
        return WorkerNotificationClient(addresses, key, timeout_s=timeout_s)
    except ConnectionError:
        return None
