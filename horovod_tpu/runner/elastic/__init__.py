"""Elastic launcher: dynamic world size with fault tolerance.

Reference: /root/reference/horovod/runner/elastic/ — ElasticDriver
(driver.py:69), host discovery + blacklist (discovery.py), worker state
registry (registration.py), worker notification protocol (worker.py).
The worker-side state commit/restore/sync lives in horovod_tpu/elastic/.
"""

from .discovery import (  # noqa: F401
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
)
from .driver import ElasticDriver  # noqa: F401
from .settings import ElasticSettings  # noqa: F401
