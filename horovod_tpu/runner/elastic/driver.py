"""Elastic driver: discovery loop, rank-stable reassignment, fault rounds.

Reference: /root/reference/horovod/runner/elastic/driver.py:69
(`ElasticDriver`) — a discovery thread polls
`HostManager.update_available_hosts` every second (:102); host-set changes
push notifications to workers (:210); `_update_host_assignments` (:240)
recomputes SlotInfo preserving surviving ranks; `WorkerStateRegistry`
barriers trigger `resume()`; failing hosts are blacklisted; `reset_limit`
bounds total resets.

TPU adaptation: a *reset* respawns worker processes on the new host set
(the JAX runtime re-initializes its coordination service + device mesh at
startup; in-process slice resize is not supported by XLA). Worker-side
state continuity across resets is the elastic State's job: commit()
snapshots survive in the coordinator's memory or on disk
(horovod_tpu/elastic/state.py), and on respawn `state.sync()` restores
from rank 0. Between resets, in-flight workers are notified of host
changes through WorkerNotificationClient so they can commit early.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

from ...elastic.preemption import PREEMPTED_EXIT_CODE
from ...utils import faults, retry
from ...utils import metrics as _metrics
from ..exec_run import launch_slots
from ..http.http_server import RendezvousServer
from ..util.hosts import SlotInfo, get_host_assignments
from ..util.network import get_local_host_addresses
from ..util.secret import ENV_SECRET, make_secret_key
from .discovery import NO_UPDATE, HostManager
from .registration import FAILURE, SUCCESS, WorkerStateRegistry
from .settings import ElasticSettings
from .worker import get_worker_client

LOG = logging.getLogger("horovod_tpu.elastic")


class ElasticDriver:
    def __init__(
        self,
        host_manager: HostManager,
        settings: ElasticSettings,
        command: List[str],
        env: Dict[str, str],
        exec_fn: Optional[Callable] = None,
        nics: Optional[List[str]] = None,
        rendezvous_state_dir: Optional[str] = None,
        control_supervisor=None,
    ):
        self._host_manager = host_manager
        self._settings = settings
        self._command = list(command)
        self._env = dict(env)
        # explicit --network-interface pins control-plane binding for
        # every elastic round (auto ring-probing per round would add a
        # discovery round-trip to each respawn; explicit only)
        self._nics = list(nics) if nics else None
        if ENV_SECRET not in self._env:
            self._env[ENV_SECRET] = make_secret_key().decode()
        self._exec_fn = exec_fn

        self._registry = WorkerStateRegistry(self._on_barrier)
        # --rendezvous-state-dir: the KV store (rendezvous state,
        # worker registrations, replication manifests, shipped flight
        # dumps, metrics pushes) persists to an atomic on-disk
        # snapshot, so a crashed-and-restarted driver resumes the same
        # job — same port, same round, same rank assignments — while
        # workers ride their RetryPolicy through the outage
        # (docs/recovery.md).
        self._rendezvous = RendezvousServer(
            state_dir=rendezvous_state_dir)
        self._rank_assignments: Dict[str, List[int]] = {}
        self._assignments: List[SlotInfo] = []
        if self._rendezvous.restored:
            for slot in self._rendezvous.last_assignments():
                self._rank_assignments.setdefault(
                    slot.hostname, []).append(slot.rank)
            if self._rank_assignments:
                LOG.warning(
                    "resuming persisted rendezvous state (round %d, "
                    "rank assignments %s)", self._rendezvous.round,
                    self._rank_assignments,
                )

        # launcher-spawned control-plane tier (sharded root replicas +
        # pod relays, runner/supervisor.py): the driver owns its
        # lifetime — elastic rounds come and go, the tier persists
        # across them and is reaped exactly once at driver stop
        # (docs/control_plane.md)
        self._control_supervisor = control_supervisor

        self._shutdown = threading.Event()
        self._notify_addr: Optional[str] = None
        self._notify_retry = retry.RetryPolicy(
            max_attempts=2, base_delay_s=0.05, max_delay_s=0.2
        )
        self._barrier_states: Optional[Dict[str, str]] = None
        self._barrier_event = threading.Event()
        self._notify_timestamp = 0
        self._discovery_thread: Optional[threading.Thread] = None
        self._resets = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Begin host discovery (reference driver.py:102)."""
        self._host_manager.update_available_hosts()
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, daemon=True, name="elastic-discovery"
        )
        self._discovery_thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._discovery_thread is not None:
            self._discovery_thread.join(timeout=5)
        self._rendezvous.shutdown_server()
        if self._control_supervisor is not None:
            self._control_supervisor.shutdown()

    def wait_for_available_slots(
        self, min_np: int, timeout_s: Optional[float] = None
    ) -> int:
        """Block until discovery reports >= min_np slots
        (reference driver.py:153)."""
        timeout_s = (
            timeout_s if timeout_s is not None else self._settings.timeout_s
        )
        # monotonic deadline: a wall-clock step (NTP, date -s) must not
        # expire — or extend — the wait
        deadline = retry.Deadline(timeout_s)
        while not deadline.expired() and not self._shutdown.is_set():
            n = self._host_manager.current_hosts.count_available_slots()
            if n >= min_np:
                return n
            time.sleep(0.1)
        raise TimeoutError(
            f"timed out waiting for {min_np} slots "
            f"(have {self._host_manager.current_hosts.count_available_slots()})"
        )

    # ------------------------------------------------------------- main loop

    def run(self) -> int:
        """Run elastic rounds until global success or unrecoverable failure."""
        self.start()
        try:
            while not self._shutdown.is_set():
                try:
                    self.wait_for_available_slots(self._settings.min_np)
                except TimeoutError as e:
                    LOG.error("elastic job cannot continue: %s", e)
                    return 1
                states = self._run_round()
                if states and all(s == SUCCESS for s in states.values()):
                    return 0
                self._resets += 1
                if (
                    self._settings.reset_limit
                    and self._resets >= self._settings.reset_limit
                ):
                    LOG.error(
                        "elastic reset limit %d reached",
                        self._settings.reset_limit,
                    )
                    return 1
            return 1
        finally:
            self.stop()

    def _run_round(self) -> Dict[str, str]:
        assignments = self._update_host_assignments()
        self._assignments = assignments
        self._registry.reset(len(assignments))
        self._barrier_event.clear()
        def _init_rendezvous():
            faults.inject("rendezvous.init", round=self._rendezvous.round)
            self._rendezvous.init(assignments)

        retry.default_policy().call(
            _init_rendezvous, point="rendezvous.init"
        )
        _metrics.record_elastic_event("round")

        spawn_done = threading.Event()

        def spawn():
            try:
                launch_slots(
                    self._command,
                    assignments,
                    self._env,
                    rendezvous=self._rendezvous,
                    exec_fn=self._wrap_exec(),
                    nics=self._nics,
                )
            finally:
                spawn_done.set()

        threading.Thread(target=spawn, daemon=True).start()
        # watchdog while waiting on the round barrier: a worker whose exec
        # hangs (dead host, stuck ssh) never reaches a terminal state on
        # its own — once discovery stops listing its host, count it failed
        # so the barrier can complete (reference driver.py:304 handles
        # this via worker exit; a hung ssh never exits)
        vanished_since: Dict[str, float] = {}
        grace = self._settings.host_vanish_grace_s
        while not self._barrier_event.wait(timeout=1.0):
            if self._shutdown.is_set():
                break
            live = self._host_manager.current_hosts.available_hosts
            now = time.monotonic()  # step-immune vanish accounting
            for slot in assignments:
                if slot.hostname in live:
                    vanished_since.pop(slot.hostname, None)
                elif (
                    now - vanished_since.setdefault(slot.hostname, now)
                    > grace
                ):
                    self._registry.record_failure(
                        slot.hostname, slot.local_rank
                    )
        spawn_done.wait(timeout=self._settings.spawn_join_timeout_s)
        # barrier may never have fired if shutdown interrupted the round —
        # an empty dict means "no successful round", never a crash in run()
        states = self._barrier_states or {}
        if states:
            for key, state in states.items():
                if state == FAILURE:
                    host = key.rsplit(":", 1)[0]
                    self._host_manager.blacklist(host)
                    _metrics.record_elastic_event("blacklist")
                    LOG.warning("blacklisting failed host %s", host)
            self._host_manager.update_available_hosts()
        if not states or any(s != SUCCESS for s in states.values()):
            # failed/aborted round: the next rendezvous.init wipes the
            # store, and with it any flight dumps the dying workers
            # shipped — persist them to disk first so the post-mortem
            # survives the respawn (docs/flight.md)
            self._persist_flight_dumps()
        return states

    def _persist_flight_dumps(self) -> None:
        """Write worker flight dumps (PUT /flight/<rank>) out of the
        rendezvous store into HOROVOD_FLIGHT_DIR for offline analysis
        with scripts/flight_analyze.py."""
        import json

        from ..http.http_server import FLIGHT_META_SCOPE
        from ...utils.flight import FLIGHT_SCOPE

        with self._rendezvous.lock:
            dumps = dict(self._rendezvous.store.get(FLIGHT_SCOPE, {}))
            meta = dict(self._rendezvous.store.get(FLIGHT_META_SCOPE, {}))
        if not dumps:
            return
        import tempfile

        directory = (
            os.environ.get("HVD_TPU_FLIGHT_DIR")
            or os.environ.get("HOROVOD_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "hvd_flight")
        )
        try:
            os.makedirs(directory, exist_ok=True)
            for rank_key, payload in dumps.items():
                path = os.path.join(
                    directory, f"flight_rank{rank_key}.jsonl")
                # single-host launches share this path with the
                # worker's own rank-local writes: a final crash dump
                # that landed locally but whose PUT never reached us
                # would be clobbered by our (older) stored copy —
                # keep whichever is newer than the receipt stamp
                try:
                    recv = json.loads(meta[rank_key]).get(
                        "recv_time_unix", 0.0)
                except (KeyError, ValueError):
                    recv = 0.0
                if (os.path.exists(path)
                        and os.path.getmtime(path) > recv):
                    continue
                with open(path, "wb") as f:
                    f.write(payload)
            LOG.warning(
                "flight recorder: persisted dumps from ranks %s to %s "
                "— analyze with: python scripts/flight_analyze.py "
                "%s/flight_rank*.jsonl",
                sorted(dumps), directory, directory,
            )
        except OSError as e:
            LOG.warning("could not persist flight dumps: %s", e)

    def _wrap_exec(self) -> Callable:
        """Exec wrapper recording worker exit states into the registry
        (reference driver.py:304 _handle_worker_exit)."""
        inner = self._exec_fn

        def exec_and_record(command, env, slot, events):
            self._registry.record_ready(slot.hostname, slot.local_rank)
            try:
                # chaos hook: a driver.exec error rule makes this slot's
                # exec fail without a process ever spawning (dead ssh)
                faults.inject(
                    "driver.exec", host=slot.hostname, rank=slot.rank
                )
                if inner is not None:
                    code = inner(command, env, slot, events)
                else:
                    from ..exec_run import _exec_local, _exec_ssh

                    local = set(get_local_host_addresses() + ["localhost"])
                    fn = _exec_local if slot.hostname in local else _exec_ssh
                    code = fn(command, env, slot, events)
            except Exception as e:
                # an exec that raises (bad command, ssh failure) must still
                # reach a terminal state or the round barrier never fires
                LOG.warning(
                    "worker exec for rank %d raised: %s", slot.rank, e
                )
                code = 1
            if code == 0:
                self._registry.record_success(slot.hostname, slot.local_rank)
            elif code == PREEMPTED_EXIT_CODE:
                # Preempted: the worker's SIGTERM handler committed its
                # state (+ emergency checkpoint) and exited with the
                # "host going away" code. Terminal for the barrier, but
                # the host was healthy — blacklisting it would shrink
                # the next round for no reason (elastic/preemption.py).
                _metrics.record_elastic_event("worker_preempted")
                LOG.warning(
                    "rank %d on %s preempted; host stays eligible",
                    slot.rank, slot.hostname,
                )
                self._registry.record_aborted(slot.hostname, slot.local_rank)
            elif (
                code < 0 and events and any(e.is_set() for e in events)
            ):
                # Killed by signal while the round was aborting: the
                # launcher terminated this worker because ANOTHER slot
                # failed first (any-failure-kills-the-round). Terminal for
                # the barrier, but not this host's fault — it stays
                # eligible for the next round with its rank preserved.
                # A worker that exited nonzero on its own (code > 0) is a
                # real FAILURE even if the event fired meanwhile — two
                # simultaneous crashes must both blacklist.
                self._registry.record_aborted(slot.hostname, slot.local_rank)
            elif code == -signal.SIGTERM:
                # SIGTERM from outside the launcher (no abort event):
                # the platform is reclaiming the host and the worker had
                # no handler installed. Same preemption semantics — the
                # host goes away through no fault of its own.
                _metrics.record_elastic_event("worker_preempted")
                LOG.warning(
                    "rank %d on %s killed by external SIGTERM; treating "
                    "as preemption, host stays eligible",
                    slot.rank, slot.hostname,
                )
                self._registry.record_aborted(slot.hostname, slot.local_rank)
            else:
                self._registry.record_failure(slot.hostname, slot.local_rank)
            return code

        return exec_and_record

    def _on_barrier(self, states: Dict[str, str]) -> None:
        self._barrier_states = states
        self._barrier_event.set()

    # ------------------------------------------------------- host management

    def _update_host_assignments(self) -> List[SlotInfo]:
        """Recompute slot assignments, keeping surviving hosts' ranks
        (reference driver.py:240-283)."""
        hosts = self._host_manager.current_hosts.host_infos()
        assignments = get_host_assignments(
            hosts,
            self._settings.min_np,
            self._settings.max_np,
            rank_assignments=self._rank_assignments,
        )
        new_ranks: Dict[str, List[int]] = {}
        for slot in assignments:
            new_ranks.setdefault(slot.hostname, []).append(slot.rank)
        self._rank_assignments = new_ranks
        return assignments

    def _discovery_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                result = self._host_manager.update_available_hosts()
            except Exception as e:  # discovery script hiccup: warn, retry
                LOG.warning("host discovery failed: %s", e)
                result = NO_UPDATE
            if result != NO_UPDATE:
                self._notify_workers_host_changes(result)
            self._shutdown.wait(self._settings.discovery_interval_s)

    def _notification_addr(self) -> str:
        """The local address for worker-client lookups: the pinned
        --network-interface NIC when one was given (so notifications
        bind the same plane the data path was pinned to), else the most
        routable local address. Cached — the NIC set is fixed for the
        driver's lifetime."""
        if self._notify_addr is not None:
            return self._notify_addr
        if not self._nics:
            self._notify_addr = get_local_host_addresses()[-1]
            return self._notify_addr
        try:
            from ..driver.probe import interface_addresses

            by_iface = interface_addresses(self._nics)
            for nic in self._nics:
                if nic in by_iface:
                    self._notify_addr = by_iface[nic]
                    return self._notify_addr
        except Exception as e:
            LOG.warning(
                "could not resolve --network-interface %s for worker "
                "notifications (%s); using default address for this "
                "round", self._nics, e,
            )
        # do NOT cache the fallback: a NIC still coming up must win the
        # next attempt, or the pin would be silently lost for the run
        return get_local_host_addresses()[-1]

    def _notify_workers_host_changes(self, update_result: int) -> None:
        """Push HostsUpdatedRequest to every registered worker
        (reference driver.py:210)."""
        self._notify_timestamp += 1
        addr = self._notification_addr()
        port = self._rendezvous.port
        key = self._env[ENV_SECRET].encode()
        timestamp = self._notify_timestamp
        for slot in self._assignments:
            def _notify(slot=slot):
                faults.inject("worker.notify", rank=slot.rank)
                client = get_worker_client(addr, port, slot.rank, key)
                if client is not None:
                    client.notify_hosts_updated(timestamp, update_result)

            try:
                # one quick retry, not the full backoff ladder: dead
                # workers are EXPECTED here (that is often the very
                # change being notified) and this loop runs on the
                # discovery thread — a truly-gone worker stays a DEBUG
                # line after one cheap re-attempt
                self._notify_retry.call(
                    _notify, point="worker.notify",
                    retryable=lambda e: isinstance(e, (OSError, EOFError)),
                )
            except Exception as e:
                LOG.debug("notify rank %d failed: %s", slot.rank, e)
