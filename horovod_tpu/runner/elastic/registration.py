"""Per-slot worker state registry.

Reference: /root/reference/horovod/runner/elastic/registration.py:28
(`WorkerStateRegistry`) — collects READY/SUCCESS/FAILURE reports per slot
for the current rendezvous round; when every slot of the round has
reported, fires the driver's barrier callback (driver.resume or shutdown).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"
# killed by the launcher because ANOTHER worker failed (round abort):
# terminal for the barrier, but not the worker's own fault — its host must
# not be blacklisted (reference keeps surviving workers alive instead;
# the respawn model terminates and re-launches them)
ABORTED = "ABORTED"

_TERMINAL = (SUCCESS, FAILURE, ABORTED)


class WorkerStateRegistry:
    def __init__(self, on_barrier: Callable[[Dict[str, str]], None]):
        self._on_barrier = on_barrier
        self._lock = threading.Lock()
        self._expected = 0
        self._round = 0
        self._states: Dict[str, str] = {}  # "host:local_rank" → state

    def reset(self, expected_workers: int) -> None:
        """New rendezvous round (reference registration.py reset)."""
        with self._lock:
            self._expected = expected_workers
            self._states = {}
            self._round += 1

    @property
    def round(self) -> int:
        return self._round

    def _record(self, key: str, state: str) -> None:
        fire: Optional[Dict[str, str]] = None
        with self._lock:
            # first terminal state wins (a FAILURE then exit-0 is FAILURE)
            if self._states.get(key) in _TERMINAL:
                return
            self._states[key] = state
            terminal = [
                s for s in self._states.values() if s in _TERMINAL
            ]
            if self._expected and len(terminal) >= self._expected:
                fire = dict(self._states)
        if fire is not None:
            self._on_barrier(fire)

    def record_ready(self, host: str, local_rank: int) -> None:
        self._record(f"{host}:{local_rank}", READY)

    def record_success(self, host: str, local_rank: int) -> None:
        self._record(f"{host}:{local_rank}", SUCCESS)

    def record_failure(self, host: str, local_rank: int) -> None:
        self._record(f"{host}:{local_rank}", FAILURE)

    def record_aborted(self, host: str, local_rank: int) -> None:
        self._record(f"{host}:{local_rank}", ABORTED)

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def get(self, host: str, local_rank: int) -> Optional[str]:
        with self._lock:
            return self._states.get(f"{host}:{local_rank}")
