"""Compute service: register data-producing workers, hand out shards.

Reference: /root/reference/horovod/runner/common/service/compute_service.py
:97,219 (`ComputeService`/`ComputeClient`) — the registry behind
`horovod.tensorflow.data.compute` (TF data-service dispatchers/workers on
Horovod slots). TPU-analog: a generic registry over the launcher's
authenticated TCP transport — compute workers register (kind, index,
address); trainers wait for and look up all workers of a kind; shutdown
broadcasts to every waiter.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .util.network import AckResponse, BasicClient, BasicService

SERVICE_NAME = "compute-service"


class RegisterWorkerRequest:
    def __init__(self, kind: str, index: int, address: str):
        self.kind = kind
        self.index = index
        self.address = address


class WaitForWorkersRequest:
    def __init__(self, kind: str, count: int, timeout_s: float):
        self.kind = kind
        self.count = count
        self.timeout_s = timeout_s


class WorkersResponse:
    def __init__(self, addresses: Dict[int, str]):
        self.addresses = addresses


class ShutdownRequest:
    pass


class ComputeService(BasicService):
    """Driver-side registry (reference compute_service.py:97)."""

    def __init__(self, key: bytes, port: int = 0):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._workers: Dict[str, Dict[int, str]] = {}
        self._shutdown = False
        super().__init__(SERVICE_NAME, key, port=port)

    def _handle(self, req, client_address):
        if isinstance(req, RegisterWorkerRequest):
            with self._cv:
                self._workers.setdefault(req.kind, {})[req.index] = (
                    req.address
                )
                self._cv.notify_all()
            return AckResponse()
        if isinstance(req, WaitForWorkersRequest):
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: self._shutdown
                    or len(self._workers.get(req.kind, {})) >= req.count,
                    timeout=req.timeout_s,
                )
                if self._shutdown:
                    return WorkersResponse({})
                del ok  # on timeout, return what we have
                return WorkersResponse(dict(self._workers.get(req.kind, {})))
        if isinstance(req, ShutdownRequest):
            with self._cv:
                self._shutdown = True
                self._cv.notify_all()
            return AckResponse()
        return super()._handle(req, client_address)


class ComputeClient(BasicClient):
    """Worker/trainer-side client (reference compute_service.py:219)."""

    def __init__(self, addresses: List[Tuple[str, int]], key: bytes,
                 timeout_s: float = 30.0):
        super().__init__(SERVICE_NAME, addresses, key, timeout_s=timeout_s)

    def register_worker(self, kind: str, index: int, address: str) -> None:
        self.request(RegisterWorkerRequest(kind, index, address))

    def wait_for_workers(self, kind: str, count: int,
                         timeout_s: float = 60.0) -> Dict[int, str]:
        # transport timeout must outlast the server-side wait, or the
        # socket read times out before the server's cv.wait_for returns
        saved = self._timeout
        self._timeout = max(saved, timeout_s + 10.0)
        try:
            resp = self.request(WaitForWorkersRequest(kind, count, timeout_s))
        finally:
            self._timeout = saved
        return resp.addresses

    def shutdown_service(self) -> None:
        self.request(ShutdownRequest())
