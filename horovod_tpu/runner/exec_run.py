"""Static-run slot spawning (the gloo_run analog).

Reference: /root/reference/horovod/runner/gloo_run.py — `launch_gloo`
(:242): start an in-proc RendezvousServer, compute SlotInfo assignments,
build per-slot env (HOROVOD_RANK/SIZE/... :66-101), spawn each slot via
local exec or ssh in a thread pool, and kill everything if any slot fails
(:137-199).

TPU mapping: one slot per *host process*; the first assigned host doubles
as the JAX coordination-service coordinator (jax.distributed), published to
all workers via env. Per-slot env keeps the HOROVOD_* names so reference
scripts run unmodified, plus HVD_TPU_* equivalents.
"""

from __future__ import annotations

import os
import shlex
import sys
import threading
from typing import Callable, Dict, List, Optional

from .http.http_server import RendezvousServer
from .util import safe_shell_exec
from .util.hosts import HostInfo, SlotInfo, get_host_assignments
from .util.network import (
    find_free_port,
    get_local_host_addresses,
    is_local_host,
    routable_host_address,
)
from .util.secret import ENV_SECRET

JAX_COORD_PORT_OFFSET = 19  # coordinator port = rendezvous port + offset
NATIVE_COORD_PORT_OFFSET = 23  # native control-plane coordinator port


def slot_env(
    slot: SlotInfo,
    base_env: Dict[str, str],
    rendezvous_addr: str,
    rendezvous_port: int,
    coordinator_address: str,
    native_coordinator_port: int = 0,
) -> Dict[str, str]:
    """Per-slot worker environment (reference gloo_run.py:66-101)."""
    env = dict(base_env)
    pairs = {
        "RANK": slot.rank,
        "SIZE": slot.size,
        "LOCAL_RANK": slot.local_rank,
        "LOCAL_SIZE": slot.local_size,
        "CROSS_RANK": slot.cross_rank,
        "CROSS_SIZE": slot.cross_size,
    }
    for name, v in pairs.items():
        env[f"HOROVOD_{name}"] = str(v)
        env[f"HVD_TPU_{name}"] = str(v)
    env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = rendezvous_addr
    env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(rendezvous_port)
    env["HVD_TPU_RENDEZVOUS_ADDR"] = rendezvous_addr
    env["HVD_TPU_RENDEZVOUS_PORT"] = str(rendezvous_port)
    env["HOROVOD_CONTROLLER"] = "xla"
    env["HOROVOD_CPU_OPERATIONS"] = "xla"
    # JAX coordination service (the DCN control plane; SURVEY.md §2.6).
    # Each slot is one JAX process: on TPU pods that is one host driving
    # all its local chips (hosts listed as "host:1"); in CPU test worlds a
    # host may carry several single-device processes.
    env["HVD_TPU_COORDINATOR_ADDRESS"] = coordinator_address
    env["HVD_TPU_NUM_PROCESSES"] = str(slot.size)
    env["HVD_TPU_PROCESS_ID"] = str(slot.rank)
    # Native eager control plane (HVD_TPU_NATIVE=1): the rank-0 worker's
    # TcpController binds this port on its host; all workers dial it
    # (hvd.init → core/basics._start_native_eager). Always published —
    # harmless when native mode is off.
    if native_coordinator_port:
        env["HVD_TPU_NATIVE_COORDINATOR_ADDR"] = (
            coordinator_address.rsplit(":", 1)[0]
        )
        env["HVD_TPU_NATIVE_COORDINATOR_PORT"] = str(
            native_coordinator_port
        )
    return env


def _exec_local(command: List[str], env, slot: SlotInfo, events) -> int:
    return safe_shell_exec.execute(
        command, env=env, prefix=f"{slot.rank}", events=events
    )


def _remote_command(command: List[str], env) -> str:
    """The `cd && env ... cmd` line a remote shell runs: exports the
    control-plane env plus PATH/PYTHON* so venv/PYTHONPATH setups that
    work locally keep working over ssh."""
    exported = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in env.items()
        if k.startswith(("HOROVOD_", "HVD_TPU_", "PYTHON")) or k == "PATH"
    )
    return f"cd {shlex.quote(os.getcwd())} && env {exported} " + " ".join(
        shlex.quote(c) for c in command
    )


def _exec_ssh(command: List[str], env, slot: SlotInfo, events) -> int:
    # -tt allocates a tty so the remote worker gets SIGHUP when the local
    # ssh client is killed — no orphan trainers holding TPU chips
    ssh_cmd = [
        "ssh", "-tt", "-o", "StrictHostKeyChecking=no", slot.hostname,
        _remote_command(command, env),
    ]
    return safe_shell_exec.execute(
        ssh_cmd, env=dict(os.environ), prefix=f"{slot.rank}", events=events
    )


def launch_slots(
    command: List[str],
    assignments: List[SlotInfo],
    env: Dict[str, str],
    rendezvous: Optional[RendezvousServer] = None,
    exec_fn: Optional[Callable] = None,
    local_hosts: Optional[List[str]] = None,
    nics: Optional[List[str]] = None,
    nics_explicit: bool = True,
) -> List[int]:
    """Spawn one worker per slot; any failure terminates all others.

    Returns per-slot exit codes. `exec_fn(command, env, slot, events)` is
    injectable for tests (reference pattern: mocked ssh in test_run.py).
    """
    own = rendezvous is None
    if rendezvous is None:
        rendezvous = RendezvousServer()
        port = rendezvous.init(assignments)
    else:
        # caller (elastic driver) already published this round's
        # assignments; don't double-publish / double-bump the round
        port = rendezvous.port
    local = set(local_hosts) if local_hosts else None
    rendezvous_addr = routable_host_address()
    if all(
        slot.hostname in local if local else is_local_host(slot.hostname)
        for slot in assignments
    ):
        # single-host world: loopback always routes; the outbound-NIC
        # address may not accept hairpin connections (sandboxes,
        # firewalled hosts) and no remote worker needs to reach us
        rendezvous_addr = "127.0.0.1"
    if nics:
        env = dict(env)
        env["HOROVOD_NICS"] = ",".join(nics)
        # Rebind the launcher's rendezvous address only for an EXPLICIT
        # --network-interface: the user names a launcher NIC and gets it
        # verbatim. Auto-probed NICs were validated for WORKER-to-worker
        # routability — the launcher never probed itself, and a launcher
        # NIC that merely shares the name could carry an address workers
        # cannot route (reference ships probed NICs to NCCL/Gloo but
        # keeps its own service on all addresses, driver_service.py:260).
        if nics_explicit:
            from .driver.probe import interface_addresses

            by_iface = interface_addresses(nics)
            for nic in nics:
                if nic in by_iface:
                    rendezvous_addr = by_iface[nic]
                    break
    # The JAX coordination service runs inside the rank-0 *worker*, so the
    # coordinator address must name rank 0's host, not the launcher. For a
    # local rank-0 we can probe a free port; for a remote one use a
    # deterministic port derived from the rendezvous port.
    rank0_host = assignments[0].hostname
    if local and rank0_host in local or not local and is_local_host(rank0_host):
        coordinator = f"{rendezvous_addr}:{find_free_port()}"
        native_port = find_free_port()
    else:
        coordinator = f"{rank0_host}:{port + JAX_COORD_PORT_OFFSET}"
        native_port = port + NATIVE_COORD_PORT_OFFSET

    if ENV_SECRET not in env:
        from .util.secret import make_secret_key

        env = dict(env)
        env[ENV_SECRET] = make_secret_key().decode()

    failure = threading.Event()
    codes: List[Optional[int]] = [None] * len(assignments)

    def run_slot(i: int, slot: SlotInfo):
        wenv = slot_env(slot, env, rendezvous_addr, port, coordinator,
                        native_coordinator_port=native_port)
        fn = exec_fn
        if fn is None:
            slot_is_local = (
                slot.hostname in local if local else is_local_host(slot.hostname)
            )
            fn = _exec_local if slot_is_local else _exec_ssh
        try:
            codes[i] = fn(command, wenv, slot, [failure])
        except BaseException:
            codes[i] = 1
            raise
        finally:
            if codes[i] != 0:
                failure.set()

    threads = [
        threading.Thread(target=run_slot, args=(i, s), daemon=True)
        for i, s in enumerate(assignments)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if own:
        rendezvous.shutdown_server()
    return [c if c is not None else 1 for c in codes]


def probe_task_launcher(env: Dict[str, str]) -> Callable:
    """launch_task_fn for driver.probe.get_common_interfaces: start one
    probe task per host (local exec or ssh), detached — the task
    registers with the driver service and exits on its shutdown request
    (reference _launch_task_servers, driver_service.py:90)."""
    import base64
    import json

    secret = env.get(ENV_SECRET, os.environ.get(ENV_SECRET, ""))

    def launch(idx: int, host: str, driver_addresses) -> None:
        b64 = base64.b64encode(
            json.dumps([list(a) for a in driver_addresses]).encode()
        ).decode()
        # "python" resolves via the exported PATH on the remote host —
        # the launcher's sys.executable path may not exist there
        cmd = [
            "python", "-m", "horovod_tpu.runner.driver.probe_task",
            str(idx), b64,
        ]
        task_env = dict(os.environ)
        task_env[ENV_SECRET] = secret

        def run():
            if is_local_host(host):
                local_cmd = [sys.executable] + cmd[1:]
                safe_shell_exec.execute(local_cmd, env=task_env,
                                        prefix=f"probe-{idx}")
            else:
                # same env-export contract as worker ssh (_exec_ssh):
                # PATH/PYTHON* travel so venv setups keep working
                safe_shell_exec.execute(
                    ["ssh", "-tt", "-o", "StrictHostKeyChecking=no",
                     host, _remote_command(cmd, task_env)],
                    env=dict(os.environ), prefix=f"probe-{idx}",
                )

        threading.Thread(target=run, daemon=True,
                         name=f"probe-task-{idx}").start()

    return launch


def run_static(
    command: List[str],
    hosts: List[HostInfo],
    np: int,
    env: Optional[Dict[str, str]] = None,
    exec_fn: Optional[Callable] = None,
    nics: Optional[List[str]] = None,
) -> List[int]:
    """Static (non-elastic) launch: assignments once, run to completion.

    With remote hosts and no explicit `nics`, the task-to-task NIC probe
    runs first and the control plane binds only interfaces every host
    can actually route (reference driver_service.py:260)."""
    assignments = get_host_assignments(hosts, np, np)
    env = dict(env or os.environ)
    if ENV_SECRET not in env:
        from .util.secret import make_secret_key

        env[ENV_SECRET] = make_secret_key().decode()
    host_names = [h.hostname for h in hosts]
    explicit = bool(nics)
    if exec_fn is None and (
        nics or any(not is_local_host(h) for h in host_names)
    ):
        from .driver.probe import get_common_interfaces

        nics = get_common_interfaces(
            host_names, env[ENV_SECRET].encode(), nics=nics,
            launch_task_fn=probe_task_launcher(env),
        )
    return launch_slots(
        command, assignments, env, exec_fn=exec_fn, nics=nics,
        nics_explicit=explicit,
    )
