"""HTTP rendezvous + key/value store for bootstrap.

Reference: /root/reference/horovod/runner/http/http_server.py:192,232 —
`RendezvousServer` publishes per-slot SlotInfo under scope `rendezvous`
(workers GET their rank's record); `KVStoreServer` is a generic
PUT/GET/DELETE scope/key byte store used by worker-address registration and
elastic re-rendezvous. Paths: /scope/key. A GET for a missing key returns
404 so clients can poll-wait.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

LOG = logging.getLogger("horovod_tpu.runner")

from ...utils import faults
from ...utils.flight import FLIGHT_SCOPE
from ..util.hosts import SlotInfo

RENDEZVOUS_SCOPE = "rendezvous"

# one batched relay forward (multipod/relay.py): a per-pod relay PUTs
# /relay_batch/<pod_id> with a JSON array of {scope, key, value_b64}
# entries and the root unpacks it into the store under the original
# scopes — the O(pods) replacement for O(hosts) individual
# control-plane PUTs. JSON+base64, NOT pickle: this is an
# unauthenticated network surface and unpickling it would hand remote
# code execution to anyone who can reach the port.
RELAY_BATCH_PATH = "relay_batch"


def decode_relay_batch(body: bytes):
    """Parse + validate one relay batch; returns [(scope, key, value)]
    or raises ValueError. Validation is all-or-nothing so a malformed
    batch never half-applies."""
    import base64

    entries = json.loads(body)
    if not isinstance(entries, list):
        raise ValueError("relay batch is not a list")
    out = []
    for e in entries:
        if not isinstance(e, dict):
            raise ValueError("relay entry is not an object")
        scope, key = e.get("scope"), e.get("key")
        if not isinstance(scope, str) or not isinstance(key, str) \
                or not scope or not key or "/" in scope:
            raise ValueError("bad relay entry scope/key")
        try:
            value = base64.b64decode(e.get("value_b64", ""),
                                     validate=True)
        except Exception:
            raise ValueError("bad relay entry payload")
        out.append((scope, key, value))
    return out

# driver-side receipt stamps for worker flight dumps (PUT /flight/<r>):
# scripts/flight_analyze.py reads them as a second clock-alignment
# signal next to each dump's own /clock-probe offset
FLIGHT_META_SCOPE = "flight_meta"


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _split(self) -> Optional[Tuple[str, str]]:
        parts = self.path.strip("/").split("/", 1)
        if len(parts) != 2 or not parts[0] or not parts[1]:
            return None
        return parts[0], parts[1]

    def _count(self) -> None:
        """Request-count instrumentation: the control-plane fan-in
        scoreboard the relay reduction is measured against
        (scripts/multipod_check.py, scripts/control_plane_scaling.py
        --pods)."""
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.request_count = getattr(  # type: ignore[attr-defined]
                self.server, "request_count", 0) + 1

    def _injected_503(self) -> bool:
        """Server-side fault point: an ``http.server`` error rule turns
        this request into a 503 — the retryable-status path clients
        must survive (their 5xx-retry discipline, http_client.py)."""
        try:
            faults.inject("http.server", method=self.command)
        except faults.InjectedFault:
            self._reply(503, b"injected fault")
            return True
        return False

    def do_GET(self):
        self._count()
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/metrics":
            # cluster-aggregated telemetry scrape (utils/metrics.py):
            # this process's registry plus every worker exposition
            # pushed to /metrics_push/<rank>, the latter rank-labeled —
            # one endpoint answers for the whole world
            # (docs/metrics.md). Single-segment path — can't collide
            # with the scope/key namespace (always two segments).
            from ...utils import metrics

            with self.server.lock:  # type: ignore[attr-defined]
                pushed = dict(
                    self.server.store.get(  # type: ignore[attr-defined]
                        metrics.METRICS_PUSH_SCOPE, {})
                )
            ctype, body = metrics.exposition(pushed or None)
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/health":
            # fleet-health verdict (horovod_tpu/health): fold every
            # rank's pushed summary (/health/<rank>, pod-labeled by the
            # relay) into one live verdict naming suspected straggler
            # ranks — the runtime analogue of flight.straggler_report.
            # Single-segment path like /metrics; GET /health/<rank>
            # still reads one raw summary through the scope namespace.
            from ...health import fleet

            with self.server.lock:  # type: ignore[attr-defined]
                pushed = dict(
                    self.server.store.get(  # type: ignore[attr-defined]
                        fleet.HEALTH_SCOPE, {})
                )
            self._reply(200, json.dumps(
                fleet.evaluate_store(pushed)).encode())
            return
        if path == "/clock":
            # clock-alignment ping for the flight recorder: workers
            # stamp each dump with (server time - local time) measured
            # through this route so flight_analyze can merge per-rank
            # dumps onto the driver's time axis (utils/flight.py)
            self._reply(200, json.dumps(
                {"time_unix": time.time()}).encode())
            return
        if self._injected_503():
            return
        sk = self._split()
        store = self.server.store  # type: ignore[attr-defined]
        if sk is None:
            self._reply(400, b"bad path")
            return
        with self.server.lock:  # type: ignore[attr-defined]
            value = store.get(sk[0], {}).get(sk[1])
        if value is None:
            self._reply(404, b"not found")
        else:
            self._reply(200, value)

    def _store_one(self, scope: str, key: str, body: bytes) -> None:
        """One mutation into the store (lock held by the caller)."""
        store = self.server.store  # type: ignore[attr-defined]
        store.setdefault(scope, {})[key] = body
        if scope == FLIGHT_SCOPE:
            # PUT /flight/<rank>: stamp the driver-side receipt so
            # post-hoc analysis has a second alignment anchor and
            # an arrival order even for dumps whose /clock probe
            # failed
            store.setdefault(FLIGHT_META_SCOPE, {})[key] = (
                json.dumps({
                    "recv_time_unix": time.time(),
                    "bytes": len(body),
                }).encode()
            )

    def do_PUT(self):
        self._count()
        if self._injected_503():
            return
        sk = self._split()
        if sk is None:
            self._reply(400, b"bad path")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if sk[0] == RELAY_BATCH_PATH:
            # one pod relay's coalesced forward: unpack into the store
            # under the original scopes, exactly as if each entry had
            # arrived as its own PUT — every reader (aggregated
            # /metrics, recovery GETs, last_assignments) is oblivious
            # to whether a record came direct or relayed
            try:
                entries = decode_relay_batch(body)
            except Exception:
                self._reply(400, b"bad relay batch")
                return
            with self.server.lock:  # type: ignore[attr-defined]
                for scope, key, value in entries:
                    self._store_one(str(scope), str(key), value)
            self.server.dirty.set()  # type: ignore[attr-defined]
            self._reply(200, b"ok")
            return
        on_mutation = getattr(self.server, "on_mutation", None)
        with self.server.lock:  # type: ignore[attr-defined]
            self._store_one(sk[0], sk[1], body)
            if on_mutation is not None:
                # relay hook (multipod/relay.py): observe the mutation
                # for batched upward forwarding. UNDER the store lock:
                # two same-key PUTs racing outside it could reach the
                # hook in reverse order and forward the stale value
                # while the store holds the fresh one. The hook only
                # touches its own pending dict — no lock cycle.
                on_mutation(sk[0], sk[1], body)
        self.server.dirty.set()  # type: ignore[attr-defined]
        self._reply(200, b"ok")

    def do_DELETE(self):
        self._count()
        if self._injected_503():
            return
        sk = self._split()
        if sk is None:
            self._reply(400, b"bad path")
            return
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.store.get(sk[0], {}).pop(sk[1], None)  # type: ignore[attr-defined]
        self.server.dirty.set()  # type: ignore[attr-defined]
        self._reply(200, b"ok")

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request logging
        pass


class KVStoreServer:
    """Generic scope/key byte store over HTTP (reference :232).

    With ``state_path`` the store is durable: every mutation marks a
    dirty flag and a background flusher writes an atomic (tmp + rename)
    pickle snapshot — store contents, bound port, subclass extras — at
    most every ``flush_interval_s``. A server constructed on an
    existing snapshot reloads the store AND rebinds the same port, so a
    restarted rendezvous/driver answers at the address its workers are
    already retrying against (docs/recovery.md).
    """

    STATE_FORMAT = 1

    def __init__(self, port: int = 0,
                 store: Optional[Dict[str, Dict[str, bytes]]] = None,
                 state_path: Optional[str] = None,
                 flush_interval_s: float = 0.3):
        self._state_path = state_path
        self._flush_interval_s = flush_interval_s
        self._flush_stop = threading.Event()
        self._flush_thread: Optional[threading.Thread] = None
        restored = self._load_state() if state_path else None
        self.restored = restored is not None
        bind_port = port
        if restored is not None and not port:
            bind_port = int(restored.get("port", 0))
        try:
            self._httpd = ThreadingHTTPServer(
                ("0.0.0.0", bind_port), _KVHandler)
        except OSError:
            if not bind_port or bind_port == port:
                raise
            LOG.warning(
                "could not rebind persisted KV-store port %d; binding "
                "a fresh port (workers polling the old address will "
                "time out)", bind_port,
            )
            self._httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                              _KVHandler)
        init_store: Dict[str, Dict[str, bytes]] = (
            store if store is not None else {}
        )
        if restored is not None:
            for scope, kv in restored.get("store", {}).items():
                init_store.setdefault(scope, {}).update(kv)
        self._httpd.store = init_store  # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.dirty = threading.Event()  # type: ignore[attr-defined]
        self._httpd.request_count = 0  # type: ignore[attr-defined]
        self._httpd.on_mutation = None  # type: ignore[attr-defined]
        if restored is not None:
            self._apply_state_extra(restored.get("extra", {}))
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="kvstore",
        )

    def start_server(self) -> int:
        self._thread.start()
        if self._state_path and self._flush_thread is None:
            self._flush_stop.clear()
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True, name="kvstore-flush",
            )
            self._flush_thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def store(self) -> Dict[str, Dict[str, bytes]]:
        return self._httpd.store  # type: ignore[attr-defined]

    @property
    def lock(self):
        return self._httpd.lock  # type: ignore[attr-defined]

    @property
    def request_count(self) -> int:
        """Requests served since start — the fan-in scoreboard
        (multipod relay reduction is measured against this)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return int(self._httpd.request_count)  # type: ignore[attr-defined]

    def set_mutation_hook(self, fn) -> None:
        """Install a (scope, key, value) observer called after every
        direct PUT (relay forwarding, multipod/relay.py). None
        removes."""
        self._httpd.on_mutation = fn  # type: ignore[attr-defined]

    def shutdown_server(self) -> None:
        # BaseServer.shutdown() blocks on the serve_forever loop's ack, so
        # only call it if the loop is actually running.
        if self._thread.is_alive():
            self._httpd.shutdown()
            self._thread.join(timeout=5)
        if self._flush_thread is not None:
            self._flush_stop.set()
            self._flush_thread.join(timeout=5)
            self._flush_thread = None
        if self._state_path:
            self.persist()  # final flush: clean shutdowns lose nothing
        self._httpd.server_close()

    # -------------------------------------------------------- persistence

    def _state_extra(self) -> Dict:
        """Subclass hook: extra durable state (RendezvousServer adds
        its round counter)."""
        return {}

    def _apply_state_extra(self, extra: Dict) -> None:
        pass

    def persist(self) -> None:
        """Write the atomic on-disk snapshot now (flusher + shutdown
        path; callers may also force a barrier, e.g. after publishing a
        rendezvous round)."""
        if not self._state_path:
            return
        with self.lock:
            snap = {scope: dict(kv) for scope, kv in self.store.items()}
        payload = {
            "format": self.STATE_FORMAT,
            "time_unix": time.time(),
            "port": self.port,
            "store": snap,
            "extra": self._state_extra(),
        }
        try:
            d = os.path.dirname(os.path.abspath(self._state_path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{self._state_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._state_path)
        except OSError as e:
            LOG.warning("could not persist KV-store state: %s", e)

    def _load_state(self) -> Optional[Dict]:
        try:
            with open(self._state_path, "rb") as f:
                payload = pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception as e:
            LOG.warning(
                "ignoring unreadable KV-store state %s: %s",
                self._state_path, e,
            )
            return None
        if payload.get("format") != self.STATE_FORMAT:
            LOG.warning(
                "ignoring KV-store state %s with unknown format %r",
                self._state_path, payload.get("format"),
            )
            return None
        return payload

    def _flush_loop(self) -> None:
        dirty = self._httpd.dirty  # type: ignore[attr-defined]
        while not self._flush_stop.is_set():
            if dirty.wait(timeout=0.5):
                dirty.clear()
                self.persist()
                # debounce: batch bursts of mutations into one write
                self._flush_stop.wait(self._flush_interval_s)


class RendezvousServer(KVStoreServer):
    """KV store that additionally publishes slot assignments
    (reference http_server.py:192; elastic variant swaps assignments on
    every new rendezvous round).

    With ``state_dir`` the server is failover-capable: its scopes
    (rendezvous state, worker registrations, replication manifests,
    flight dumps, metrics pushes) and round counter persist to an
    atomic on-disk snapshot, and a restarted server resumes the same
    job on the same port — workers riding their RetryPolicy through
    the outage reconnect without a new rendezvous round
    (docs/recovery.md)."""

    STATE_FILE = "rendezvous_state.pkl"

    def __init__(self, verbose: int = 0,
                 state_dir: Optional[str] = None):
        super().__init__(
            state_path=(os.path.join(state_dir, self.STATE_FILE)
                        if state_dir else None),
        )
        if not self.restored:
            self._round = 0

    def _state_extra(self) -> Dict:
        return {"round": self._round}

    def _apply_state_extra(self, extra: Dict) -> None:
        self._round = int(extra.get("round", 0))

    def last_assignments(self) -> List[SlotInfo]:
        """The slot assignments of the persisted (in-flight) round —
        what a restarted driver uses to resume the same job instead of
        reshuffling ranks (runner/elastic/driver.py)."""
        out: List[SlotInfo] = []
        with self.lock:
            scope = dict(self.store.get(RENDEZVOUS_SCOPE, {}))
        for key, raw in scope.items():
            if not key.startswith("rank_"):
                continue
            try:
                out.append(SlotInfo.from_response_string(
                    raw.decode() if isinstance(raw, bytes) else raw))
            except Exception:
                LOG.warning("unparseable persisted slot record %s", key)
        out.sort(key=lambda s: s.rank)
        return out

    def init(self, host_assignments: List[SlotInfo]) -> int:
        """Publish a new round of slot assignments; returns server port."""
        from ...health.fleet import HEALTH_SCOPE
        from ...utils.metrics import METRICS_PUSH_SCOPE

        if not self._thread.is_alive():
            self.start_server()
        with self.lock:
            scope = self.store.setdefault(RENDEZVOUS_SCOPE, {})
            scope.clear()
            scope["round"] = str(self._round).encode()
            scope["size"] = str(len(host_assignments)).encode()
            for slot in host_assignments:
                scope[f"rank_{slot.rank}"] = (
                    slot.to_response_string().encode()
                )
            # a new round is a new worker incarnation (and possibly a
            # smaller world): previous-round flight dumps would poison
            # straggler attribution with stale enqueue counts, and
            # departed ranks' metric pushes would serve forever on the
            # aggregated scrape. The elastic driver persists dumps to
            # disk before calling init (driver._persist_flight_dumps).
            # health summaries age out the same way: a departed rank's
            # last summary would read as "silent" (= suspected
            # straggler) on every later round's verdict
            for stale in (FLIGHT_SCOPE, FLIGHT_META_SCOPE,
                          METRICS_PUSH_SCOPE, HEALTH_SCOPE):
                self.store.pop(stale, None)
        self._round += 1
        # barrier-persist the new round before workers can see it: a
        # driver crash between publish and flush must not resurrect
        # the previous round's assignments
        self.persist()
        return self.port

    @property
    def round(self) -> int:
        return self._round
