"""HTTP rendezvous + key/value store for bootstrap.

Reference: /root/reference/horovod/runner/http/http_server.py:192,232 —
`RendezvousServer` publishes per-slot SlotInfo under scope `rendezvous`
(workers GET their rank's record); `KVStoreServer` is a generic
PUT/GET/DELETE scope/key byte store used by worker-address registration and
elastic re-rendezvous. Paths: /scope/key. A GET for a missing key returns
404 so clients can poll-wait.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

LOG = logging.getLogger("horovod_tpu.runner")

from ...utils import faults
from ...utils.flight import FLIGHT_SCOPE
from ..util.hosts import SlotInfo

RENDEZVOUS_SCOPE = "rendezvous"

# one batched relay forward (multipod/relay.py): a per-pod relay PUTs
# /relay_batch/<pod_id> with a JSON array of {scope, key, value_b64}
# entries and the root unpacks it into the store under the original
# scopes — the O(pods) replacement for O(hosts) individual
# control-plane PUTs. JSON+base64, NOT pickle: this is an
# unauthenticated network surface and unpickling it would hand remote
# code execution to anyone who can reach the port.
RELAY_BATCH_PATH = "relay_batch"


def decode_relay_batch(body: bytes):
    """Parse + validate one relay batch; returns [(scope, key, value)]
    or raises ValueError. Validation is all-or-nothing so a malformed
    batch never half-applies."""
    import base64

    entries = json.loads(body)
    if not isinstance(entries, list):
        raise ValueError("relay batch is not a list")
    out = []
    for e in entries:
        if not isinstance(e, dict):
            raise ValueError("relay entry is not an object")
        scope, key = e.get("scope"), e.get("key")
        if not isinstance(scope, str) or not isinstance(key, str) \
                or not scope or not key or "/" in scope:
            raise ValueError("bad relay entry scope/key")
        try:
            value = base64.b64decode(e.get("value_b64", ""),
                                     validate=True)
        except Exception:
            raise ValueError("bad relay entry payload")
        out.append((scope, key, value))
    return out

# sharded-root control namespace (runner/http/ring.py): replica-to-
# replica traffic (leases, fenced backup sync, fence broadcasts, rejoin
# dumps) lives under this reserved scope so it can never collide with —
# or be shard-routed like — user data. Ownership checks skip it: every
# replica answers its own `_cp` routes.
CP_SCOPE = "_cp"

#: HTTP status for a scope/key request that reached a replica which
#: does not own it under the current shard map: 421 Misdirected
#: Request, body JSON {"error": "NotOwner", "epoch": E, "owner":
#: {"id", "addr", "port"}}. Clients (http_client.ShardClient) refresh
#: their map from the hint and retry — never treated as a failure.
NOT_OWNER_CODE = 421

# driver-side receipt stamps for worker flight dumps (PUT /flight/<r>):
# scripts/flight_analyze.py reads them as a second clock-alignment
# signal next to each dump's own /clock-probe offset
FLIGHT_META_SCOPE = "flight_meta"


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _split(self) -> Optional[Tuple[str, str]]:
        parts = self.path.strip("/").split("/", 1)
        if len(parts) != 2 or not parts[0] or not parts[1]:
            return None
        return parts[0], parts[1]

    def _shard(self):
        """The owning :class:`ShardReplica`, or None on an unsharded
        server. EVERY shard behavior hangs off this being non-None, so
        a plain KVStoreServer/RendezvousServer executes byte-identical
        pre-shard code paths (--root-replicas 1 contract)."""
        return getattr(self.server, "shard", None)

    def _misrouted(self, scope: str, key: str) -> bool:
        """Ownership gate for one scope/key verb: replies 421 with the
        owner hint and returns True when a sharded replica does not own
        the entry. False (serve it) when unsharded, owned, or an
        internal scope."""
        shard = self._shard()
        if shard is None or scope == CP_SCOPE:
            return False
        rej = shard.not_owner_response(scope, key)
        if rej is None:
            return False
        self._reply(*rej)
        return True

    def _count(self) -> None:
        """Request-count instrumentation: the control-plane fan-in
        scoreboard the relay reduction is measured against
        (scripts/multipod_check.py, scripts/control_plane_scaling.py
        --pods)."""
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.request_count = getattr(  # type: ignore[attr-defined]
                self.server, "request_count", 0) + 1

    def _injected_503(self) -> bool:
        """Server-side fault point: an ``http.server`` error rule turns
        this request into a 503 — the retryable-status path clients
        must survive (their 5xx-retry discipline, http_client.py)."""
        try:
            faults.inject("http.server", method=self.command)
        except faults.InjectedFault:
            self._reply(503, b"injected fault")
            return True
        return False

    def do_GET(self):
        self._count()
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/metrics":
            # cluster-aggregated telemetry scrape (utils/metrics.py):
            # this process's registry plus every worker exposition
            # pushed to /metrics_push/<rank>, the latter rank-labeled —
            # one endpoint answers for the whole world
            # (docs/metrics.md). Single-segment path — can't collide
            # with the scope/key namespace (always two segments).
            from ...utils import metrics

            shard = self._shard()
            if shard is not None:
                # sharded root: pushed summaries hash across replicas,
                # so one replica's local scope is a fraction of the
                # fleet — fold the shard owners' slices back together
                # before rendering (the /health//metrics satellite fix;
                # tests/test_control_plane.py regression-gates it)
                pushed = shard.collect_scope(metrics.METRICS_PUSH_SCOPE)
            else:
                with self.server.lock:  # type: ignore[attr-defined]
                    pushed = dict(
                        self.server.store.get(  # type: ignore[attr-defined]
                            metrics.METRICS_PUSH_SCOPE, {})
                    )
            ctype, body = metrics.exposition(pushed or None)
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/health":
            # fleet-health verdict (horovod_tpu/health): fold every
            # rank's pushed summary (/health/<rank>, pod-labeled by the
            # relay) into one live verdict naming suspected straggler
            # ranks — the runtime analogue of flight.straggler_report.
            # Single-segment path like /metrics; GET /health/<rank>
            # still reads one raw summary through the scope namespace.
            from ...health import fleet

            shard = self._shard()
            if shard is not None:
                # same shard fan-in as /metrics: the fleet verdict must
                # see EVERY rank's summary, not this replica's hash
                # slice of them
                pushed = shard.collect_scope(fleet.HEALTH_SCOPE)
            else:
                with self.server.lock:  # type: ignore[attr-defined]
                    pushed = dict(
                        self.server.store.get(  # type: ignore[attr-defined]
                            fleet.HEALTH_SCOPE, {})
                    )
            self._reply(200, json.dumps(
                fleet.evaluate_store(pushed)).encode())
            return
        if path == "/clock":
            # clock-alignment ping for the flight recorder: workers
            # stamp each dump with (server time - local time) measured
            # through this route so flight_analyze can merge per-rank
            # dumps onto the driver's time axis (utils/flight.py)
            self._reply(200, json.dumps(
                {"time_unix": time.time()}).encode())
            return
        if path == "/shard_map":
            # the epoch-stamped membership record: clients/relays route
            # from it and refresh it on 421. 404 on an unsharded server
            # is the client's "plain single root" signal.
            shard = self._shard()
            if shard is None:
                self._reply(404, b"not sharded")
            else:
                self._reply(200, shard.membership_json())
            return
        if self._injected_503():
            return
        sk = self._split()
        store = self.server.store  # type: ignore[attr-defined]
        if sk is None:
            self._reply(400, b"bad path")
            return
        if sk[0] == CP_SCOPE:
            shard = self._shard()
            if shard is None:
                self._reply(404, b"not sharded")
                return
            code, resp = shard.handle_cp_get(sk[1])
            self._reply(code, resp)
            return
        if self._misrouted(sk[0], sk[1]):
            return
        with self.server.lock:  # type: ignore[attr-defined]
            value = store.get(sk[0], {}).get(sk[1])
        if value is None:
            self._reply(404, b"not found")
        else:
            self._reply(200, value)

    def _store_one(self, scope: str, key: str, body: bytes) -> None:
        """One mutation into the store (lock held by the caller)."""
        store = self.server.store  # type: ignore[attr-defined]
        store.setdefault(scope, {})[key] = body
        if scope == FLIGHT_SCOPE:
            # PUT /flight/<rank>: stamp the driver-side receipt so
            # post-hoc analysis has a second alignment anchor and
            # an arrival order even for dumps whose /clock probe
            # failed
            store.setdefault(FLIGHT_META_SCOPE, {})[key] = (
                json.dumps({
                    "recv_time_unix": time.time(),
                    "bytes": len(body),
                }).encode()
            )

    def do_PUT(self):
        self._count()
        if self._injected_503():
            return
        sk = self._split()
        if sk is None:
            self._reply(400, b"bad path")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if sk[0] == CP_SCOPE:
            # replica-to-replica control traffic: leases, fenced backup
            # sync, fence broadcasts. Epoch discipline (stale → 409)
            # lives in ShardReplica.handle_cp_put.
            shard = self._shard()
            if shard is None:
                self._reply(404, b"not sharded")
                return
            code, resp = shard.handle_cp_put(sk[1], body)
            self._reply(code, resp)
            return
        if sk[0] == RELAY_BATCH_PATH:
            # one pod relay's coalesced forward: unpack into the store
            # under the original scopes, exactly as if each entry had
            # arrived as its own PUT — every reader (aggregated
            # /metrics, recovery GETs, last_assignments) is oblivious
            # to whether a record came direct or relayed
            try:
                entries = decode_relay_batch(body)
            except Exception:
                self._reply(400, b"bad relay batch")
                return
            shard = self._shard()
            if shard is not None:
                # sharded root: apply the entries this replica owns,
                # hand the misrouted rest back with owner hints —
                # all-or-nothing per entry, never per batch, so one
                # takeover mid-batch costs the relay one re-route
                # instead of the whole batch (multipod/relay.py splits
                # by owner up front; rejects only happen on a stale
                # map)
                owned, rejected = shard.partition_owned(entries)
                with self.server.lock:  # type: ignore[attr-defined]
                    for scope, key, value in owned:
                        self._store_one(str(scope), str(key), value)
                self.server.dirty.set()  # type: ignore[attr-defined]
                shard.enqueue_backups(
                    [(s, k, v) for s, k, v in owned])
                shard.drain_backups()
                self._reply(200, json.dumps({
                    "applied": len(owned),
                    "rejected": rejected,
                    "epoch": shard.epoch,
                }).encode())
                return
            with self.server.lock:  # type: ignore[attr-defined]
                for scope, key, value in entries:
                    self._store_one(str(scope), str(key), value)
            self.server.dirty.set()  # type: ignore[attr-defined]
            self._reply(200, b"ok")
            return
        if self._misrouted(sk[0], sk[1]):
            return
        on_mutation = getattr(self.server, "on_mutation", None)
        with self.server.lock:  # type: ignore[attr-defined]
            self._store_one(sk[0], sk[1], body)
            if on_mutation is not None:
                # relay hook (multipod/relay.py): observe the mutation
                # for batched upward forwarding. UNDER the store lock:
                # two same-key PUTs racing outside it could reach the
                # hook in reverse order and forward the stale value
                # while the store holds the fresh one. The hook only
                # touches its own pending dict — no lock cycle.
                on_mutation(sk[0], sk[1], body)
        self.server.dirty.set()  # type: ignore[attr-defined]
        shard = self._shard()
        if shard is not None:
            # write-through to the per-key backup BEFORE acking: once
            # the client sees 200, the entry survives this replica's
            # SIGKILL (the zero-lost-scopes contract,
            # scripts/multipod_check.py root-replica-kill). Outside the
            # store lock — the forward is a network call; last-write-
            # wins through the pending dict keeps racing same-key PUTs
            # ordered.
            shard.enqueue_backups([(sk[0], sk[1], body)])
            shard.drain_backups()
        self._reply(200, b"ok")

    def do_DELETE(self):
        self._count()
        if self._injected_503():
            return
        sk = self._split()
        if sk is None:
            self._reply(400, b"bad path")
            return
        if self._misrouted(sk[0], sk[1]):
            return
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.store.get(sk[0], {}).pop(sk[1], None)  # type: ignore[attr-defined]
        self.server.dirty.set()  # type: ignore[attr-defined]
        shard = self._shard()
        if shard is not None:
            # a tombstone (value None) propagates the delete to the
            # backup so takeover can't resurrect the entry
            shard.enqueue_backups([(sk[0], sk[1], None)])
            shard.drain_backups()
        self._reply(200, b"ok")

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request logging
        pass


class KVStoreServer:
    """Generic scope/key byte store over HTTP (reference :232).

    With ``state_path`` the store is durable: every mutation marks a
    dirty flag and a background flusher writes an atomic (tmp + rename)
    pickle snapshot — store contents, bound port, subclass extras — at
    most every ``flush_interval_s``. A server constructed on an
    existing snapshot reloads the store AND rebinds the same port, so a
    restarted rendezvous/driver answers at the address its workers are
    already retrying against (docs/recovery.md).
    """

    STATE_FORMAT = 1

    def __init__(self, port: int = 0,
                 store: Optional[Dict[str, Dict[str, bytes]]] = None,
                 state_path: Optional[str] = None,
                 flush_interval_s: float = 0.3):
        self._state_path = state_path
        self._flush_interval_s = flush_interval_s
        self._flush_stop = threading.Event()
        self._flush_thread: Optional[threading.Thread] = None
        restored = self._load_state() if state_path else None
        self.restored = restored is not None
        bind_port = port
        if restored is not None and not port:
            bind_port = int(restored.get("port", 0))
        try:
            self._httpd = ThreadingHTTPServer(
                ("0.0.0.0", bind_port), _KVHandler)
        except OSError:
            if not bind_port or bind_port == port:
                raise
            LOG.warning(
                "could not rebind persisted KV-store port %d; binding "
                "a fresh port (workers polling the old address will "
                "time out)", bind_port,
            )
            self._httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                              _KVHandler)
        init_store: Dict[str, Dict[str, bytes]] = (
            store if store is not None else {}
        )
        if restored is not None:
            for scope, kv in restored.get("store", {}).items():
                init_store.setdefault(scope, {}).update(kv)
        self._httpd.store = init_store  # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.dirty = threading.Event()  # type: ignore[attr-defined]
        self._httpd.request_count = 0  # type: ignore[attr-defined]
        self._httpd.on_mutation = None  # type: ignore[attr-defined]
        if restored is not None:
            self._apply_state_extra(restored.get("extra", {}))
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="kvstore",
        )

    def start_server(self) -> int:
        self._thread.start()
        if self._state_path and self._flush_thread is None:
            self._flush_stop.clear()
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True, name="kvstore-flush",
            )
            self._flush_thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def store(self) -> Dict[str, Dict[str, bytes]]:
        return self._httpd.store  # type: ignore[attr-defined]

    @property
    def lock(self):
        return self._httpd.lock  # type: ignore[attr-defined]

    @property
    def request_count(self) -> int:
        """Requests served since start — the fan-in scoreboard
        (multipod relay reduction is measured against this)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return int(self._httpd.request_count)  # type: ignore[attr-defined]

    def set_mutation_hook(self, fn) -> None:
        """Install a (scope, key, value) observer called after every
        direct PUT (relay forwarding, multipod/relay.py). None
        removes."""
        self._httpd.on_mutation = fn  # type: ignore[attr-defined]

    def shutdown_server(self) -> None:
        # BaseServer.shutdown() blocks on the serve_forever loop's ack, so
        # only call it if the loop is actually running.
        if self._thread.is_alive():
            self._httpd.shutdown()
            self._thread.join(timeout=5)
        if self._flush_thread is not None:
            self._flush_stop.set()
            self._flush_thread.join(timeout=5)
            self._flush_thread = None
        if self._state_path:
            self.persist()  # final flush: clean shutdowns lose nothing
        self._httpd.server_close()

    # -------------------------------------------------------- persistence

    def _state_extra(self) -> Dict:
        """Subclass hook: extra durable state (RendezvousServer adds
        its round counter)."""
        return {}

    def _apply_state_extra(self, extra: Dict) -> None:
        pass

    def persist(self) -> None:
        """Write the atomic on-disk snapshot now (flusher + shutdown
        path; callers may also force a barrier, e.g. after publishing a
        rendezvous round)."""
        if not self._state_path:
            return
        with self.lock:
            snap = {scope: dict(kv) for scope, kv in self.store.items()}
        payload = {
            "format": self.STATE_FORMAT,
            "time_unix": time.time(),
            "port": self.port,
            "store": snap,
            "extra": self._state_extra(),
        }
        try:
            d = os.path.dirname(os.path.abspath(self._state_path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{self._state_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._state_path)
        except OSError as e:
            LOG.warning("could not persist KV-store state: %s", e)

    def _load_state(self) -> Optional[Dict]:
        try:
            with open(self._state_path, "rb") as f:
                payload = pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception as e:
            LOG.warning(
                "ignoring unreadable KV-store state %s: %s",
                self._state_path, e,
            )
            return None
        if payload.get("format") != self.STATE_FORMAT:
            LOG.warning(
                "ignoring KV-store state %s with unknown format %r",
                self._state_path, payload.get("format"),
            )
            return None
        return payload

    def _flush_loop(self) -> None:
        dirty = self._httpd.dirty  # type: ignore[attr-defined]
        while not self._flush_stop.is_set():
            if dirty.wait(timeout=0.5):
                dirty.clear()
                self.persist()
                # debounce: batch bursts of mutations into one write
                self._flush_stop.wait(self._flush_interval_s)


class ShardReplica(KVStoreServer):
    """One replica of the sharded root KV tier (docs/control_plane.md).

    N of these, all built from the same ``roots`` list (index = replica
    id, the ``HOROVOD_ROOT_ADDRS`` order), partition every (scope, key)
    by consistent hashing (runner/http/ring.py). Each replica:

    * serves the scope/key verbs for the entries it OWNS and answers
      421 + owner hint for the rest (clients re-route — never an
      error);
    * write-through-replicates each owned mutation to the entry's ring
      backup via ``PUT /_cp/sync/<id>`` before acking, so a SIGKILL of
      the owner loses nothing: the backup IS the next owner on the
      post-fence ring by construction;
    * heartbeats a lease (its membership record) to its peers; when a
      peer's lease lapses past ``lease_ttl_s``, the dead replica's ring
      successor — deterministically, exactly one survivor — fences it
      at epoch+1 and broadcasts the new record;
    * rejects any replica-to-replica write stamped with a pre-fence
      epoch (409) — a paused-then-resumed stale owner cannot corrupt
      the new owner's data;
    * on restart, adopts the newest peer map, rejoins at a fresh epoch,
      and re-pulls its ranges from peers (``GET /_cp/dump``) before the
      supervisor's next spawn-cycle traffic lands on it.

    Generalizes the PR 6 persisted-state machinery: the on-disk
    snapshot (store + membership epoch) still covers same-process
    restart; the ``/_cp/sync`` stream covers the cross-replica case.

    ``clock`` and ``auto_heartbeat=False`` make every timing decision
    injectable — tests/test_control_plane.py drives takeover with a
    fake clock and manual :meth:`heartbeat_once` calls.
    """

    HVD_CP_LEASE_TTL_S = 3.0
    HVD_CP_HEARTBEAT_S = 0.5
    _PEER_TIMEOUT_S = 5.0

    def __init__(self, replica_id: int,
                 roots: "List[Tuple[str, int]]",
                 port: int = 0,
                 state_path: Optional[str] = None,
                 lease_ttl_s: float = HVD_CP_LEASE_TTL_S,
                 heartbeat_interval_s: float = HVD_CP_HEARTBEAT_S,
                 vnodes: Optional[int] = None,
                 clock=time.monotonic,
                 auto_heartbeat: bool = True,
                 flush_interval_s: float = 0.3):
        from .ring import (DEFAULT_VNODES, Membership,
                           membership_for_roots)

        self._restored_extra: Dict = {}
        self.replica_id = int(replica_id)
        bind_port = port or roots[self.replica_id][1]
        super().__init__(port=bind_port, state_path=state_path,
                         flush_interval_s=flush_interval_s)
        self._clock = clock
        self.lease_ttl_s = float(lease_ttl_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._mlock = threading.RLock()
        restored_m = self._restored_extra.get("membership")
        if restored_m:
            self._membership = Membership.from_json(restored_m)
        else:
            self._membership = membership_for_roots(
                roots, vnodes=vnodes or DEFAULT_VNODES)
        now = self._clock()
        self._last_heard: Dict[int, float] = {
            rid: now for rid in self._membership.alive}
        # owner→backup replication queue: (scope, key) → value bytes,
        # None = tombstone. Last-write-wins through the dict keeps
        # racing same-key mutations ordered without holding the store
        # lock across network calls.
        self._backup_pending: Dict[Tuple[str, str],
                                   Optional[bytes]] = {}
        self._backup_plock = threading.Lock()
        self._backup_flock = threading.Lock()  # serializes forwards
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._auto_heartbeat = bool(auto_heartbeat)
        self.takeovers = 0
        self.fenced_writes_rejected = 0
        from ...utils import metrics as _metrics
        lbl = str(self.replica_id)
        self._m_takeovers = _metrics.registry.counter(
            "hvd_cp_takeovers_total",
            "shard takeovers claimed, by surviving replica",
            ("replica",)).labels(lbl)
        self._m_fenced = _metrics.registry.counter(
            "hvd_cp_fenced_writes_total",
            "stale-epoch replica-to-replica writes rejected (409)",
            ("replica",)).labels(lbl)
        self._m_epoch = _metrics.registry.gauge(
            "hvd_cp_epoch",
            "current fencing epoch of the shard membership record",
            ("replica",)).labels(lbl)
        self._m_epoch.set(self._membership.epoch)
        self._httpd.shard = self  # type: ignore[attr-defined]

    # -- membership views ---------------------------------------------------

    @property
    def membership(self):
        with self._mlock:
            return self._membership

    @property
    def epoch(self) -> int:
        return self.membership.epoch

    def membership_json(self) -> bytes:
        return self.membership.to_json()

    def adopt(self, m) -> bool:
        """Merge a peer's record; True if it was strictly newer and we
        switched to it (epochs totally order membership views)."""
        with self._mlock:
            if m.epoch <= self._membership.epoch:
                return False
            self._membership = m
            for rid in m.alive:
                self._last_heard.setdefault(rid, self._clock())
        self._m_epoch.set(m.epoch)
        self._httpd.dirty.set()  # type: ignore[attr-defined]
        return True

    def not_owner_response(
            self, scope: str, key: str,
    ) -> Optional[Tuple[int, bytes]]:
        """None when this replica owns (scope, key) under the current
        map; else the 421 reply carrying the owner hint."""
        m = self.membership
        owner = m.owner_of(scope, key)
        if owner == self.replica_id:
            return None
        addr, port = m.addr_of(owner)
        return NOT_OWNER_CODE, json.dumps({
            "error": "NotOwner",
            "epoch": m.epoch,
            "owner": {"id": owner, "addr": addr, "port": port},
        }).encode()

    def partition_owned(self, entries):
        """Split relay-batch entries into (owned, rejected-with-hints)
        under ONE membership snapshot, so a concurrent takeover can't
        split a batch against two different maps."""
        m = self.membership
        owned, rejected = [], []
        for scope, key, value in entries:
            owner = m.owner_of(scope, key)
            if owner == self.replica_id:
                owned.append((scope, key, value))
            else:
                addr, port = m.addr_of(owner)
                rejected.append({
                    "scope": scope, "key": key,
                    "owner": {"id": owner, "addr": addr, "port": port},
                })
        return owned, rejected

    # -- owner → backup replication -----------------------------------------

    def enqueue_backups(self, entries) -> None:
        """Queue owned mutations for backup write-through; entries are
        (scope, key, value-bytes-or-None-tombstone)."""
        with self._backup_plock:
            for scope, key, value in entries:
                self._backup_pending[(scope, key)] = value

    def drain_backups(self) -> int:
        """Forward everything queued to each entry's ring backup, one
        batched ``/_cp/sync`` per target. Unreachable backups re-merge
        (the heartbeat loop re-drains); a 409 means WE are fenced —
        drop the batch, the new owner already took over. Returns
        entries delivered."""
        import base64
        import urllib.error

        with self._backup_flock:
            with self._backup_plock:
                pending = dict(self._backup_pending)
                self._backup_pending.clear()
            if not pending:
                return 0
            m = self.membership
            by_target: Dict[int, List] = {}
            for (scope, key), value in pending.items():
                rid = m.backup_of(scope, key)
                if rid is None or rid == self.replica_id:
                    continue  # single-replica world: no backup leg
                by_target.setdefault(rid, []).append(
                    (scope, key, value))
            delivered = 0
            for rid, ents in by_target.items():
                addr, port = m.addr_of(rid)
                body = json.dumps({
                    "epoch": m.epoch,
                    "entries": [
                        {"scope": s, "key": k,
                         "value_b64": (None if v is None else
                                       base64.b64encode(v).decode())}
                        for s, k, v in ents
                    ],
                }).encode()
                try:
                    self._peer_put(
                        addr, port,
                        f"{CP_SCOPE}/sync/{self.replica_id}", body)
                    delivered += len(ents)
                except urllib.error.HTTPError as e:
                    if e.code == 409:
                        LOG.warning(
                            "replica %d fenced by backup %d "
                            "(stale epoch %d); dropping sync batch",
                            self.replica_id, rid, m.epoch)
                        continue
                    self._requeue(ents)
                except OSError:
                    self._requeue(ents)
            return delivered

    def _requeue(self, ents) -> None:
        with self._backup_plock:
            for scope, key, value in ents:
                # setdefault: a newer mutation queued meanwhile wins
                self._backup_pending.setdefault((scope, key), value)

    def backup_backlog(self) -> int:
        with self._backup_plock:
            return len(self._backup_pending)

    def _peer_put(self, addr: str, port: int, path: str,
                  body: bytes) -> bytes:
        import urllib.request

        req = urllib.request.Request(
            f"http://{addr}:{port}/{path}", data=body, method="PUT")
        with urllib.request.urlopen(
                req, timeout=self._PEER_TIMEOUT_S) as resp:
            return resp.read()

    def _peer_get(self, addr: str, port: int, path: str) -> bytes:
        import urllib.request

        with urllib.request.urlopen(
                f"http://{addr}:{port}/{path}",
                timeout=self._PEER_TIMEOUT_S) as resp:
            return resp.read()

    # -- replica-to-replica routes (handler delegates) ----------------------

    def handle_cp_put(self, sub: str,
                      body: bytes) -> Tuple[int, bytes]:
        from .ring import Membership

        if sub.startswith("lease/"):
            try:
                sender = int(sub.split("/", 1)[1])
                peer_m = Membership.from_json(body)
            except Exception:
                return 400, b"bad lease"
            cur = self.membership
            if peer_m.epoch < cur.epoch:
                # stale lessor: it missed a fence — tell it so it
                # refreshes instead of believing its old map
                self.fenced_writes_rejected += 1
                self._m_fenced.inc()
                return 409, cur.to_json()
            self.adopt(peer_m)
            with self._mlock:
                self._last_heard[sender] = self._clock()
            return 200, self.membership_json()
        if sub.startswith("sync/"):
            import base64

            try:
                sender = int(sub.split("/", 1)[1])
                payload = json.loads(body)
                sender_epoch = int(payload["epoch"])
                entries = payload["entries"]
            except Exception:
                return 400, b"bad sync"
            cur = self.membership
            if sender_epoch < cur.epoch:
                # THE fencing moment: a deposed owner streaming pre-
                # fence state is rejected wholesale (acceptance
                # criterion; tests/test_control_plane.py)
                self.fenced_writes_rejected += 1
                self._m_fenced.inc()
                return 409, cur.to_json()
            with self.lock:
                for e in entries:
                    scope, key = str(e["scope"]), str(e["key"])
                    v64 = e.get("value_b64")
                    if v64 is None:
                        self.store.get(scope, {}).pop(key, None)
                    else:
                        self.store.setdefault(scope, {})[key] = (
                            base64.b64decode(v64))
                self._last_heard[sender] = self._clock()
            self._httpd.dirty.set()  # type: ignore[attr-defined]
            return 200, b"ok"
        if sub == "fence":
            try:
                peer_m = Membership.from_json(body)
            except Exception:
                return 400, b"bad fence"
            self.adopt(peer_m)
            return 200, self.membership_json()
        return 400, b"bad _cp route"

    def handle_cp_get(self, sub: str) -> Tuple[int, bytes]:
        import base64

        if sub == "dump":
            # rejoin pull: everything this replica holds (primary +
            # backup copies), minus the control scope
            with self.lock:
                snap = {
                    scope: {k: base64.b64encode(v).decode()
                            for k, v in kv.items()}
                    for scope, kv in self.store.items()
                    if scope != CP_SCOPE
                }
            return 200, json.dumps({"scopes": snap}).encode()
        if sub.startswith("scope/"):
            # /metrics + /health shard fan-in: one replica's local
            # slice of a scope, merged by the serving replica
            scope = sub[len("scope/"):]
            with self.lock:
                kv = {k: base64.b64encode(v).decode()
                      for k, v in self.store.get(scope, {}).items()}
            return 200, json.dumps({"keys": kv}).encode()
        return 400, b"bad _cp route"

    # -- scope fan-in (aggregated /metrics, /health) ------------------------

    def collect_scope(self, scope: str) -> Dict[str, bytes]:
        """This scope's entries across ALL live replicas: local slice
        plus each peer's ``GET /_cp/scope/<scope>``. Best-effort on
        peer outages — a dying replica must not take the fleet scrape
        down with it; its slice reappears post-takeover from the
        backup copies."""
        import base64

        with self.lock:
            merged = dict(self.store.get(scope, {}))
        m = self.membership
        for rid in m.alive:
            if rid == self.replica_id:
                continue
            addr, port = m.addr_of(rid)
            try:
                raw = self._peer_get(
                    addr, port, f"{CP_SCOPE}/scope/{scope}")
                for k, v64 in json.loads(raw).get("keys", {}).items():
                    # local copy wins ties (we may hold the backup of a
                    # peer's fresher write, but never the reverse)
                    merged.setdefault(k, base64.b64decode(v64))
            except Exception:
                continue
        return merged

    # -- lease heartbeat + failure detection --------------------------------

    def heartbeat_once(self) -> None:
        """One lease round: push our record to each live peer, adopt
        anything newer that comes back, then fence any peer whose lease
        lapsed — IF we are its ring successor (exactly one survivor
        claims, no dueling epochs)."""
        import urllib.error

        from .ring import Membership

        faults.inject("root.replica", id=self.replica_id)
        m = self.membership
        now = self._clock()
        for rid in m.alive:
            if rid == self.replica_id:
                continue
            addr, port = m.addr_of(rid)
            try:
                raw = self._peer_put(
                    addr, port, f"{CP_SCOPE}/lease/{self.replica_id}",
                    m.to_json())
                self.adopt(Membership.from_json(raw))
                with self._mlock:
                    self._last_heard[rid] = now
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    # we are the stale one: adopt the newer record the
                    # rejecting peer returned
                    try:
                        self.adopt(Membership.from_json(e.read()))
                    except Exception:
                        pass
                with self._mlock:
                    self._last_heard[rid] = now  # alive, just newer
            except OSError:
                pass  # unreachable: lease keeps aging toward the TTL
        # failure detection over the post-gossip record
        m = self.membership
        with self._mlock:
            lapsed = [
                rid for rid in m.alive
                if rid != self.replica_id
                and now - self._last_heard.get(rid, now)
                > self.lease_ttl_s
            ]
        if not lapsed:
            return
        survivors = [r for r in m.alive if r not in lapsed]
        claims = [rid for rid in lapsed
                  if m.ring.successor(rid, survivors)
                  == self.replica_id]
        if claims:
            self.fence_and_takeover(claims)

    def fence_and_takeover(self, dead_ids) -> None:
        """Fence ``dead_ids`` at epoch+1, broadcast the record, and
        re-seed backups for every range this replica just inherited
        (its copies' OLD backup was the dead owner itself — the new
        ring assigns them a live one)."""
        with self._mlock:
            new_m = self._membership.fence(dead_ids)
            self._membership = new_m
        self.takeovers += 1
        self._m_takeovers.inc()
        self._m_epoch.set(new_m.epoch)
        self._httpd.dirty.set()  # type: ignore[attr-defined]
        LOG.warning(
            "replica %d fenced %s at epoch %d (lease lapsed); "
            "taking over their ranges", self.replica_id,
            sorted(int(d) for d in dead_ids), new_m.epoch)
        for rid in new_m.alive:
            if rid == self.replica_id:
                continue
            addr, port = new_m.addr_of(rid)
            try:
                self._peer_put(addr, port, f"{CP_SCOPE}/fence",
                               new_m.to_json())
            except Exception:
                pass  # they'll learn via lease gossip / 409s
        self._reseed_backups()

    def _reseed_backups(self) -> None:
        """Queue every entry this replica now owns for backup sync —
        run after any ring change so the replication invariant (each
        owned entry has one live backup copy) is restored."""
        m = self.membership
        with self.lock:
            owned = [
                (scope, key, value)
                for scope, kv in self.store.items()
                if scope != CP_SCOPE
                for key, value in kv.items()
                if m.owner_of(scope, key) == self.replica_id
            ]
        if owned:
            self.enqueue_backups(owned)
            self.drain_backups()

    def rejoin(self) -> bool:
        """Restarted-replica re-entry: adopt the newest peer map; if we
        were fenced, rejoin at a fresh epoch, broadcast it, and re-pull
        our ranges from peers' dumps. True if a fenced rejoin
        happened."""
        import base64

        from .ring import Membership

        m = self.membership
        for rid, addr, port in m.replicas:
            if rid == self.replica_id:
                continue
            try:
                raw = self._peer_get(addr, port, "shard_map")
                self.adopt(Membership.from_json(raw))
            except Exception:
                continue
        m = self.membership
        if self.replica_id in m.alive:
            return False  # never fenced (fast restart / fresh cluster)
        with self._mlock:
            new_m = self._membership.rejoin(self.replica_id)
            self._membership = new_m
            self._last_heard = {
                rid: self._clock() for rid in new_m.alive}
        self._m_epoch.set(new_m.epoch)
        self._httpd.dirty.set()  # type: ignore[attr-defined]
        for rid in new_m.alive:
            if rid == self.replica_id:
                continue
            addr, port = new_m.addr_of(rid)
            try:
                self._peer_put(addr, port, f"{CP_SCOPE}/fence",
                               new_m.to_json())
                raw = self._peer_get(addr, port, f"{CP_SCOPE}/dump")
                scopes = json.loads(raw).get("scopes", {})
                with self.lock:
                    for scope, kv in scopes.items():
                        dst = self.store.setdefault(scope, {})
                        for k, v64 in kv.items():
                            # don't clobber anything we restored from
                            # our own snapshot — it can only be newer
                            # than what peers backed up for us
                            dst.setdefault(k, base64.b64decode(v64))
            except Exception:
                continue
        self._reseed_backups()
        LOG.warning("replica %d rejoined at epoch %d",
                    self.replica_id, new_m.epoch)
        return True

    # -- lifecycle ----------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            try:
                self.heartbeat_once()
                self.drain_backups()  # retry any re-merged sync
            except faults.InjectedFault:
                raise
            except Exception as e:  # never let the loop die silently
                LOG.warning("replica %d heartbeat error: %s",
                            self.replica_id, e)

    def start_server(self) -> int:
        port = super().start_server()
        if self._auto_heartbeat and self._hb_thread is None:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"shard-hb-{self.replica_id}")
            self._hb_thread.start()
        return port

    def shutdown_server(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=10)
            self._hb_thread = None
        self.drain_backups()  # final drain, same as the relay's
        super().shutdown_server()

    # -- persistence hooks --------------------------------------------------

    def _state_extra(self) -> Dict:
        return {"membership": self.membership.to_json()}

    def _apply_state_extra(self, extra: Dict) -> None:
        # runs inside KVStoreServer.__init__, before our own ctor body:
        # stash for processing once ring/clock attrs exist
        self._restored_extra = dict(extra or {})


def replica_main(argv: Optional[List[str]] = None) -> int:
    """Process entry point for one supervised shard replica
    (``python -m horovod_tpu.runner.http.http_server ...``). The
    launcher (runner/launch.py) spawns N of these and restarts them
    under backoff; a restart lands here with the same ``--replica-id``
    and rejoins the ring. Fault specs arrive via the environment
    (utils/faults import-time arming), so ``root.replica:kill`` rounds
    in scripts/multipod_check.py kill the real process from inside its
    own heartbeat."""
    import argparse

    from .ring import parse_root_addrs

    p = argparse.ArgumentParser(prog="shard-replica")
    p.add_argument("--replica-id", type=int, required=True)
    p.add_argument("--roots", required=True,
                   help="comma-separated addr:port, index = replica id")
    p.add_argument("--state-path", default=None)
    p.add_argument("--lease-ttl", type=float,
                   default=ShardReplica.HVD_CP_LEASE_TTL_S)
    p.add_argument("--heartbeat-interval", type=float,
                   default=ShardReplica.HVD_CP_HEARTBEAT_S)
    p.add_argument("--vnodes", type=int, default=None)
    args = p.parse_args(argv)
    roots = parse_root_addrs(args.roots)
    srv = ShardReplica(
        args.replica_id, roots,
        state_path=args.state_path,
        lease_ttl_s=args.lease_ttl,
        heartbeat_interval_s=args.heartbeat_interval,
        vnodes=args.vnodes)
    srv.start_server()
    srv.rejoin()
    LOG.info("shard replica %d serving on port %d",
             args.replica_id, srv.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.shutdown_server()
    return 0


class RendezvousServer(KVStoreServer):
    """KV store that additionally publishes slot assignments
    (reference http_server.py:192; elastic variant swaps assignments on
    every new rendezvous round).

    With ``state_dir`` the server is failover-capable: its scopes
    (rendezvous state, worker registrations, replication manifests,
    flight dumps, metrics pushes) and round counter persist to an
    atomic on-disk snapshot, and a restarted server resumes the same
    job on the same port — workers riding their RetryPolicy through
    the outage reconnect without a new rendezvous round
    (docs/recovery.md)."""

    STATE_FILE = "rendezvous_state.pkl"

    def __init__(self, verbose: int = 0,
                 state_dir: Optional[str] = None):
        super().__init__(
            state_path=(os.path.join(state_dir, self.STATE_FILE)
                        if state_dir else None),
        )
        if not self.restored:
            self._round = 0

    def _state_extra(self) -> Dict:
        return {"round": self._round}

    def _apply_state_extra(self, extra: Dict) -> None:
        self._round = int(extra.get("round", 0))

    def last_assignments(self) -> List[SlotInfo]:
        """The slot assignments of the persisted (in-flight) round —
        what a restarted driver uses to resume the same job instead of
        reshuffling ranks (runner/elastic/driver.py)."""
        out: List[SlotInfo] = []
        with self.lock:
            scope = dict(self.store.get(RENDEZVOUS_SCOPE, {}))
        for key, raw in scope.items():
            if not key.startswith("rank_"):
                continue
            try:
                out.append(SlotInfo.from_response_string(
                    raw.decode() if isinstance(raw, bytes) else raw))
            except Exception:
                LOG.warning("unparseable persisted slot record %s", key)
        out.sort(key=lambda s: s.rank)
        return out

    def init(self, host_assignments: List[SlotInfo]) -> int:
        """Publish a new round of slot assignments; returns server port."""
        from ...health.fleet import HEALTH_SCOPE
        from ...utils.metrics import METRICS_PUSH_SCOPE

        if not self._thread.is_alive():
            self.start_server()
        with self.lock:
            scope = self.store.setdefault(RENDEZVOUS_SCOPE, {})
            scope.clear()
            scope["round"] = str(self._round).encode()
            scope["size"] = str(len(host_assignments)).encode()
            for slot in host_assignments:
                scope[f"rank_{slot.rank}"] = (
                    slot.to_response_string().encode()
                )
            # a new round is a new worker incarnation (and possibly a
            # smaller world): previous-round flight dumps would poison
            # straggler attribution with stale enqueue counts, and
            # departed ranks' metric pushes would serve forever on the
            # aggregated scrape. The elastic driver persists dumps to
            # disk before calling init (driver._persist_flight_dumps).
            # health summaries age out the same way: a departed rank's
            # last summary would read as "silent" (= suspected
            # straggler) on every later round's verdict
            for stale in (FLIGHT_SCOPE, FLIGHT_META_SCOPE,
                          METRICS_PUSH_SCOPE, HEALTH_SCOPE):
                self.store.pop(stale, None)
        self._round += 1
        # barrier-persist the new round before workers can see it: a
        # driver crash between publish and flush must not resurrect
        # the previous round's assignments
        self.persist()
        return self.port

    @property
    def round(self) -> int:
        return self._round


if __name__ == "__main__":
    import sys

    logging.basicConfig(level=logging.INFO)
    sys.exit(replica_main())
