"""Client for the rendezvous/KV HTTP store (reference runner/http/http_client.py)."""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Optional


def put(addr: str, port: int, scope: str, key: str, value: bytes) -> None:
    req = urllib.request.Request(
        f"http://{addr}:{port}/{scope}/{key}", data=value, method="PUT"
    )
    with urllib.request.urlopen(req, timeout=10):
        pass


def get(addr: str, port: int, scope: str, key: str) -> Optional[bytes]:
    try:
        with urllib.request.urlopen(
            f"http://{addr}:{port}/{scope}/{key}", timeout=10
        ) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def wait_for_key(
    addr: str, port: int, scope: str, key: str, timeout_s: float = 60.0
) -> bytes:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = get(addr, port, scope, key)
        if v is not None:
            return v
        time.sleep(0.2)
    raise TimeoutError(f"key {scope}/{key} not published within {timeout_s}s")


def delete(addr: str, port: int, scope: str, key: str) -> None:
    req = urllib.request.Request(
        f"http://{addr}:{port}/{scope}/{key}", method="DELETE"
    )
    with urllib.request.urlopen(req, timeout=10):
        pass
