"""Client for the rendezvous/KV HTTP store (reference runner/http/http_client.py).

Hardened control plane: every verb runs under the shared
:class:`~horovod_tpu.utils.retry.RetryPolicy` (exponential backoff +
jitter, ``HOROVOD_RETRY_*`` knobs), so a transient ECONNRESET or a 5xx
from a restarting rendezvous server no longer kills a worker mid-
bootstrap. A 404 on GET stays significant (poll-wait contract) and 4xx
responses never retry. ``wait_for_key`` runs on a monotonic deadline —
wall-clock steps cannot break the timeout — and keeps polling through
transient store outages until the deadline. Fault-injection points
``http.put`` / ``http.get`` / ``http.delete`` fire inside the retried
body (utils/faults.py), so injected errors exercise the real retry
path.
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Optional

from ...utils import faults, retry

_TIMEOUT_S = 10.0


def _retryable(exc: BaseException) -> bool:
    """Transport failures and server-side (5xx) errors retry; client
    errors (4xx, notably the 404 poll-wait signal) propagate."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500
    return isinstance(exc, (OSError, EOFError))


def put(addr: str, port: int, scope: str, key: str, value: bytes) -> None:
    def _do() -> None:
        faults.inject("http.put", scope=scope, key=key)
        req = urllib.request.Request(
            f"http://{addr}:{port}/{scope}/{key}", data=value, method="PUT"
        )
        with urllib.request.urlopen(req, timeout=_TIMEOUT_S):
            pass

    retry.default_policy().call(_do, point="http.put", retryable=_retryable)


def get(addr: str, port: int, scope: str, key: str) -> Optional[bytes]:
    def _do() -> Optional[bytes]:
        faults.inject("http.get", scope=scope, key=key)
        try:
            with urllib.request.urlopen(
                f"http://{addr}:{port}/{scope}/{key}", timeout=_TIMEOUT_S
            ) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    return retry.default_policy().call(
        _do, point="http.get", retryable=_retryable
    )


def wait_for_key(
    addr: str, port: int, scope: str, key: str, timeout_s: float = 60.0
) -> bytes:
    deadline = retry.Deadline(timeout_s)
    last_err: Optional[Exception] = None
    while not deadline.expired():
        try:
            v = get(addr, port, scope, key)
        except Exception as e:
            if not _retryable(e):
                raise
            # the store itself is down: the per-call retries gave up,
            # but the poll-wait contract owns the deadline — keep
            # polling until it expires
            last_err = e
            v = None
        if v is not None:
            return v
        time.sleep(0.2)
    raise TimeoutError(
        f"key {scope}/{key} not published within {timeout_s}s"
        + (f" (last error: {last_err})" if last_err else "")
    )


def server_clock(addr: str, port: int,
                 timeout_s: float = 2.0) -> "tuple[float, float]":
    """One retry-free ping to the rendezvous ``GET /clock`` route:
    returns ``(server_time_unix, rtt_s)``. Deliberately outside the
    RetryPolicy — it is a *measurement* (the flight recorder and
    ``scripts/flight_analyze.py`` derive clock offsets from it), and a
    backed-off retry would smear the RTT it exists to bound."""
    import json as _json

    t0 = time.monotonic()
    with urllib.request.urlopen(
            f"http://{addr}:{port}/clock", timeout=timeout_s) as resp:
        body = _json.loads(resp.read())
    return float(body["time_unix"]), time.monotonic() - t0


def delete(addr: str, port: int, scope: str, key: str) -> None:
    def _do() -> None:
        faults.inject("http.delete", scope=scope, key=key)
        req = urllib.request.Request(
            f"http://{addr}:{port}/{scope}/{key}", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=_TIMEOUT_S):
            pass

    retry.default_policy().call(
        _do, point="http.delete", retryable=_retryable
    )
