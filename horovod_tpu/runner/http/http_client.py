"""Client for the rendezvous/KV HTTP store (reference runner/http/http_client.py).

Hardened control plane: every verb runs under the shared
:class:`~horovod_tpu.utils.retry.RetryPolicy` (exponential backoff +
jitter, ``HOROVOD_RETRY_*`` knobs), so a transient ECONNRESET or a 5xx
from a restarting rendezvous server no longer kills a worker mid-
bootstrap. A 404 on GET stays significant (poll-wait contract) and 4xx
responses never retry. ``wait_for_key`` runs on a monotonic deadline —
wall-clock steps cannot break the timeout — and keeps polling through
transient store outages until the deadline. Fault-injection points
``http.put`` / ``http.get`` / ``http.delete`` fire inside the retried
body (utils/faults.py), so injected errors exercise the real retry
path.
"""

from __future__ import annotations

import json as _json_mod
import os
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

from ...utils import faults, retry

_TIMEOUT_S = 10.0

#: env pair publishing the sharded root set (runner/launch.py
#: --root-replicas exports it): comma-separated ``addr:port`` in
#: replica-id order. When set, the module-level verbs transparently
#: shard-route any call addressed at a configured root; when unset,
#: behavior is byte-identical to the single-root client.
ROOT_ADDRS_ENVS = ("HVD_TPU_ROOT_ADDRS", "HOROVOD_ROOT_ADDRS")


def _retryable(exc: BaseException) -> bool:
    """Transport failures and server-side (5xx) errors retry; client
    errors (4xx, notably the 404 poll-wait signal) propagate."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500
    return isinstance(exc, (OSError, EOFError))


def _put_direct(addr: str, port: int, scope: str, key: str,
                value: bytes) -> None:
    def _do() -> None:
        faults.inject("http.put", scope=scope, key=key)
        req = urllib.request.Request(
            f"http://{addr}:{port}/{scope}/{key}", data=value, method="PUT"
        )
        with urllib.request.urlopen(req, timeout=_TIMEOUT_S):
            pass

    retry.default_policy().call(_do, point="http.put", retryable=_retryable)


def _get_direct(addr: str, port: int, scope: str,
                key: str) -> Optional[bytes]:
    def _do() -> Optional[bytes]:
        faults.inject("http.get", scope=scope, key=key)
        try:
            with urllib.request.urlopen(
                f"http://{addr}:{port}/{scope}/{key}", timeout=_TIMEOUT_S
            ) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    return retry.default_policy().call(
        _do, point="http.get", retryable=_retryable
    )


def put(addr: str, port: int, scope: str, key: str, value: bytes) -> None:
    c = _env_client_for(addr, port)
    if c is not None:
        return c.put(scope, key, value)
    return _put_direct(addr, port, scope, key, value)


def get(addr: str, port: int, scope: str, key: str) -> Optional[bytes]:
    c = _env_client_for(addr, port)
    if c is not None:
        return c.get(scope, key)
    return _get_direct(addr, port, scope, key)


def wait_for_key(
    addr: str, port: int, scope: str, key: str, timeout_s: float = 60.0
) -> bytes:
    deadline = retry.Deadline(timeout_s)
    last_err: Optional[Exception] = None
    while not deadline.expired():
        try:
            v = get(addr, port, scope, key)
        except Exception as e:
            if not _retryable(e):
                raise
            # the store itself is down: the per-call retries gave up,
            # but the poll-wait contract owns the deadline — keep
            # polling until it expires
            last_err = e
            v = None
        if v is not None:
            return v
        time.sleep(0.2)
    raise TimeoutError(
        f"key {scope}/{key} not published within {timeout_s}s"
        + (f" (last error: {last_err})" if last_err else "")
    )


def server_clock(addr: str, port: int,
                 timeout_s: float = 2.0) -> "tuple[float, float]":
    """One retry-free ping to the rendezvous ``GET /clock`` route:
    returns ``(server_time_unix, rtt_s)``. Deliberately outside the
    RetryPolicy — it is a *measurement* (the flight recorder and
    ``scripts/flight_analyze.py`` derive clock offsets from it), and a
    backed-off retry would smear the RTT it exists to bound."""
    import json as _json

    t0 = time.monotonic()
    with urllib.request.urlopen(
            f"http://{addr}:{port}/clock", timeout=timeout_s) as resp:
        body = _json.loads(resp.read())
    return float(body["time_unix"]), time.monotonic() - t0


def _delete_direct(addr: str, port: int, scope: str, key: str) -> None:
    def _do() -> None:
        faults.inject("http.delete", scope=scope, key=key)
        req = urllib.request.Request(
            f"http://{addr}:{port}/{scope}/{key}", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=_TIMEOUT_S):
            pass

    retry.default_policy().call(
        _do, point="http.delete", retryable=_retryable
    )


def delete(addr: str, port: int, scope: str, key: str) -> None:
    c = _env_client_for(addr, port)
    if c is not None:
        return c.delete(scope, key)
    return _delete_direct(addr, port, scope, key)


# ---------------------------------------------------------------- sharding

class ShardClient:
    """Multi-root client for the sharded control plane
    (docs/control_plane.md).

    Holds the configured root set plus a cached, epoch-stamped shard
    map fetched from ``GET /shard_map``; routes each (scope, key) verb
    to its ring owner locally (no per-request map traffic). Two
    recovery legs, both invisible to callers:

    * **421 NotOwner** (our map is stale — a takeover moved the key):
      refresh the map from the owner named in the reply and retry the
      verb. Bounded hops — a healthy ring resolves in one.
    * **dead owner** (transport errors exhausted the per-call
      RetryPolicy): poll the surviving roots for a newer map until the
      fencing epoch bumps, then retry at the new owner. Bounded by
      ``takeover_timeout_s`` — covers the lease TTL plus takeover
      broadcast, so workers ride a replica SIGKILL with zero giveups
      (scripts/multipod_check.py).

    Against roots that answer 404 on ``/shard_map`` (a plain
    single-root server) the client degrades to direct calls at
    ``roots[0]`` — today's path, byte-identical.

    ``clock``/``sleep`` are injectable for deterministic tests.
    """

    MAX_REDIRECTS = 8

    def __init__(self, roots: List[Tuple[str, int]],
                 takeover_timeout_s: float = 30.0,
                 clock=time.monotonic, sleep=time.sleep):
        if not roots:
            raise ValueError("ShardClient needs at least one root")
        self.roots = [(str(a), int(p)) for a, p in roots]
        self.takeover_timeout_s = float(takeover_timeout_s)
        self._clock = clock
        self._sleep = sleep
        self._map = None  # Membership | False (unsharded) | None
        self._mlock = threading.Lock()
        self.redirects = 0
        self.map_refreshes = 0

    # -- shard map ----------------------------------------------------------

    def _fetch_map_from(self, addr: str, port: int):
        """One root's view: a Membership, False for an unsharded
        server, or raises on transport failure."""
        from .ring import Membership

        try:
            with urllib.request.urlopen(
                    f"http://{addr}:{port}/shard_map",
                    timeout=_TIMEOUT_S) as resp:
                return Membership.from_json(resp.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def refresh_map(self, prefer: Optional[Tuple[str, int]] = None):
        """Re-fetch the shard map, newest epoch wins. ``prefer`` (the
        owner a 421 hinted at) is asked first — it is the one node
        guaranteed to hold the post-takeover record."""
        self.map_refreshes += 1
        newest = None
        unsharded = False
        targets = ([prefer] if prefer else []) + self.roots
        for addr, port in targets:
            try:
                m = self._fetch_map_from(addr, port)
            except Exception:
                continue
            if m is False:
                unsharded = True
                continue
            if newest is None or m.epoch > newest.epoch:
                newest = m
        with self._mlock:
            if newest is not None:
                if self._map in (None, False) \
                        or newest.epoch > self._map.epoch:
                    self._map = newest
            elif unsharded:
                self._map = False
        if newest is None and not unsharded:
            raise OSError("no root replica answered /shard_map")
        return self._map

    def shard_map(self):
        with self._mlock:
            m = self._map
        if m is None:
            m = self.refresh_map()
        return m

    def owner_addr(self, scope: str, key: str) -> Tuple[str, int]:
        m = self.shard_map()
        if m is False:
            return self.roots[0]
        return m.addr_of(m.owner_of(scope, key))

    # -- verbs --------------------------------------------------------------

    def put(self, scope: str, key: str, value: bytes) -> None:
        self._routed(_put_direct, scope, key, value)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        return self._routed(_get_direct, scope, key)

    def delete(self, scope: str, key: str) -> None:
        self._routed(_delete_direct, scope, key)

    def wait_for_key(self, scope: str, key: str,
                     timeout_s: float = 60.0) -> bytes:
        deadline = retry.Deadline(timeout_s, clock=self._clock)
        last_err: Optional[Exception] = None
        while not deadline.expired():
            try:
                v = self.get(scope, key)
            except Exception as e:
                if not _retryable(e):
                    raise
                last_err = e
                v = None
            if v is not None:
                return v
            self._sleep(0.2)
        raise TimeoutError(
            f"key {scope}/{key} not published within {timeout_s}s"
            + (f" (last error: {last_err})" if last_err else "")
        )

    def _routed(self, fn, scope: str, key: str, *args):
        """Run one direct verb at the key's owner, riding 421
        redirects and dead-owner takeover waits."""
        deadline = retry.Deadline(self.takeover_timeout_s,
                                  clock=self._clock)
        hops = 0
        last_err: Optional[BaseException] = None
        while True:
            addr, port = self.owner_addr(scope, key)
            try:
                return fn(addr, port, scope, key, *args)
            except urllib.error.HTTPError as e:
                if e.code != 421:
                    raise
                # stale map: adopt the hinted owner's view and re-route
                self.redirects += 1
                hops += 1
                if hops > self.MAX_REDIRECTS:
                    raise OSError(
                        f"shard routing for {scope}/{key} did not "
                        f"converge after {hops} redirects"
                    ) from e
                prefer = None
                try:
                    hint = _json_mod.loads(e.read())["owner"]
                    prefer = (str(hint["addr"]), int(hint["port"]))
                except Exception:
                    pass
                self.refresh_map(prefer=prefer)
                last_err = e
            except (OSError, EOFError) as e:
                # owner down and per-call retries exhausted: wait out
                # the takeover (survivors fence after the lease TTL and
                # publish a new-epoch map), bounded by our deadline
                if deadline.expired():
                    raise
                last_err = e
                self._sleep(0.2)
                try:
                    self.refresh_map()
                except OSError:
                    if deadline.expired():
                        raise
            if deadline.expired():
                raise OSError(
                    f"no owner for {scope}/{key} within "
                    f"{self.takeover_timeout_s}s (last: {last_err})")


def parse_root_addrs_env() -> Optional[List[Tuple[str, int]]]:
    """The configured multi-root set, or None when unsharded."""
    from .ring import parse_root_addrs

    spec = next((os.environ[n] for n in ROOT_ADDRS_ENVS
                 if os.environ.get(n)), None)
    if not spec:
        return None
    try:
        roots = parse_root_addrs(spec)
    except ValueError:
        return None
    return roots or None


_ENV_CLIENT: Optional[ShardClient] = None
_ENV_CLIENT_SPEC: Optional[str] = None
_ENV_CLIENT_LOCK = threading.Lock()


def _env_client_for(addr: str, port: int) -> Optional[ShardClient]:
    """The process-wide ShardClient when ``HOROVOD_ROOT_ADDRS`` is set
    AND (addr, port) addresses a configured root — legacy callers that
    target a specific non-root server (relays, test fixtures) keep
    their direct path untouched."""
    global _ENV_CLIENT, _ENV_CLIENT_SPEC
    spec = next((os.environ[n] for n in ROOT_ADDRS_ENVS
                 if os.environ.get(n)), None)
    if not spec:
        return None
    roots = parse_root_addrs_env()
    if not roots:
        return None
    if not any(int(port) == p and str(addr) == a for a, p in roots):
        return None
    with _ENV_CLIENT_LOCK:
        if _ENV_CLIENT is None or _ENV_CLIENT_SPEC != spec:
            _ENV_CLIENT = ShardClient(roots)
            _ENV_CLIENT_SPEC = spec
        return _ENV_CLIENT


def reset_shard_client() -> None:
    """Drop the cached env-built ShardClient (tests re-point roots)."""
    global _ENV_CLIENT, _ENV_CLIENT_SPEC
    with _ENV_CLIENT_LOCK:
        _ENV_CLIENT = None
        _ENV_CLIENT_SPEC = None


def resolve_owner(addr: str, port: int, scope: str,
                  key: str) -> Tuple[str, int]:
    """Where a write for (scope, key) should land: the shard owner
    when (addr, port) names a configured sharded root, else (addr,
    port) unchanged. For callers that manage their own HTTP (e.g.
    elastic/replication.py's raw manifest path)."""
    c = _env_client_for(addr, port)
    if c is None:
        return str(addr), int(port)
    try:
        return c.owner_addr(scope, key)
    except Exception:
        return str(addr), int(port)
