"""Consistent-hash ring + replicated membership for the sharded root.

The root KV tier (docs/control_plane.md) is N :class:`ShardReplica`
servers (runner/http/http_server.py). This module is the pure,
deterministic core they and every client share:

* :class:`HashRing` — virtual-node consistent hashing of routing keys
  onto replica ids. Ownership is computed over the LIVE replica set, so
  removing a dead replica moves exactly its own ranges (to the next
  live replica clockwise — which, by construction, is also the replica
  its owner was streaming backups to) and adding one back moves only
  the ranges it re-claims. That bounded-movement property is what makes
  takeover a local event instead of a cluster-wide reshuffle, and it is
  gated by tests/test_control_plane.py.
* :class:`Membership` — the small replicated record every replica
  stores: the configured replica set, which ids are fenced (dead), and
  the **fencing epoch**. The epoch only ever increases; any
  server-to-server write stamped with a stale epoch is rejected with
  409 by the receiver, which is what makes a paused-then-resurrected
  owner harmless (its writes bounce until it rejoins at the current
  epoch).

Routing key: ``(scope, key)`` hash by default, so one scope's keys
spread over the replicas. Scopes in :data:`PINNED_SCOPES` route by
scope name alone — the rendezvous scope must stay whole (a round is
read as a unit), so it lands on exactly one replica.

Everything here is process-local arithmetic on plain data — no
sockets, no threads — so the ring logic is testable exhaustively and
clients/relays can route locally from a fetched shard map.
"""

from __future__ import annotations

import bisect
import hashlib
import json
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: scopes routed by scope name alone (one replica owns the whole
#: scope). The rendezvous round is published and read as a unit;
#: splitting its keys across replicas would turn one atomic publish
#: into N partial ones.
PINNED_SCOPES = frozenset({"rendezvous"})

#: virtual nodes per replica: enough to spread load evenly at small N
#: (per-replica request share ≈ 1/N, scripts/control_plane_scaling.py
#: --root-replicas) while keeping the ring tiny to serialize.
DEFAULT_VNODES = 64


def _hash64(s: str) -> int:
    """Stable 64-bit hash (sha1 prefix) — identical across processes
    and Python runs, unlike ``hash()`` with PYTHONHASHSEED."""
    return int.from_bytes(
        hashlib.sha1(s.encode("utf-8", "surrogatepass")).digest()[:8],
        "big")


def routing_key(scope: str, key: str) -> str:
    """The string the ring hashes for one (scope, key): pinned scopes
    collapse to the scope name so the whole scope shares one owner."""
    if scope in PINNED_SCOPES:
        return scope
    return f"{scope}/{key}"


class HashRing:
    """Consistent hashing of routing keys onto replica ids.

    The ring is built once over the CONFIGURED replica set; liveness is
    a per-lookup filter (``alive``), so every participant with the same
    configuration + the same live set computes the same owner without
    any coordination.
    """

    def __init__(self, replica_ids: Sequence[int],
                 vnodes: int = DEFAULT_VNODES):
        if not replica_ids:
            raise ValueError("HashRing needs at least one replica id")
        self.replica_ids = sorted(int(i) for i in replica_ids)
        self.vnodes = int(vnodes)
        points: List[Tuple[int, int]] = []
        for rid in self.replica_ids:
            for v in range(self.vnodes):
                points.append((_hash64(f"replica:{rid}#{v}"), rid))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    def _walk(self, h: int) -> Iterable[int]:
        """Replica ids clockwise from hash point ``h`` (wrapping),
        deduplicated in encounter order."""
        n = len(self._points)
        start = bisect.bisect_right(self._hashes, h) % n
        seen = set()
        for off in range(n):
            rid = self._points[(start + off) % n][1]
            if rid not in seen:
                seen.add(rid)
                yield rid

    def owner(self, rkey: str,
              alive: Optional[Iterable[int]] = None) -> int:
        """The live owner of ``rkey`` (a :func:`routing_key` string)."""
        live = set(self.replica_ids if alive is None else alive)
        if not live:
            raise ValueError("no live replicas")
        for rid in self._walk(_hash64(rkey)):
            if rid in live:
                return rid
        raise ValueError("no live replicas on ring")  # pragma: no cover

    def backup(self, rkey: str,
               alive: Optional[Iterable[int]] = None) -> Optional[int]:
        """The NEXT live replica clockwise after the owner — where the
        owner streams its copy of this entry, and (by the same walk)
        exactly who inherits ownership when the owner is fenced.
        None in a single-replica world."""
        live = set(self.replica_ids if alive is None else alive)
        first: Optional[int] = None
        for rid in self._walk(_hash64(rkey)):
            if rid not in live:
                continue
            if first is None:
                first = rid
                continue
            return rid
        return None

    def successor(self, rid: int,
                  alive: Optional[Iterable[int]] = None) -> Optional[int]:
        """The first OTHER live replica clockwise from ``rid``'s primary
        ring point — the deterministic takeover claimant for ``rid``:
        every survivor computes the same successor from the same live
        set, so exactly one of them bumps the epoch and fences (no
        dueling claims)."""
        live = set(self.replica_ids if alive is None else alive)
        live.discard(int(rid))
        if not live:
            return None
        for cand in self._walk(_hash64(f"replica:{int(rid)}#0")):
            if cand in live:
                return cand
        return None  # pragma: no cover

    def owner_of_key(self, scope: str, key: str,
                     alive: Optional[Iterable[int]] = None) -> int:
        return self.owner(routing_key(scope, key), alive)

    def assignment(self, rkeys: Iterable[str],
                   alive: Optional[Iterable[int]] = None,
                   ) -> Dict[str, int]:
        """Bulk owner map — the test harness's bounded-movement probe."""
        live = list(self.replica_ids if alive is None else alive)
        return {rk: self.owner(rk, live) for rk in rkeys}


class Membership:
    """The replicated membership/epoch record.

    Plain data + pure transitions: replicas persist it in their KV
    store (scope ``_cp``), serve it on ``GET /shard_map``, and advance
    it only through :meth:`fence` / :meth:`rejoin`, both of which bump
    the epoch. ``merge`` applies a peer's strictly-newer record —
    epochs totally order membership views, so survivors converge on
    the highest epoch they have seen (the takeover broadcast).
    """

    def __init__(self, replicas: Sequence[Tuple[int, str, int]],
                 epoch: int = 0, dead: Optional[Iterable[int]] = None,
                 vnodes: int = DEFAULT_VNODES):
        # replicas: (id, addr, port), the CONFIGURED root set
        self.replicas = sorted(
            (int(i), str(a), int(p)) for i, a, p in replicas)
        self.epoch = int(epoch)
        self.dead = set(int(d) for d in (dead or ()))
        self.vnodes = int(vnodes)
        self.ring = HashRing([i for i, _, _ in self.replicas],
                             vnodes=self.vnodes)

    # -- views --------------------------------------------------------------

    @property
    def alive(self) -> List[int]:
        return [i for i, _, _ in self.replicas if i not in self.dead]

    def addr_of(self, rid: int) -> Tuple[str, int]:
        for i, a, p in self.replicas:
            if i == int(rid):
                return a, p
        raise KeyError(f"unknown replica id {rid}")

    def owner_of(self, scope: str, key: str) -> int:
        return self.ring.owner(routing_key(scope, key), self.alive)

    def backup_of(self, scope: str, key: str) -> Optional[int]:
        return self.ring.backup(routing_key(scope, key), self.alive)

    # -- transitions (all epoch-bumping) ------------------------------------

    def fence(self, dead_ids: Iterable[int]) -> "Membership":
        """A survivor fencing dead replicas: new record at epoch+1 with
        the ids marked dead. The stale owners' writes are rejected by
        everyone who adopts this record."""
        return Membership(self.replicas, epoch=self.epoch + 1,
                          dead=self.dead | set(int(d) for d in dead_ids),
                          vnodes=self.vnodes)

    def rejoin(self, rid: int) -> "Membership":
        """A restarted replica re-entering the ring at a fresh epoch
        (it must rebuild its ranges from peers before serving —
        ShardReplica.rejoin drives that)."""
        return Membership(self.replicas, epoch=self.epoch + 1,
                          dead=self.dead - {int(rid)},
                          vnodes=self.vnodes)

    def merge(self, other: "Membership") -> "Membership":
        """Adopt the strictly-newer record; ties keep self (records at
        equal epoch are identical by construction — only one claimant
        per fenced id, tests/test_control_plane.py)."""
        return other if other.epoch > self.epoch else self

    # -- wire format --------------------------------------------------------

    def to_json(self) -> bytes:
        return json.dumps({
            "epoch": self.epoch,
            "vnodes": self.vnodes,
            "replicas": [
                {"id": i, "addr": a, "port": p,
                 "alive": i not in self.dead}
                for i, a, p in self.replicas
            ],
            "pinned_scopes": sorted(PINNED_SCOPES),
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Membership":
        obj = json.loads(raw)
        reps = [(r["id"], r["addr"], r["port"])
                for r in obj.get("replicas", [])]
        dead = [r["id"] for r in obj.get("replicas", [])
                if not r.get("alive", True)]
        return cls(reps, epoch=int(obj.get("epoch", 0)), dead=dead,
                   vnodes=int(obj.get("vnodes", DEFAULT_VNODES)))

    def __repr__(self) -> str:  # diagnostics only
        return (f"Membership(epoch={self.epoch}, "
                f"alive={self.alive}, dead={sorted(self.dead)})")


def parse_root_addrs(spec: str) -> List[Tuple[str, int]]:
    """``HOROVOD_ROOT_ADDRS`` grammar: comma-separated ``addr:port``
    in replica-id order (index in the list IS the replica id — every
    participant must agree on it, so the launcher exports one string
    to the whole fleet)."""
    out: List[Tuple[str, int]] = []
    for chunk in (spec or "").replace(";", ",").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        addr, _, port = chunk.rpartition(":")
        if not addr or not port:
            raise ValueError(
                f"bad HOROVOD_ROOT_ADDRS entry {chunk!r} "
                f"(want addr:port)")
        out.append((addr, int(port)))
    return out


def membership_for_roots(roots: Sequence[Tuple[str, int]],
                         vnodes: int = DEFAULT_VNODES) -> Membership:
    """Fresh epoch-0 membership over a configured root set."""
    return Membership(
        [(i, a, p) for i, (a, p) in enumerate(roots)], vnodes=vnodes)
