"""TCP control-plane services: length-prefixed pickled messages with HMAC.

Reference: /root/reference/horovod/runner/common/util/network.py:102,175
(`BasicService`/`BasicClient`) — the transport under the driver/task
services, worker notification, and compute-service registry. Wire format
here: 4-byte big-endian length, 32-byte HMAC-SHA256 over the payload,
pickled payload. Any message failing HMAC verification is dropped and the
connection closed (launcher control plane only ever runs inside one job's
trust domain, keyed by the per-job secret).
"""

from __future__ import annotations

import hashlib
import hmac
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, List, Optional, Tuple

_LEN = struct.Struct(">I")
_DIGEST_BYTES = 32


class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name: str, source_address: str):
        self.service_name = service_name
        self.source_address = source_address


class AckResponse:
    """Generic empty OK."""


def _sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, hashlib.sha256).digest()


def _read_exact(rfile, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise EOFError("connection closed mid-message")
        buf += chunk
    return buf


class Wire:
    """Serialize/deserialize one authenticated message on a stream."""

    def __init__(self, key: bytes):
        self._key = key

    def write(self, obj: Any, wfile) -> None:
        payload = pickle.dumps(obj)
        wfile.write(_LEN.pack(len(payload)))
        wfile.write(_sign(self._key, payload))
        wfile.write(payload)
        wfile.flush()

    def read(self, rfile) -> Any:
        (length,) = _LEN.unpack(_read_exact(rfile, _LEN.size))
        digest = _read_exact(rfile, _DIGEST_BYTES)
        payload = _read_exact(rfile, length)
        if not hmac.compare_digest(digest, _sign(self._key, payload)):
            raise PermissionError("message failed HMAC verification")
        return pickle.loads(payload)


class BasicService:
    """Threaded TCP request/response server.

    Subclasses override `_handle(req, client_address)` and return the
    response object (reference network.py:102).
    """

    def __init__(self, name: str, key: bytes,
                 nics: Optional[List[str]] = None, port: int = 0):
        self._name = name
        self._wire = Wire(key)
        service = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    while True:
                        try:
                            req = service._wire.read(self.rfile)
                        except EOFError:
                            return
                        resp = service._handle(req, self.client_address)
                        service._wire.write(resp, self.wfile)
                except (PermissionError, ConnectionError, BrokenPipeError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        # port 0 (the default) = ephemeral, the launcher-internal case;
        # a fixed port serves standalone registries workers are told
        # about by address (e.g. the serving-replica registry)
        self._server = _Server(("0.0.0.0", port), _Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"{name}-server",
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> List[Tuple[str, int]]:
        """All routable (ip, port) pairs for this service."""
        addrs = [("127.0.0.1", self._port)]
        try:
            hostname_ip = socket.gethostbyname(socket.gethostname())
            if hostname_ip != "127.0.0.1":
                addrs.append((hostname_ip, self._port))
        except OSError:
            pass
        return addrs

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self._name, client_address[0])
        raise NotImplementedError(f"unhandled request {type(req).__name__}")

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class BasicClient:
    """Blocking request/response client (reference network.py:175)."""

    def __init__(
        self,
        service_name: str,
        addresses: List[Tuple[str, int]],
        key: bytes,
        attempts: int = 3,
        timeout_s: float = 10.0,
    ):
        self._name = service_name
        self._wire = Wire(key)
        self._timeout = timeout_s
        self._address = self._probe(addresses, attempts)

    def _probe(self, addresses, attempts) -> Tuple[str, int]:
        last_err: Optional[Exception] = None
        for _ in range(attempts):
            for addr in addresses:
                try:
                    resp = self._request_at(addr, PingRequest())
                    if (
                        isinstance(resp, PingResponse)
                        and resp.service_name == self._name
                    ):
                        return addr
                except (OSError, EOFError, PermissionError) as e:
                    last_err = e
        raise ConnectionError(
            f"unable to reach {self._name} at any of {addresses}: {last_err}"
        )

    def _request_at(self, addr: Tuple[str, int], req: Any) -> Any:
        with socket.create_connection(addr, timeout=self._timeout) as sock:
            rfile = sock.makefile("rb")
            wfile = sock.makefile("wb")
            self._wire.write(req, wfile)
            return self._wire.read(rfile)

    def request(self, req: Any) -> Any:
        return self._request_at(self._address, req)

    @property
    def address(self) -> Tuple[str, int]:
        return self._address


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def get_local_host_addresses() -> List[str]:
    """Local addresses, loopback first; the last entry is the most
    routable one (real NIC IP when resolvable, else loopback)."""
    addrs = ["127.0.0.1"]
    candidates = []
    try:
        # Debian-style hosts resolve the hostname to 127.0.1.1 — any
        # 127.x.x.x is loopback and useless to remote workers
        candidates.append(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    try:
        # UDP connect sends no packets but selects the outbound NIC
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            candidates.append(s.getsockname()[0])
    except OSError:
        pass
    for ip in candidates:
        if not ip.startswith("127.") and ip not in addrs:
            addrs.append(ip)
    return addrs


def routable_host_address() -> str:
    """The address remote workers should use to reach this machine."""
    return get_local_host_addresses()[-1]


def is_local_host(name: str) -> bool:
    """True if `name` refers to this machine (hostname, localhost, or any
    local address)."""
    if name in ("localhost", socket.gethostname()):
        return True
    if name in get_local_host_addresses():
        return True
    try:
        return socket.gethostbyname(name) in get_local_host_addresses() + [
            "127.0.1.1"
        ]
    except OSError:
        return False
