"""CLI/YAML config → environment-variable knobs.

Reference: /root/reference/horovod/runner/common/util/config_parser.py +
launch.py:286-580 — every launcher flag maps onto a `HOROVOD_*` env var
that the in-process runtime (core/knobs.py) reads. YAML config files set
the same keys; explicit CLI flags win over the file.
"""

from __future__ import annotations

from typing import Dict, Optional

# flag name (argparse dest) → env var set for workers
ARG_TO_ENV = {
    "fusion_threshold_mb": "HOROVOD_FUSION_THRESHOLD",
    "cycle_time_ms": "HOROVOD_CYCLE_TIME",
    "cache_capacity": "HOROVOD_CACHE_CAPACITY",
    "timeline_filename": "HOROVOD_TIMELINE",
    "timeline_mark_cycles": "HOROVOD_TIMELINE_MARK_CYCLES",
    "autotune": "HOROVOD_AUTOTUNE",
    "autotune_bayes": "HOROVOD_AUTOTUNE_BAYES",
    "autotune_log": "HOROVOD_AUTOTUNE_LOG",
    # closed-loop OnlineTuner warm start + scoring (docs/autotune.md).
    # --autotune-mfu / --autotune-wire store literal "0"/"1"
    # (env_from_args skips boolean False, so a store_false flag could
    # never reach the env — the --fsdp precedent)
    "autotune_cache": "HOROVOD_AUTOTUNE_CACHE",
    "autotune_mfu": "HOROVOD_AUTOTUNE_MFU",
    "autotune_wire": "HOROVOD_AUTOTUNE_WIRE",
    "compression_wire_dtype": "HOROVOD_COMPRESSION_WIRE_DTYPE",
    "compression": "HOROVOD_COMPRESSION",
    "compression_block": "HOROVOD_COMPRESSION_BLOCK",
    "overlap_schedule": "HOROVOD_OVERLAP_SCHEDULE",
    # --fsdp stores the literal "0"/"1" (env_from_args skips boolean
    # False, so a store_false flag could never reach the env)
    "fsdp": "HOROVOD_FSDP",
    "fsdp_prefetch": "HOROVOD_FSDP_PREFETCH",
    "fsdp_regather": "HOROVOD_FSDP_REGATHER",
    "fsdp_offload": "HOROVOD_FSDP_OFFLOAD",
    "fsdp_offload_duty": "HOROVOD_FSDP_OFFLOAD_DUTY",
    "fused_collectives": "HOROVOD_FUSED_COLLECTIVES",
    "hierarchical_allreduce": "HOROVOD_HIERARCHICAL_ALLREDUCE",
    "hierarchical_allgather": "HOROVOD_HIERARCHICAL_ALLGATHER",
    "hierarchical_local_size": "HOROVOD_HIERARCHICAL_LOCAL_SIZE",
    "elastic_timeout": "HOROVOD_ELASTIC_TIMEOUT",
    "reset_limit": "HOROVOD_RESET_LIMIT",
    "stall_check_disable": "HOROVOD_STALL_CHECK_DISABLE",
    "stall_warning_time_seconds": "HOROVOD_STALL_CHECK_TIME_SECONDS",
    "stall_shutdown_time_seconds": "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
    "stall_abort_s": "HOROVOD_STALL_ABORT_S",
    "fault_spec": "HOROVOD_TPU_FAULT_SPEC",
    "retry_max_attempts": "HOROVOD_RETRY_MAX_ATTEMPTS",
    "retry_base_delay": "HOROVOD_RETRY_BASE_DELAY",
    "retry_max_delay": "HOROVOD_RETRY_MAX_DELAY",
    "vanish_grace": "HOROVOD_ELASTIC_VANISH_GRACE",
    "spawn_join": "HOROVOD_ELASTIC_SPAWN_JOIN",
    # --no-preemption stores the literal "0" (env_from_args skips
    # boolean False, so a store_false flag could never reach the env)
    "preemption": "HOROVOD_PREEMPTION",
    "emergency_checkpoint": "HOROVOD_EMERGENCY_CHECKPOINT",
    # --replication stores the literal "1" (same reason as preemption)
    "replication": "HOROVOD_REPLICATION",
    "replication_partners": "HOROVOD_REPLICATION_PARTNERS",
    # --no-flight-recorder stores "0" for the same reason
    "flight_recorder": "HOROVOD_FLIGHT_RECORDER",
    "flight_dir": "HOROVOD_FLIGHT_DIR",
    # sharded root control plane (docs/control_plane.md): the replica
    # count + timing knobs ride to workers so in-worker clients and
    # knobs.from_env agree with the launcher-spawned tier.
    # HOROVOD_ROOT_ADDRS itself is NOT here — the launcher computes it
    # after reserving ports and exports it directly.
    "root_replicas": "HOROVOD_ROOT_REPLICAS",
    "root_lease_ttl": "HOROVOD_ROOT_LEASE_TTL",
    "root_heartbeat": "HOROVOD_ROOT_HEARTBEAT",
    "prof_every": "HOROVOD_PROF_EVERY",
    "prof_dir": "HOROVOD_PROF_DIR",
    "prof_duty_cycle": "HOROVOD_PROF_DUTY_CYCLE",
    "log_level": "HOROVOD_LOG_LEVEL",
    "mesh": "HOROVOD_MESH",
}


def _to_env_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    return str(v)


def env_from_args(args, env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Collect worker env vars from parsed CLI args (None values skipped)."""
    env = dict(env or {})
    for dest, var in ARG_TO_ENV.items():
        v = getattr(args, dest, None)
        if v is None or v is False or v == "":
            continue
        if dest == "fusion_threshold_mb":
            v = int(v) * 1024 * 1024
        env[var] = _to_env_value(v)
    return env


def load_config_file(path: str) -> Dict[str, object]:
    """YAML (or key: value) config file → {argparse dest: value}."""
    try:
        import yaml  # type: ignore

        with open(path) as f:
            data = yaml.safe_load(f) or {}
    except ImportError:
        data = {}
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line or ":" not in line:
                    continue
                k, _, v = line.partition(":")
                data[k.strip()] = _parse_scalar(v.strip())
    flat: Dict[str, object] = {}
    _flatten(data, flat)
    return {k.replace("-", "_"): v for k, v in flat.items()}


def _flatten(d, out):
    for k, v in d.items():
        if isinstance(v, dict):
            _flatten(v, out)
        elif k in out and out[k] != v:
            raise ValueError(
                f"config key {k!r} appears in multiple sections with "
                f"different values ({out[k]!r} vs {v!r})"
            )
        else:
            out[k] = v


def _parse_scalar(v: str):
    low = v.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    return v


def apply_config_file(args, path: str, explicit_dests) -> None:
    """Set args fields from the config file unless given explicitly on the
    command line (reference config_parser.py behavior)."""
    for dest, value in load_config_file(path).items():
        if dest in explicit_dests:
            continue
        if hasattr(args, dest):
            setattr(args, dest, value)
