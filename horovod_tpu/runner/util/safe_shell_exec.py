"""Subprocess execution with whole-process-tree termination.

Reference: /root/reference/horovod/runner/common/util/safe_shell_exec.py —
launcher-spawned workers get their own process group; on failure/interrupt
the entire tree is terminated (GRACEFUL_TERMINATION then SIGKILL) so no
orphan trainers hold TPU chips.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0


def terminate_process_tree(pid: int, timeout_s: float = GRACEFUL_TERMINATION_TIME_S) -> None:
    """SIGTERM the process group; escalate to SIGKILL after timeout."""
    try:
        pgid = os.getpgid(pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            os.killpg(pgid, 0)
        except ProcessLookupError:
            return
        time.sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass


def _pipe(stream, sink, prefix: str) -> threading.Thread:
    def pump():
        try:
            for line in iter(stream.readline, b""):
                text = line.decode(errors="replace")
                if prefix:
                    text = f"[{prefix}]{text}" if text.strip() else text
                sink.write(text)
                sink.flush()
        except ValueError:
            pass

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def execute(
    command,
    env: Optional[Dict[str, str]] = None,
    stdout=None,
    stderr=None,
    prefix: str = "",
    events=None,
    shell: bool = False,
) -> int:
    """Run command in its own process group, streaming output.

    `events` is an optional list of threading.Event; if any fires, the
    process tree is terminated (the launcher's any-failure-kills-all
    behavior, reference gloo_run.py:137-199).
    """
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    proc = subprocess.Popen(
        command,
        env=env,
        shell=shell,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        start_new_session=True,
    )

    pumps = [
        _pipe(proc.stdout, stdout, prefix),
        _pipe(proc.stderr, stderr, prefix),
    ]

    stop_watch = threading.Event()
    if events:
        def watch():
            while not stop_watch.is_set():
                for ev in events:
                    if ev.is_set():
                        terminate_process_tree(proc.pid)
                        return
                time.sleep(0.1)

        threading.Thread(target=watch, daemon=True).start()

    try:
        ret = proc.wait()
    except KeyboardInterrupt:
        terminate_process_tree(proc.pid)
        raise
    finally:
        stop_watch.set()
    for t in pumps:
        t.join(timeout=2)
    return ret
