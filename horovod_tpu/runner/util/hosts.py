"""Host parsing and slot assignment.

Reference: /root/reference/horovod/runner/common/util/hosts.py —
`SlotInfo(rank, local_rank, cross_rank, ...)` (:34), `parse_hosts` (:87),
`get_host_assignments` (:100). The rank model carries over verbatim
(SURVEY.md §2.6): rank = global slot index, local_rank = index within the
host, cross_rank = index of the host among hosts that have this local_rank
(for a homogeneous job: the host index).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(spec: str) -> "HostInfo":
        host, _, n = spec.strip().partition(":")
        if not host:
            raise ValueError(f"bad host spec {spec!r}")
        return HostInfo(host, int(n) if n else 1)


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_response_string(self) -> str:
        return ":".join(
            str(v)
            for v in (
                self.hostname, self.rank, self.local_rank, self.cross_rank,
                self.size, self.local_size, self.cross_size,
            )
        )

    @staticmethod
    def from_response_string(s: str) -> "SlotInfo":
        host, rank, lrank, crank, size, lsize, csize = s.split(":")
        return SlotInfo(
            host, int(rank), int(lrank), int(crank),
            int(size), int(lsize), int(csize),
        )


INVALID_SLOT_INFO = SlotInfo("", -1, -1, -1, -1, -1, -1)


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """"h1:4,h2:4" → [HostInfo]. (reference hosts.py:87)"""
    return [
        HostInfo.from_string(spec)
        for spec in hosts_string.split(",")
        if spec.strip()
    ]


def parse_host_files(filename: str) -> str:
    """Hostfile with `host slots=N` or `host:N` lines → "h:N,h:N"."""
    specs = []
    with open(filename) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            if ":" in host:
                host, _, s = host.partition(":")
                slots = int(s)
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p[len("slots="):])
            specs.append(f"{host}:{slots}")
    return ",".join(specs)


def get_host_assignments(
    hosts: List[HostInfo],
    min_np: int,
    max_np: Optional[int] = None,
    rank_assignments: Optional[Dict[str, List[int]]] = None,
) -> List[SlotInfo]:
    """Assign global/local/cross ranks over hosts in order.

    `rank_assignments` maps hostname → previously-held global ranks, used by
    the elastic driver to keep surviving workers' ranks stable across a
    world resize (reference hosts.py:100, elastic/driver.py:240).
    """
    np_total = sum(h.slots for h in hosts)
    if max_np is not None:
        np_total = min(np_total, max_np)
    if np_total < min_np:
        raise ValueError(
            f"{np_total} slots available on {len(hosts)} hosts, "
            f"but at least {min_np} required"
        )

    # slots in host order
    slot_hosts: List[str] = []
    local_ranks: List[int] = []
    local_sizes: Dict[str, int] = {}
    for h in hosts:
        take = min(h.slots, np_total - len(slot_hosts))
        for i in range(take):
            slot_hosts.append(h.hostname)
            local_ranks.append(i)
        local_sizes[h.hostname] = take
        if len(slot_hosts) >= np_total:
            break

    # global ranks: honor prior assignments for surviving hosts, fill the
    # rest with unused ranks in order
    n = len(slot_hosts)
    ranks: List[Optional[int]] = [None] * n
    used = set()
    if rank_assignments:
        per_host_prior = {h: list(r) for h, r in rank_assignments.items()}
        for i, host in enumerate(slot_hosts):
            prior = per_host_prior.get(host)
            if prior:
                r = prior.pop(0)
                if 0 <= r < n and r not in used:
                    ranks[i] = r
                    used.add(r)
    free = iter(r for r in range(n) if r not in used)
    for i in range(n):
        if ranks[i] is None:
            ranks[i] = next(free)

    # cross ranks: among slots sharing a local_rank, order by host order
    cross_sizes: Dict[int, int] = {}
    for lr in local_ranks:
        cross_sizes[lr] = cross_sizes.get(lr, 0) + 1
    cross_seen: Dict[int, int] = {}
    assignments = []
    for i in range(n):
        lr = local_ranks[i]
        cr = cross_seen.get(lr, 0)
        cross_seen[lr] = cr + 1
        assignments.append(
            SlotInfo(
                hostname=slot_hosts[i],
                rank=ranks[i],
                local_rank=lr,
                cross_rank=cr,
                size=n,
                local_size=local_sizes[slot_hosts[i]],
                cross_size=cross_sizes[lr],
            )
        )
    assignments.sort(key=lambda s: s.rank)
    return assignments


def host_hash(salt: str = "") -> str:
    """Stable identifier for 'same physical host' grouping
    (reference host_hash.py)."""
    import hashlib
    import socket

    return hashlib.md5(
        (socket.gethostname() + salt).encode()
    ).hexdigest()[:16]
