"""Shared-secret generation for launcher control-plane authentication.

Reference: /root/reference/horovod/runner/common/util/secret.py:26-34 —
every network service message is HMAC-signed with a per-job secret the
launcher generates and passes to workers via env.
"""

import base64
import os

ENV_SECRET = "HVD_TPU_SECRET_KEY"


def make_secret_key() -> bytes:
    return base64.b64encode(os.urandom(32))


def secret_from_env() -> bytes:
    v = os.environ.get(ENV_SECRET, "")
    if not v:
        raise RuntimeError(f"{ENV_SECRET} not set; launcher must provide it")
    return v.encode() if isinstance(v, str) else v
