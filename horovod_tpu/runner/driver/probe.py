"""Task-to-task interface routability probing.

Reference: /root/reference/horovod/runner/driver/driver_service.py:260
(`get_common_interfaces`) + task_service ring probe: on multi-NIC hosts
the address a worker *advertises* may not be the one its peers can
*reach* (management NICs, container bridges, IB-only fabrics). The
reference has every task probe the interfaces of the next task in a
ring and the driver intersect the routable sets.

TPU-native shape: the same ring intersection, over this launcher's
authenticated BasicService transport. One TaskProbeService per host
(bound 0.0.0.0, so one port serves every NIC); the driver asks each
task to TCP-probe its ring successor's per-interface addresses and
keeps the interfaces every hop could reach. The result names the NICs
whose addresses the rendezvous/coordinator endpoints should bind —
on TPU pods the data plane rides ICI/DCN picked by XLA, so the probed
NICs govern the *control* plane (rendezvous, elastic notifications,
compute service), which is exactly where a wrong-NIC pick hangs jobs.
"""

from __future__ import annotations

import socket
from typing import Dict, List, Optional, Tuple

from ..util.network import AckResponse, BasicClient, BasicService


def interface_addresses(
    nics: Optional[List[str]] = None,
) -> Dict[str, str]:
    """IPv4 address of each up interface (iface -> ip), loopback
    excluded unless it is all there is. `nics` filters to a user-given
    allowlist (reference --network-interface semantics)."""
    addrs: Dict[str, str] = {}
    try:
        import psutil

        for iface, snics in psutil.net_if_addrs().items():
            if nics and iface not in nics:
                continue
            for sn in snics:
                if sn.family == socket.AF_INET:
                    addrs[iface] = sn.address
                    break
    except ImportError:
        pass
    if not addrs:
        # psutil-less fallback: the outbound-route trick names one NIC
        from ..util.network import routable_host_address

        addrs["default"] = routable_host_address()
    if nics:
        # explicit allowlist wins verbatim — a user naming lo means lo
        return addrs
    non_loop = {
        i: a for i, a in addrs.items() if not a.startswith("127.")
    }
    return non_loop or addrs


class InterfacesRequest:
    pass


class InterfacesResponse:
    def __init__(self, iface_addrs: Dict[str, Tuple[str, int]]):
        self.iface_addrs = iface_addrs


class ProbePeerRequest:
    """Ask a task to TCP-probe a peer's per-interface addresses."""

    def __init__(self, iface_addrs: Dict[str, Tuple[str, int]],
                 timeout_s: float = 2.0):
        self.iface_addrs = iface_addrs
        self.timeout_s = timeout_s


class ProbePeerResponse:
    def __init__(self, reachable: List[str]):
        self.reachable = reachable


class RegisterTaskRequest:
    def __init__(self, index: int, addresses: List[Tuple[str, int]]):
        self.index = index
        self.addresses = addresses


class ShutdownTaskRequest:
    pass


class TaskProbeService(BasicService):
    """Per-host probe endpoint (reference task_service.py). Advertises
    its interface map and probes peers on request."""

    def __init__(self, index: int, key: bytes,
                 nics: Optional[List[str]] = None,
                 advertised: Optional[Dict[str, str]] = None):
        super().__init__(f"task-probe-{index}", key)
        self.index = index
        # advertised overrides discovery — tests inject unreachable
        # addresses to model a dark NIC
        self._ifaces = dict(advertised or interface_addresses(nics))
        import threading

        self.stop_event = threading.Event()

    def interface_map(self) -> Dict[str, Tuple[str, int]]:
        # advertised values are plain ips (served on this service's
        # port) or explicit (ip, port) pairs — the latter lets tests
        # model a dark NIC with a dead endpoint
        return {
            i: ((a, self.port) if isinstance(a, str) else tuple(a))
            for i, a in self._ifaces.items()
        }

    def addresses(self) -> List[Tuple[str, int]]:
        """Every interface address (plus loopback) — the driver registers
        the source address it actually saw first, but keeps the rest as
        fallbacks for the ring clients."""
        addrs = [
            (a, self.port) for a in self._ifaces.values()
            if isinstance(a, str)
        ]
        addrs.append(("127.0.0.1", self.port))
        return addrs

    def _handle(self, req, client_address):
        if isinstance(req, InterfacesRequest):
            return InterfacesResponse(self.interface_map())
        if isinstance(req, ProbePeerRequest):
            reachable = []
            for iface, (ip, port) in sorted(req.iface_addrs.items()):
                try:
                    with socket.create_connection(
                        (ip, port), timeout=req.timeout_s
                    ):
                        reachable.append(iface)
                except OSError:
                    continue
            return ProbePeerResponse(reachable)
        if isinstance(req, ShutdownTaskRequest):
            self.stop_event.set()
            return AckResponse()
        return super()._handle(req, client_address)


class DriverProbeService(BasicService):
    """Launcher-side registry the probe tasks report in to
    (reference HorovodRunDriverService)."""

    def __init__(self, num_tasks: int, key: bytes):
        super().__init__("driver-probe", key)
        import threading

        self._num = num_tasks
        self._cv = threading.Condition()
        self.task_addresses: Dict[int, List[Tuple[str, int]]] = {}

    def addresses(self) -> List[Tuple[str, int]]:
        """Every candidate address a remote probe task might reach the
        driver on: all NIC addresses, the default-route pick, loopback.
        The base-class hostname lookup alone is a trap — Debian-style
        hosts resolve to 127.0.1.1 and multi-NIC launchers to an
        arbitrary NIC (the very problem this module exists to fix)."""
        from ..util.network import get_local_host_addresses

        ips = list(interface_addresses().values())
        for a in reversed(get_local_host_addresses()):
            if a not in ips:
                ips.append(a)
        return [(a, self.port) for a in ips]

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._cv:
                # record the observed source address first: it is the one
                # address the DRIVER verifiably can reach the task on
                seen = (client_address[0], req.addresses[0][1])
                ordered = [seen] + [
                    a for a in req.addresses if tuple(a) != seen
                ]
                self.task_addresses[req.index] = ordered
                self._cv.notify_all()
            return AckResponse()
        return super()._handle(req, client_address)

    def wait_for_registration(self, timeout_s: float = 60.0) -> None:
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self.task_addresses) >= self._num, timeout_s
            )
        if not ok:
            raise TimeoutError(
                f"only {len(self.task_addresses)}/{self._num} probe tasks "
                f"registered within {timeout_s}s"
            )


def find_common_nics(
    task_addresses: List[List[Tuple[str, int]]],
    key: bytes,
    timeout_s: float = 10.0,
) -> List[str]:
    """Ring-probe every task and intersect the routable interface sets
    (reference _run_probe, driver_service.py:122)."""
    clients = [
        BasicClient(f"task-probe-{i}", [tuple(a) for a in addrs], key,
                    timeout_s=timeout_s)
        for i, addrs in enumerate(task_addresses)
    ]
    iface_maps = [
        c.request(InterfacesRequest()).iface_addrs for c in clients
    ]
    common: Optional[set] = None
    n = len(clients)
    for i, c in enumerate(clients):
        peer = iface_maps[(i + 1) % n]
        resp = c.request(ProbePeerRequest(peer))
        s = set(resp.reachable)
        common = s if common is None else common & s
    if not common:
        raise RuntimeError(
            "no common routable interface across all hosts "
            f"(per-task interface maps: {iface_maps}); pass "
            "--network-interface to override "
            "(reference driver_service.py:260)"
        )
    return sorted(common)


def shutdown_tasks(task_addresses, key: bytes) -> None:
    """Accepts an index-ordered list or an {index: addresses} dict (the
    partial-registration case preserves the true task indices)."""
    items = (
        task_addresses.items()
        if isinstance(task_addresses, dict)
        else enumerate(task_addresses)
    )
    for i, addrs in items:
        try:
            BasicClient(
                f"task-probe-{i}", [tuple(a) for a in addrs], key,
                attempts=1, timeout_s=2.0,
            ).request(ShutdownTaskRequest())
        except Exception:
            pass  # task already gone; probing is best-effort cleanup


def get_common_interfaces(
    hosts: List[str],
    key: bytes,
    nics: Optional[List[str]] = None,
    launch_task_fn=None,
    timeout_s: float = 60.0,
) -> Optional[List[str]]:
    """High-level flow (reference get_common_interfaces,
    driver_service.py:260): explicit --network-interface wins; a
    single/local-only host list needs no probing; otherwise launch one
    probe task per host via `launch_task_fn(host, driver_addresses)`,
    wait for registration, ring-probe, intersect, shut the tasks down.
    Returns None when probing is unnecessary."""
    from ..util.network import is_local_host

    if nics:
        return list(nics)
    remote = [h for h in hosts if not is_local_host(h)]
    if not remote:
        return None
    if launch_task_fn is None:
        raise ValueError(
            "remote hosts need a launch_task_fn to start probe tasks"
        )
    driver = DriverProbeService(len(hosts), key)
    try:
        for idx, host in enumerate(hosts):
            launch_task_fn(idx, host, driver.addresses())
        try:
            driver.wait_for_registration(timeout_s)
        except TimeoutError:
            # shut down whatever DID register — otherwise their ssh
            # sessions linger for the full --linger-s and a retried
            # launch doubles them up
            shutdown_tasks(dict(driver.task_addresses), key)
            raise
        ordered = [driver.task_addresses[i] for i in range(len(hosts))]
        try:
            return find_common_nics(ordered, key, timeout_s=10.0)
        finally:
            shutdown_tasks(ordered, key)
    finally:
        driver.shutdown()
