"""Driver-side services: task-to-task NIC routability probing.

Reference: /root/reference/horovod/runner/driver/driver_service.py — the
launcher starts a task server on every host, tasks ring-probe each
other's advertised interface addresses, and the driver intersects the
routable sets into the common NICs the job binds.
"""

from .probe import (  # noqa: F401
    DriverProbeService,
    TaskProbeService,
    find_common_nics,
    get_common_interfaces,
    interface_addresses,
)
