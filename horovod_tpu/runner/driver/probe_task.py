"""Probe-task entry point, launched once per host by the driver
(reference horovod/runner/task_fn.py): starts a TaskProbeService,
registers with the driver, serves probes until told to shut down."""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys

from ..util.network import BasicClient
from ..util.secret import ENV_SECRET
from .probe import RegisterTaskRequest, TaskProbeService


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("index", type=int)
    p.add_argument("driver_addresses",
                   help="base64 JSON list of (ip, port) pairs")
    p.add_argument("--linger-s", type=float, default=300.0)
    args = p.parse_args(argv)

    key = os.environ[ENV_SECRET].encode()
    addrs = [
        (str(a), int(p_))
        for a, p_ in json.loads(base64.b64decode(args.driver_addresses))
    ]
    svc = TaskProbeService(args.index, key)
    try:
        client = BasicClient("driver-probe", addrs, key)
        client.request(
            RegisterTaskRequest(args.index, svc.addresses())
        )
        # serve probes until the driver's shutdown request (or linger cap
        # so an orphaned task never outlives a dead driver for long)
        svc.stop_event.wait(timeout=args.linger_s)
        return 0
    finally:
        svc.shutdown()


if __name__ == "__main__":
    sys.exit(main())
