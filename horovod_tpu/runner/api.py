"""In-process `horovod_tpu.runner.run()` API.

Reference: /root/reference/horovod/runner/__init__.py:94 (`horovod.run`) —
run a python function on every slot and collect return values. Each slot
executes `func` in a spawned interpreter; results come back pickled via
the rendezvous KV store.
"""

from __future__ import annotations

import base64
import os
import pickle
import sys
import tempfile
from typing import Any, Callable, List, Optional

from .exec_run import run_static
from .util.hosts import HostInfo, parse_hosts

_WORKER_SNIPPET = r"""
import base64, os, pickle, sys
with open(os.environ["HVD_TPU_FUNC_FILE"], "rb") as f:
    func, args, kwargs = pickle.loads(f.read())
result = func(*args, **kwargs)
out = os.environ["HVD_TPU_RESULT_DIR"]
rank = os.environ["HVD_TPU_RANK"]
with open(os.path.join(out, f"result_{rank}.pkl"), "wb") as f:
    f.write(pickle.dumps(result))
"""


def run(
    func: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    np: int = 1,
    hosts: Optional[str] = None,
    env: Optional[dict] = None,
    use_cloudpickle: bool = True,
) -> List[Any]:
    """Run `func(*args, **kwargs)` on np slots; return per-rank results."""
    try:
        import cloudpickle  # type: ignore

        dumps = cloudpickle.dumps if use_cloudpickle else pickle.dumps
    except ImportError:
        dumps = pickle.dumps

    host_list = (
        parse_hosts(hosts) if hosts else [HostInfo("localhost", np)]
    )
    from .util.network import get_local_host_addresses

    local = set(get_local_host_addresses()) | {"localhost"}
    remote = [h.hostname for h in host_list if h.hostname not in local]
    if remote:
        # function + results travel through a launcher-local tempdir; a
        # shared-filesystem multi-host variant would need a remote channel
        raise ValueError(
            f"runner.run() executes slots on this machine only; remote "
            f"hosts {remote} are not supported — use hvdrun with a script"
        )
    with tempfile.TemporaryDirectory(prefix="hvd_tpu_run_") as tmp:
        func_file = os.path.join(tmp, "func.pkl")
        with open(func_file, "wb") as f:
            f.write(dumps((func, args, kwargs or {})))
        run_env = dict(env or os.environ)
        run_env["HVD_TPU_FUNC_FILE"] = func_file
        run_env["HVD_TPU_RESULT_DIR"] = tmp
        command = [sys.executable, "-c", _WORKER_SNIPPET]
        codes = run_static(command, host_list, np, env=run_env)
        if any(codes):
            raise RuntimeError(f"worker failure, exit codes {codes}")
        results = []
        for rank in range(np):
            with open(os.path.join(tmp, f"result_{rank}.pkl"), "rb") as f:
                results.append(pickle.loads(f.read()))
        return results
