"""SyncBatchNorm: batch normalization with cross-replica statistics.

Reference: /root/reference/horovod/torch/sync_batch_norm.py:40 (allreduce
of sum/sum-of-squares + count across the process set) and
tensorflow/sync_batch_norm.py:65. TPU-native form: a flax module whose
batch statistics are `lax.pmean`'d over the data-parallel mesh axes when
called inside shard_map/pjit — one fused XLA collective per layer instead
of the reference's handle-based allreduce pair.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from .core import basics


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm that averages statistics across the dp axis.

    Outside an SPMD context (or world of 1) it degrades to plain local
    batch norm, matching the reference's behavior when size()==1
    (torch/sync_batch_norm.py:46).
    """

    use_running_average: Optional[bool] = None
    axis: int = -1
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Any = None
    use_bias: bool = True
    use_scale: bool = True
    axis_name: Optional[Union[str, Sequence[str]]] = None
    process_set: Any = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average,
        )
        feature_axis = self.axis % x.ndim
        reduce_axes = tuple(i for i in range(x.ndim) if i != feature_axis)
        feature_shape = (x.shape[feature_axis],)

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(feature_shape, jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(feature_shape, jnp.float32)
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean2 = jnp.mean(jnp.square(xf), axis=reduce_axes)
            axes = self._live_axes()
            if axes:
                groups = None
                if self.process_set is not None:
                    st_size = basics.bound_axis_sizes()
                    world = 1
                    for ax in axes:
                        world *= st_size[ax]
                    groups = self.process_set.axis_index_groups(world)
                mean = lax.pmean(mean, axes, axis_index_groups=groups)
                mean2 = lax.pmean(mean2, axes, axis_index_groups=groups)
            var = mean2 - jnp.square(mean)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )

        y = x - mean.reshape(
            [1 if i != feature_axis else -1 for i in range(x.ndim)]
        ).astype(x.dtype)
        mul = lax.rsqrt(var + self.epsilon).astype(x.dtype)
        if self.use_scale:
            scale = self.param(
                "scale", nn.initializers.ones, feature_shape, jnp.float32
            ).astype(x.dtype)
            mul = mul * scale
        y = y * mul.reshape(
            [1 if i != feature_axis else -1 for i in range(x.ndim)]
        )
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, feature_shape, jnp.float32
            ).astype(x.dtype)
            y = y + bias.reshape(
                [1 if i != feature_axis else -1 for i in range(x.ndim)]
            )
        return y

    def _live_axes(self) -> Tuple[str, ...]:
        sizes = basics.bound_axis_sizes()
        if self.axis_name is not None:
            names = (
                (self.axis_name,)
                if isinstance(self.axis_name, str)
                else tuple(self.axis_name)
            )
            return tuple(ax for ax in names if ax in sizes)
        from .core.state import global_state

        st = global_state()
        if st.initialized:
            return tuple(ax for ax in st.dp_axis if ax in sizes)
        return ()
