"""Model save/load with DistributedOptimizer rehydration.

Reference: /root/reference/horovod/keras/__init__.py:181 (`load_model`)
and horovod/_keras/__init__.py — a saved Keras model's optimizer is
deserialized from the file and transparently re-wrapped in
`DistributedOptimizer`, so slot state (momenta, Adam moments) carries
into retraining.

TPU-native form: JAX models are pytrees, optimizers are optax
transformations. `save_model` writes an orbax checkpoint of
{params, opt_state} plus a JSON spec of the inner optimizer (name +
kwargs) and the DistributedOptimizer wrapper config; `load_model`
rebuilds the optax optimizer from the spec, re-wraps it in
`DistributedOptimizer` with the same wrapper config, and restores the
optimizer state into the rebuilt transform's own structure — the exact
analog of the reference's wrap_optimizer deserialization hook.

Rank discipline matches the reference's idiom: call `save_model` on
rank 0 only; call `load_model` on every rank (each reads the same
checkpoint; parameters are already identical so no broadcast is needed,
but `hvd.broadcast_parameters` after load stays harmless).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, NamedTuple, Optional

from .optim.compression import Compression
from .optim.distributed import DistributedOptimizer
from .utils import faults, retry

_SPEC_FILE = "horovod_tpu_model.json"
_TREE_DIR = "tree"


def _ckpt_io(point: str, fn: Callable, *args, **kwargs):
    """Checkpoint I/O under the shared retry policy: a transiently
    failing filesystem (GCS 5xx surfacing as OSError, NFS hiccup) backs
    off and retries instead of losing the checkpoint; the
    ``checkpoint.save`` / ``checkpoint.restore`` fault points exercise
    exactly this path (tests/test_faults.py)."""
    def _do():
        faults.inject(point)
        return fn(*args, **kwargs)

    return retry.default_policy().call(_do, point=point)

_COMPRESSION_NAMES = {
    Compression.none: "none",
    Compression.fp16: "fp16",
    Compression.bf16: "bf16",
    Compression.int8: "int8",
    Compression.int8_raw: "int8-raw",
}
_COMPRESSION_BY_NAME = {v: k for k, v in _COMPRESSION_NAMES.items()}


class LoadedModel(NamedTuple):
    """What retraining needs: parameters, a ready DistributedOptimizer,
    its restored state, and user metadata."""

    params: Any
    optimizer: Any           # optax transform wrapped in DistributedOptimizer
    opt_state: Any           # restored slot state (None if none was saved)
    metadata: Dict[str, Any]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_model(
    path: str,
    params: Any,
    opt_state: Any = None,
    optimizer_spec: Optional[tuple] = None,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    op=None,
    gradient_predivide_factor: float = 1.0,
    metadata: Optional[Dict[str, Any]] = None,
) -> None:
    """Save params (+ optimizer slot state and its rebuild spec).

    `optimizer_spec` is `(name, kwargs)` naming an `optax` factory, e.g.
    ``("adam", {"learning_rate": 1e-3})`` — the serializable identity of
    the optimizer, playing the role of Keras's optimizer config in the
    reference's save file (keras/__init__.py:181 relies on it to rebuild
    and re-wrap). Custom factories save by name and load via
    `load_model(custom_optimizers={name: factory})`.
    """
    from .ops.collectives import ReduceOp

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    if compression not in _COMPRESSION_NAMES:
        # a silently-dropped custom compressor would change wire
        # numerics on reload with no error
        raise ValueError(
            "save_model can only serialize the built-in Compression "
            "variants (none/fp16/bf16/int8/int8-raw); re-wrap custom "
            "compressors yourself after load_model"
        )
    if op is None:
        op = ReduceOp.AVERAGE  # DistributedOptimizer's default
    spec: Dict[str, Any] = {
        "format": 1,
        "has_opt_state": opt_state is not None,
        "metadata": metadata or {},
        "wrapper": {
            "compression": _COMPRESSION_NAMES[compression],
            "backward_passes_per_step": int(backward_passes_per_step),
            "op": int(op),
            "gradient_predivide_factor": float(gradient_predivide_factor),
        },
    }
    if optimizer_spec is not None:
        name, kwargs = optimizer_spec
        spec["optimizer"] = {"name": str(name), "kwargs": dict(kwargs)}
    with open(os.path.join(path, _SPEC_FILE), "w") as f:
        json.dump(spec, f, indent=2, sort_keys=True)
    tree = {"params": params}
    if opt_state is not None:
        tree["opt_state"] = opt_state
    ckptr = _checkpointer()
    tree_path = os.path.join(path, _TREE_DIR)

    def _save():
        ckptr.save(tree_path, tree, force=True)
        ckptr.wait_until_finished()

    _ckpt_io("checkpoint.save", _save)


def orbax_rung(path: str, attrs: Optional[Dict[str, str]] = None):
    """Build the orbax rung of the layered recovery ladder
    (elastic/replication.py): a callable ``fn(state) -> bool`` that
    restores a saved checkpoint's trees into elastic-state attributes
    when the fresher rungs (peer replica, emergency snapshot) fall
    through.

    ``attrs`` maps state attribute name → checkpoint tree key (default
    ``{"params": "params", "opt_state": "opt_state"}``, matching
    :func:`save_model`); attributes the checkpoint does not carry are
    left untouched. Attach it before ``hvd.elastic.run``::

        state = hvd.elastic.TpuState(params=params, opt_state=opt_state)
        state.orbax_restore = hvd.checkpoint.orbax_rung("/ckpt/latest")
    """
    mapping = dict(attrs) if attrs else {
        "params": "params", "opt_state": "opt_state",
    }

    def _restore(state) -> bool:
        import jax
        import numpy as np

        ckptr = _checkpointer()
        raw = _ckpt_io(
            "checkpoint.restore", ckptr.restore,
            os.path.join(os.path.abspath(path), _TREE_DIR),
        )
        restored = False
        for attr, key in mapping.items():
            if key not in raw or attr not in state._known:
                continue
            host = jax.tree_util.tree_map(
                lambda x: np.asarray(x), raw[key])
            setattr(state, attr, host)
            restored = True
        return restored

    return _restore


_FSDP_SPEC_FILE = "horovod_tpu_fsdp.json"


def save_fsdp(path: str, rows, layout, opt_state: Any = None,
              metadata: Optional[Dict[str, Any]] = None) -> None:
    """Save FSDP-sharded parameter rows (+ the sharded optimizer
    state) WITHOUT materializing a full replica on any host: the row
    dict's leaves are jax.Arrays sharded one row per device over the
    data axis (optim/fsdp.py), and orbax writes each host's addressable
    shards directly — the save is keyed by the shard spec, never
    gathered (docs/recovery.md documents the on-disk layout).

    ``layout`` is the FsdpLayout the rows were sharded with; its
    world/bucket geometry is serialized to ``horovod_tpu_fsdp.json`` so
    :func:`load_fsdp` can rebuild the restore template (and refuse a
    mismatched world loudly instead of de-padding garbage). Call on
    every host (orbax coordinates the multi-host write); restore with
    ``load_fsdp`` on every host.
    """
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    import numpy as np

    spec: Dict[str, Any] = {
        "format": 1,
        "kind": "fsdp_rows",
        "world": int(layout.world),
        "has_opt_state": opt_state is not None,
        "buckets": [
            {
                "index": i,
                "len": int(L),
                "k": int(k),
                "dtype": np.dtype(d).name,
            }
            for i, (L, k, d) in enumerate(
                zip(layout.lens, layout.ks, layout.dtypes))
        ],
        "metadata": metadata or {},
    }
    with open(os.path.join(path, _FSDP_SPEC_FILE), "w") as f:
        json.dump(spec, f, indent=2, sort_keys=True)
    tree: Dict[str, Any] = {"params_rows": dict(rows)}
    if opt_state is not None:
        tree["opt_state"] = opt_state
    ckptr = _checkpointer()
    tree_path = os.path.join(path, _TREE_DIR)

    def _save():
        ckptr.save(tree_path, tree, force=True)
        ckptr.wait_until_finished()

    _ckpt_io("checkpoint.save", _save)


def load_fsdp(path: str, mesh, axis_name: Optional[str] = None,
              abstract_state: Any = None):
    """Restore FSDP-sharded parameter rows saved by :func:`save_fsdp`,
    placed DIRECTLY into their `P(ax)` shardings — each host reads only
    the shards it owns, so no full replica ever exists in host or
    device memory (the property the FSDP scale story rests on).

    ``abstract_state`` (e.g. ``jax.eval_shape(optimizer.init,
    abs_params)``) supplies the optimizer-state restore template when
    the checkpoint carries one; its `(world, k)` leaves restore sharded
    one row per device, everything else replicated. Returns
    ``(rows, opt_state, metadata)`` — ``opt_state`` is None when the
    save carried none or no template was given.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .optim.fsdp import bucket_name

    path = os.path.abspath(path)
    with open(os.path.join(path, _FSDP_SPEC_FILE)) as f:
        spec = json.load(f)
    axes = [a for a, s in zip(mesh.axis_names, mesh.devices.shape)
            if s > 1] if axis_name is None else [axis_name]
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    ax = axes[0] if axes else mesh.axis_names[0]
    world = int(spec["world"])
    if sizes.get(ax, 1) != world:
        raise ValueError(
            f"checkpoint at {path} was sharded for world {world} but "
            f"mesh axis {ax!r} has size {sizes.get(ax, 1)} — restore "
            "on the matching mesh, or restore there and re-slice with "
            "hvd.fsdp.reshard_rows (docs/recovery.md)")
    row_sh = NamedSharding(mesh, P(ax))
    rep = NamedSharding(mesh, P())
    rows_tmpl = {
        bucket_name(b["index"]): jax.ShapeDtypeStruct(
            (world, b["k"]), np.dtype(b["dtype"]), sharding=row_sh)
        for b in spec["buckets"]
    }
    template: Dict[str, Any] = {"params_rows": rows_tmpl}
    has_state = bool(spec.get("has_opt_state"))
    ckptr = _checkpointer()
    tree_path = os.path.join(path, _TREE_DIR)

    def leaf_tmpl(l):
        shape = tuple(np.shape(l))
        sh = row_sh if (len(shape) == 2 and shape[0] == world) else rep
        return jax.ShapeDtypeStruct(
            shape, np.dtype(getattr(l, "dtype", np.float32)),
            sharding=sh)

    if has_state:
        if abstract_state is None:
            # no structure template: shapes from checkpoint metadata
            # (no array bytes), restored in orbax's own tree shape —
            # pass abstract_state for the optimizer's exact structure
            meta = ckptr.metadata(tree_path)
            meta_tree = (meta.item_metadata.tree
                         if hasattr(meta, "item_metadata") else meta)
            abstract_state = meta_tree["opt_state"]
        template["opt_state"] = jax.tree_util.tree_map(
            leaf_tmpl, abstract_state)
    restored = _ckpt_io(
        "checkpoint.restore", ckptr.restore, tree_path, template,
    )
    return (
        restored["params_rows"],
        restored.get("opt_state") if has_state else None,
        dict(spec.get("metadata", {})),
    )


def load_params(path: str):
    """Params-only restore: (params, metadata) as host arrays, no
    optimizer rebuild. The inference-side counterpart of load_model —
    transformers (spark estimator models) need weights, not momenta."""
    import jax

    path = os.path.abspath(path)
    with open(os.path.join(path, _SPEC_FILE)) as f:
        spec = json.load(f)
    ckptr = _checkpointer()
    raw = _ckpt_io(
        "checkpoint.restore", ckptr.restore, os.path.join(path, _TREE_DIR)
    )
    import numpy as np

    params = jax.tree_util.tree_map(lambda x: np.asarray(x), raw["params"])
    return params, dict(spec.get("metadata", {}))


def load_model(
    path: str,
    custom_optimizers: Optional[Dict[str, Callable]] = None,
    compression=None,
    **distributed_kwargs,
) -> LoadedModel:
    """Load a saved model and re-wrap its optimizer in
    DistributedOptimizer (reference keras/__init__.py:181).

    The inner optimizer is rebuilt from the saved spec — `optax.<name>`
    by default, or `custom_optimizers[name]` (the reference's
    `custom_optimizers` hook). The wrapper config (compression,
    backward_passes_per_step, predivide) is restored from the save
    unless overridden here; the restored `opt_state` drops into the
    rebuilt transform, so momenta/moments continue across the reload.
    """
    import optax

    path = os.path.abspath(path)
    with open(os.path.join(path, _SPEC_FILE)) as f:
        spec = json.load(f)

    from .ops.collectives import ReduceOp

    wrapper = dict(spec.get("wrapper", {}))
    if compression is None:
        compression = _COMPRESSION_BY_NAME.get(
            wrapper.get("compression", "none"), Compression.none
        )
    wrapper_kwargs = {
        "backward_passes_per_step": int(
            wrapper.get("backward_passes_per_step", 1)
        ),
        "op": ReduceOp(int(wrapper.get("op", int(ReduceOp.AVERAGE)))),
        "gradient_predivide_factor": float(
            wrapper.get("gradient_predivide_factor", 1.0)
        ),
    }
    wrapper_kwargs.update(distributed_kwargs)

    opt_spec = spec.get("optimizer")
    if opt_spec is None:
        raise ValueError(
            f"checkpoint at {path} was saved without an optimizer_spec; "
            "pass one to save_model to enable optimizer rehydration"
        )
    name, kwargs = opt_spec["name"], opt_spec.get("kwargs", {})
    if custom_optimizers and name in custom_optimizers:
        inner = custom_optimizers[name](**kwargs)
    elif hasattr(optax, name):
        inner = getattr(optax, name)(**kwargs)
    else:
        raise ValueError(
            f"unknown optimizer '{name}'; pass custom_optimizers="
            f"{{'{name}': factory}} (reference load_model "
            "custom_optimizers, keras/__init__.py:181)"
        )
    optimizer = DistributedOptimizer(
        inner, compression=compression, **wrapper_kwargs
    )

    # Restore against the rebuilt transform's own structure: orbax needs
    # a target template, and init(params) IS the authoritative shape of
    # this optimizer's state for these parameters.
    import jax

    ckptr = _checkpointer()
    tree_path = os.path.join(path, _TREE_DIR)
    # restored leaves come back as host arrays (numpy) so the training
    # step's jit places everything uniformly — orbax's own device
    # placement of a template-restored tree can mix shardings
    import numpy as np

    def _to_host(tree):
        return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)

    # ONE data read: parameter shapes come from checkpoint metadata (no
    # array bytes), and the rebuilt optimizer's own init supplies the
    # authoritative opt_state structure for the restore template
    meta = ckptr.metadata(tree_path)
    # orbax >= 0.9 wraps the tree in CheckpointMetadata.item_metadata.tree;
    # 0.7.x returns the metadata tree itself
    meta_tree = (
        meta.item_metadata.tree if hasattr(meta, "item_metadata") else meta
    )
    params_tmpl = jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype),
        meta_tree["params"],
    )
    template = {"params": params_tmpl}
    if spec.get("has_opt_state"):
        template["opt_state"] = jax.eval_shape(optimizer.init, params_tmpl)
    restored = _ckpt_io(
        "checkpoint.restore", ckptr.restore, tree_path, template
    )
    params = _to_host(restored["params"])
    opt_state = (
        _to_host(restored["opt_state"])
        if spec.get("has_opt_state") else None
    )
    return LoadedModel(
        params=params,
        optimizer=optimizer,
        opt_state=opt_state,
        metadata=dict(spec.get("metadata", {})),
    )
