"""Training-loop callbacks (the Keras callback family, framework-neutral).

Reference: /root/reference/horovod/_keras/callbacks.py —
BroadcastGlobalVariablesCallback, MetricAverageCallback,
LearningRateWarmupCallback, LearningRateScheduleCallback. JAX training
loops are explicit, so these are plain objects the loop invokes; each
documents its reference analog.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional


class Callback:
    def on_train_begin(self, state: Any = None) -> Any:
        return state

    def on_epoch_begin(self, epoch: int, state: Any = None) -> Any:
        return state

    def on_batch_end(self, batch: int, state: Any = None) -> Any:
        return state

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state: Any = None) -> Any:
        return state


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial params/opt state from root at train start
    (reference _keras/callbacks.py BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        from .optim import broadcast_parameters

        return broadcast_parameters(state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Allreduce-average metric values across ranks at epoch end
    (reference MetricAverageCallback)."""

    def on_epoch_end(self, epoch, logs=None, state=None):
        if logs:
            import jax.numpy as jnp
            import numpy as np

            from .ops import allreduce

            for k, v in list(logs.items()):
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    reduced = allreduce(
                        jnp.asarray(float(v)).reshape(1),
                        average=True, name=f"metric.{k}",
                    )
                    logs[k] = float(np.asarray(reduced)[0])
                elif hasattr(v, "dtype") and jnp.issubdtype(
                    jnp.asarray(v).dtype, jnp.number
                ):
                    arr = jnp.asarray(v)
                    reduced = allreduce(
                        arr.reshape(-1), average=True, name=f"metric.{k}"
                    ).reshape(arr.shape)
                    logs[k] = (
                        float(reduced) if arr.ndim == 0
                        else np.asarray(reduced)
                    )
        return state


class LearningRateWarmupCallback(Callback):
    """Linearly ramp the LR multiplier from 1 to `size` over warmup epochs
    (reference _keras/callbacks.py LearningRateWarmupCallback: multiplier
    = 1 + epoch * (size - 1) / warmup_epochs — the gradual-warmup trick
    from the large-minibatch SGD recipe). Exposes `scale(epoch)` for
    explicit loops and an optax-style schedule via `as_schedule`.

    `momentum_correction` is accepted for reference-API compatibility; in
    optax the equivalent adjustment is applying
    `momentum_correction_factor(prev_epoch, epoch)` to the momentum
    hyperparameter via `optax.inject_hyperparams` — it is not applied
    automatically here.
    """

    def __init__(self, warmup_epochs: float = 5.0,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0,
                 initial_lr: Optional[float] = None,
                 size: Optional[int] = None):
        from .core import basics

        self.warmup_epochs = warmup_epochs
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self.size = size if size is not None else (
            basics.size() if basics.is_initialized() else 1
        )
        self.initial_lr = initial_lr

    def scale(self, epoch: float) -> float:
        """Multiplier on the base (single-rank) LR at fractional epoch."""
        if epoch >= self.warmup_epochs:
            return float(self.size)
        return 1.0 + epoch * (self.size - 1.0) / self.warmup_epochs

    def momentum_correction_factor(self, prev_epoch: float,
                                   epoch: float) -> float:
        """Multiply SGD momentum by this when the LR changes mid-warmup
        (reference callbacks.py momentum correction: new_lr/old_lr)."""
        if not self.momentum_correction:
            return 1.0
        return self.scale(epoch) / max(self.scale(prev_epoch), 1e-12)

    def as_schedule(self, steps_per_epoch: int,
                    base_lr: Optional[float] = None
                    ) -> Callable[[int], float]:
        if base_lr is None:
            base_lr = self.initial_lr
        if base_lr is None:
            raise ValueError(
                "pass base_lr to as_schedule or initial_lr at construction"
            )

        def schedule(step):
            import jax.numpy as jnp

            epoch = jnp.minimum(
                step / steps_per_epoch, float(self.warmup_epochs)
            )
            return base_lr * (
                1.0 + epoch * (self.size - 1.0) / self.warmup_epochs
            )

        return schedule


class LearningRateScheduleCallback(Callback):
    """Piecewise LR multiplier over epochs
    (reference LearningRateScheduleCallback)."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        self.multiplier = (
            multiplier if callable(multiplier) else (lambda e: multiplier)
        )
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase

    def scale(self, epoch: float) -> float:
        if epoch < self.start_epoch:
            return 1.0
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return 1.0
        e = math.floor(epoch) if self.staircase else epoch
        return float(self.multiplier(e))


class EarlyStoppingCallback(Callback):
    """Stop training when a monitored metric stops improving — the
    Lightning estimator's early-stop surface (reference
    spark/lightning/estimator.py ships pytorch_lightning's
    EarlyStopping through its callbacks param; semantics follow
    keras.callbacks.EarlyStopping).

    Sets ``self.stop_training = True``; the estimators check the flag
    after epoch-end callbacks and break the epoch loop on EVERY rank in
    the same epoch (the stop verdict is OR-reduced across ranks, so
    per-rank metric noise cannot desynchronize the collective
    schedule). ``best`` and ``stopped_epoch`` are left on the instance
    for inspection.
    """

    def __init__(self, monitor: str = "val_loss", min_delta: float = 0.0,
                 patience: int = 0, mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.monitor = monitor
        self.min_delta = abs(float(min_delta))
        self.patience = int(patience)
        self.mode = mode
        self.stop_training = False
        self.best: Optional[float] = None
        self.stopped_epoch: Optional[int] = None
        self._wait = 0

    def _improved(self, value: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_train_begin(self, state=None):
        self.stop_training = False
        self.best = None
        self.stopped_epoch = None
        self._wait = 0
        return state

    def on_epoch_end(self, epoch, logs=None, state=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return state  # metric absent this epoch: no verdict
        value = float(value)
        if self._improved(value):
            self.best = value
            self._wait = 0
        else:
            self._wait += 1
            if self._wait > self.patience:
                self.stop_training = True
                self.stopped_epoch = epoch
        return state


class CommitStateCallback(Callback):
    """Commit elastic state every N batches and at epoch end
    (reference _keras/elastic.py:17 CommitStateCallbackImpl). More
    frequent commits shrink the replay window after a failure; less
    frequent commits cost less snapshot time."""

    def __init__(self, state, batches_per_commit: int = 1):
        self.state = state
        self.batches_per_commit = max(1, int(batches_per_commit))
        self.batches_remaining = self.batches_per_commit

    def on_train_begin(self, state=None):
        # reset on every sync event for cross-rank consistency
        self.batches_remaining = self.batches_per_commit
        return state

    def on_batch_end(self, batch, state=None):
        self.batches_remaining -= 1
        if self.batches_remaining == 0:
            self.state.commit()
            self.batches_remaining = self.batches_per_commit
        return state

    def on_epoch_end(self, epoch, logs=None, state=None):
        self.state.commit()
        return state


class UpdateBatchStateCallback(Callback):
    """Track the in-epoch batch cursor in elastic state so a restarted
    epoch resumes mid-epoch instead of replaying it (reference
    _keras/elastic.py:42). Pairs with ElasticSampler, whose cursor
    skips already-processed samples."""

    def __init__(self, state):
        self.state = state
        if not hasattr(state, "batch"):
            state.batch = 0
            state.register("batch")

    def on_batch_end(self, batch, state=None):
        self.state.batch = batch
        return state

    def on_epoch_end(self, epoch, logs=None, state=None):
        self.state.batch = 0
        return state


class UpdateEpochStateCallback(Callback):
    """Track the GLOBAL epoch number across elastic resets (reference
    _keras/elastic.py:66): framework epoch counters restart at 0 after
    a reset; the state's does not."""

    def __init__(self, state):
        self.state = state
        if not hasattr(state, "epoch"):
            state.epoch = 0
            state.register("epoch")

    def on_epoch_end(self, epoch, logs=None, state=None):
        self.state.epoch += 1
        return state
