"""Iteration-level continuous batching for autoregressive decode.

The dynamic batcher (batcher.py) coalesces *one-shot* requests: a batch
forms, runs once, disbands. Generation can't work that way — a batch of
sequences finishes at wildly different lengths, and restarting the
batch when the longest member ends (the "static batching" baseline
scripts/decode_check.py measures against) leaves most slots idle most
of the time. This scheduler rebuilds the batch EVERY token:

* **each decode iteration** it (1) evicts finished sequences — EOS,
  length cap, or deadline — releasing their cache slots immediately,
  (2) admits queued prefills into whatever slots just freed, without
  touching co-resident sequences (the slotted cache makes recycling
  free, serving/decode.py), then (3) runs one decode step for every
  occupied slot;
* **admission extends batcher.py's contract**: bounded queue
  (:class:`~horovod_tpu.serving.batcher.QueueFull` → HTTP 429),
  per-request deadlines (queued expiry →
  :class:`~horovod_tpu.serving.batcher.RequestTimeout` → 504; mid-
  generation expiry ends the stream with ``finish_reason="deadline"``
  — partial output beats a dropped connection),
  :class:`~horovod_tpu.serving.batcher.Draining` on shutdown;
* **SLO classes** (``interactive`` < ``standard`` < ``batch``): the
  queue admits in (class, deadline) order, and when the queue is full
  an arriving request sheds the newest strictly-lower-priority queued
  request instead of being rejected — load is shed from the batch tier
  BEFORE an interactive deadline is missed;
* **streaming**: every generated token is pushed to the request's
  chunk queue the iteration it exists; server.py forwards chunks as a
  chunked HTTP response with the request's ``X-Request-Id`` threaded
  through (serving/tracing.py), so time-to-first-token is one prefill,
  not one full generation.

``clock`` is injectable (tests/test_decode.py drives a fake clock and
calls :meth:`step_once` directly — no background thread, fully
deterministic), the same idiom as batcher.py and utils/retry.py.
"""

from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils import faults, flight, metrics
from . import tracing
from .batcher import Draining, QueueFull, RequestTimeout
from .engine import serving_knobs

#: admission classes, best-first. Lower value = stricter SLO = admitted
#: first and shed last.
SLO_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}

_req_seq = itertools.count(1)


class GenRequest:
    """One submitted generation: future + token stream.

    The scheduler thread pushes chunk dicts (``{"tokens": [...]}``,
    then ``{"done": True, "finish_reason": ..., "n": ...}``) into a
    bounded-blocking queue; the HTTP handler (or any consumer) drains
    them via :meth:`stream` or waits for the whole thing via
    :meth:`result`.
    """

    __slots__ = ("prompt", "max_new", "slo", "slo_name", "enqueue_t",
                 "deadline_t", "req_id", "seq", "tokens",
                 "finish_reason", "_chunks", "_done", "_error")

    def __init__(self, prompt: np.ndarray, max_new: int, slo: str,
                 enqueue_t: float, deadline_t: Optional[float]):
        self.prompt = prompt
        self.max_new = max_new
        self.slo_name = slo
        self.slo = SLO_CLASSES[slo]
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        self.req_id = tracing.current_request_id()
        self.seq = next(_req_seq)
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self._chunks: "queue_mod.Queue" = queue_mod.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    # -- scheduler side ------------------------------------------------------

    def push_tokens(self, toks: Sequence[int]) -> None:
        self.tokens.extend(int(t) for t in toks)
        self._chunks.put({"tokens": [int(t) for t in toks]})

    def finish(self, reason: str) -> None:
        self.finish_reason = reason
        self._chunks.put({"done": True, "finish_reason": reason,
                          "n": len(self.tokens)})
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self._error = exc
        self._chunks.put({"done": True, "error": str(exc)})
        self._done.set()

    # -- consumer side -------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def stream(self, timeout_s: Optional[float] = None):
        """Yield chunk dicts until the done chunk (inclusive). An error
        BEFORE any token raises (the HTTP handler maps it to a status
        code); after tokens flowed the stream ends with the error chunk
        — the status line is already on the wire."""
        saw_tokens = False
        while True:
            chunk = self._chunks.get(timeout=timeout_s)
            if chunk.get("done") and self._error is not None \
                    and not saw_tokens:
                raise self._error
            yield chunk
            if chunk.get("done"):
                return
            saw_tokens = True

    def result(self, timeout_s: Optional[float] = None):
        """Block for completion; returns ``(tokens, finish_reason)``."""
        if not self._done.wait(timeout_s):
            raise RequestTimeout(
                f"no completion within {timeout_s}s (scheduler stuck?)")
        if self._error is not None:
            raise self._error
        return list(self.tokens), self.finish_reason


class DecodeScheduler:
    """Continuous-batching loop over a
    :class:`~horovod_tpu.serving.decode.GenerationEngine`.

    Invariants (tests/test_decode.py):

    * a sequence's token stream is a pure function of its prompt and
      the engine — co-residents, admissions and evictions in other
      slots never perturb it (greedy fp32-KV output is bitwise equal
      to running the same prompt alone);
    * a freed slot is admittable on the very next iteration — no
      batch restart, no drain barrier;
    * eviction reasons are exactly one of eos / length / deadline /
      shed / drain, each counted in
      ``hvd_serving_decode_evictions_total``.
    """

    def __init__(
        self,
        engine,
        *,
        queue_limit: Optional[int] = None,
        default_timeout_s: Optional[float] = None,
        default_max_new: Optional[int] = None,
        stats_every: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        knobs = serving_knobs()
        self._engine = engine
        self._queue_limit = (int(queue_limit) if queue_limit is not None
                             else int(knobs.serving_queue_limit))
        if default_timeout_s is None:
            default_timeout_s = knobs.serving_request_timeout_seconds
        self._default_timeout_s = float(default_timeout_s)
        self._default_max_new = int(
            default_max_new
            if default_max_new is not None
            else getattr(knobs, "serving_decode_max_new", 64) or 64)
        self._stats_every = int(
            stats_every if stats_every is not None
            else getattr(knobs, "serving_decode_stats_every", 50) or 0)
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[GenRequest] = []
        self._active: Dict[int, GenRequest] = {}  # slot -> request
        S = engine.slots
        self._tokens = np.zeros(S, np.int32)   # last token per slot
        self._lengths = np.zeros(S, np.int32)  # cache rows valid
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._iterations = 0
        self._tokens_out = 0
        self._evictions: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "DecodeScheduler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="hvd-decode-scheduler")
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop admission; with ``drain`` finish every admitted
        sequence (bounded by its own max_new/deadline) before
        returning, else fail queued AND active immediately."""
        with self._cv:
            self._draining = True
            if not drain:
                for r in self._queue:
                    r.fail(Draining("decode scheduler closed"))
                self._queue.clear()
                for slot, r in list(self._active.items()):
                    self._finish_locked(slot, r, "drain")
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        elif drain:
            # manual-step mode (tests): run the loop body inline
            deadline = time.monotonic() + timeout_s
            while ((self._queue or self._active)
                   and time.monotonic() < deadline):
                self.step_once()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def slot_stats(self) -> Dict[str, int]:
        """The /healthz ``slots`` body: total, occupied, queued
        prefills — what lets a probe (and the autoscaler) distinguish
        "full" from "wedged" (docs/generation.md)."""
        with self._lock:
            return {"total": int(self._engine.slots),
                    "occupied": len(self._active),
                    "queued_prefills": len(self._queue)}

    # -- admission -----------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        timeout_s: Optional[float] = None,
        slo: str = "standard",
    ) -> GenRequest:
        """Admit one generation request; returns its
        :class:`GenRequest`. Raises :class:`QueueFull` /
        :class:`Draining` / ``ValueError`` synchronously, exactly the
        batcher's admission surface."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("generate needs at least one prompt token")
        top_prefill = self._engine.prefill_buckets[-1]
        if (prompt.shape[0] >= self._engine.max_len
                or prompt.shape[0] > top_prefill):
            # can never fit (cache or prefill ladder): client error
            # (400) AT ADMISSION, not backpressure and not a deep
            # engine failure after the request already cost a slot
            raise ValueError(
                f"prompt of {prompt.shape[0]} tokens exceeds this "
                f"replica's limits (cache max_len "
                f"{self._engine.max_len}, top prefill bucket "
                f"{top_prefill}); truncate client-side or target a "
                "longer-context bucket")
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown slo class {slo!r}; expected one of "
                f"{sorted(SLO_CLASSES)}")
        faults.inject("serving.decode_admit", n=int(prompt.shape[0]))
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        if max_new_tokens is None:
            max_new = self._default_max_new
        else:
            max_new = int(max_new_tokens)
            if max_new < 1:
                # an explicit zero/negative cap is a client error, not
                # an invitation to substitute the default
                raise ValueError(
                    f"max_new_tokens must be >= 1, got {max_new}")
        # the cache bounds generation: prompt rows + generated rows
        # must fit max_len (the last token is never written)
        max_new = max(1, min(max_new,
                             self._engine.max_len - prompt.shape[0]))
        now = self._clock()
        r = GenRequest(prompt, max_new, slo, now,
                       now + timeout_s if timeout_s else None)
        with self._cv:
            if self._draining:
                raise Draining("decode scheduler is draining")
            if len(self._queue) >= self._queue_limit:
                victim = self._shed_candidate_locked(r)
                if victim is None:
                    raise QueueFull(
                        f"decode admission queue at capacity "
                        f"({len(self._queue)}/{self._queue_limit} "
                        "requests)")
                self._queue.remove(victim)
                victim.fail(QueueFull(
                    f"shed for an arriving {r.slo_name!r}-class "
                    "request (queue at capacity)"))
                self._count_eviction("shed")
                flight.record("decode_shed", victim.req_id,
                              slo=victim.slo_name, for_slo=r.slo_name)
            self._queue.append(r)
            self._cv.notify_all()
        return r

    def _shed_candidate_locked(self, incoming: GenRequest):
        """The queued request to shed for ``incoming``: the NEWEST
        queued request of the LOWEST priority class strictly below the
        incoming class (None = nothing sheddable — equal-or-better
        classes are never shed)."""
        worst = None
        for r in self._queue:
            if r.slo <= incoming.slo:
                continue
            if (worst is None or r.slo > worst.slo
                    or (r.slo == worst.slo and r.seq > worst.seq)):
                worst = r
        return worst

    # -- the iteration -------------------------------------------------------

    def _count_eviction(self, reason: str) -> None:
        self._evictions[reason] = self._evictions.get(reason, 0) + 1
        metrics.record_decode_eviction(reason)

    def _evict_locked(self, slot: int, reason: str) -> None:
        self._active.pop(slot, None)
        self._tokens[slot] = 0
        self._lengths[slot] = 0
        self._engine.release_slot(slot)

    def _finish_locked(self, slot: int, r: GenRequest,
                       reason: str) -> None:
        r.finish(reason)
        self._count_eviction(reason)
        if r.req_id:
            flight.record("decode_finish", r.req_id, reason=reason,
                          n=len(r.tokens))
        self._evict_locked(slot, reason)

    def step_once(self) -> bool:
        """One scheduler iteration: expire, evict, admit, decode.
        Returns whether any work happened (the loop idles otherwise).
        Public so tests can drive the scheduler deterministically
        under a fake clock without the background thread."""
        now = self._clock()
        admitted: List[tuple] = []  # (slot, request)
        with self._cv:
            # queued requests whose deadline passed: complete with the
            # batcher's timeout error (504) — they never cost a slot
            for r in [q for q in self._queue
                      if q.deadline_t is not None and now > q.deadline_t]:
                self._queue.remove(r)
                r.fail(RequestTimeout(
                    f"request expired after {now - r.enqueue_t:.3f}s "
                    "in the decode admission queue"))
                self._count_eviction("deadline")
            # active sequences past deadline: the stream ends with
            # what it has; co-residents are untouched
            for slot, r in list(self._active.items()):
                if r.deadline_t is not None and now > r.deadline_t:
                    self._finish_locked(slot, r, "deadline")
            # admit queued prefills into freed slots, best class /
            # earliest deadline first
            while self._queue:
                slot = self._engine.claim_slot()
                if slot is None:
                    break
                r = min(self._queue,
                        key=lambda q: (q.slo,
                                       q.deadline_t
                                       if q.deadline_t is not None
                                       else float("inf"),
                                       q.seq))
                self._queue.remove(r)
                self._active[slot] = r
                admitted.append((slot, r))
        # prefills run outside the scheduler lock (submit must not
        # block on compute; the engine serializes execution itself)
        for slot, r in admitted:
            metrics.record_serving_queue_wait(now - r.enqueue_t,
                                              slo=r.slo_name)
            if r.req_id:
                flight.record("decode_admit", r.req_id, slot=slot,
                              n=int(r.prompt.shape[0]), slo=r.slo_name)
            try:
                first, _ = self._engine.prefill(slot, r.prompt)
            except BaseException as e:  # noqa: BLE001 — fail the one
                with self._cv:
                    r.fail(e)
                    self._count_eviction("error")
                    self._evict_locked(slot, "error")
                continue
            # TTFT: admission to first emitted token, per SLO class —
            # the scoreboard series the burn-rate rules watch
            metrics.record_serving_ttft(self._clock() - r.enqueue_t,
                                        slo=r.slo_name)
            with self._cv:
                if slot not in self._active:
                    continue  # evicted between admit and prefill
                self._tokens[slot] = first
                self._lengths[slot] = r.prompt.shape[0]
                r.push_tokens([first])
                self._tokens_out += 1
                metrics.record_decode_tokens(1)
                if ((self._engine.eos_id is not None
                     and first == self._engine.eos_id)):
                    self._finish_locked(slot, r, "eos")
                elif len(r.tokens) >= r.max_new:
                    self._finish_locked(slot, r, "length")
        # one decode iteration for every occupied slot
        with self._lock:
            active = dict(self._active)
            tokens = self._tokens.copy()
            lengths = self._lengths.copy()
        did_decode = False
        if active:
            # the engine bills this iteration's wall time to each live
            # sequence as its TPOT, by SLO class (decode.py)
            nxt, _ = self._engine.decode(
                tokens, lengths,
                slos=[r.slo_name for r in active.values()])
            did_decode = True
            n_new = 0
            with self._cv:
                for slot, r in list(self._active.items()):
                    if slot not in active:
                        continue  # admitted after the snapshot
                    tok = int(nxt[slot])
                    self._tokens[slot] = tok
                    self._lengths[slot] += 1
                    r.push_tokens([tok])
                    n_new += 1
                    if (self._engine.eos_id is not None
                            and tok == self._engine.eos_id):
                        self._finish_locked(slot, r, "eos")
                    elif (len(r.tokens) >= r.max_new
                          or self._lengths[slot]
                          >= self._engine.max_len):
                        self._finish_locked(slot, r, "length")
                self._tokens_out += n_new
            metrics.record_decode_tokens(n_new)
        self._iterations += 1
        with self._lock:
            occupied = len(self._active)
            queued = len(self._queue)
        metrics.set_decode_slots(self._engine.slots, occupied, queued)
        if (self._stats_every
                and self._iterations % self._stats_every == 0):
            metrics.step_stats.emit_event("decode", {
                "iterations": self._iterations,
                "tokens": self._tokens_out,
                "slots_total": int(self._engine.slots),
                "slots_occupied": occupied,
                "queued_prefills": queued,
                "evictions": dict(self._evictions),
            })
        return bool(admitted) or did_decode

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._draining and not self._queue and not self._active:
                    return
                if not self._queue and not self._active:
                    self._cv.wait(0.05)
                    continue
            try:
                self.step_once()
            except Exception as e:  # noqa: BLE001 — the loop must live
                # an engine-level failure poisons every resident
                # sequence; fail them rather than hang their clients
                with self._cv:
                    for slot, r in list(self._active.items()):
                        r.fail(e)
                        self._count_eviction("error")
                        self._evict_locked(slot, "error")
