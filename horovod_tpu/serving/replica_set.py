"""Multi-replica data-parallel dispatch for the serving tier.

One replica = one process owning its accelerator(s), running
engine + batcher behind the HTTP front end (server.py). This module is
everything around them:

* **registration** — replicas announce ``(kind="serving", index,
  host:port)`` through the launcher's authenticated registry
  (``runner/compute_service.py``), exactly as data-service compute
  workers do; the front door waits for N replicas the same way
  trainers wait for data workers;
* **routing** — :class:`ReplicaSet` tracks per-replica in-flight
  counts locally and routes each request to the least-loaded live
  replica;
* **failover** — a replica that dies mid-request (connection error or
  5xx) is ejected and the request retried on another replica under the
  shared :class:`~horovod_tpu.utils.retry.RetryPolicy`
  (``serving.dispatch`` retry point) — the client never sees the
  death. When every replica is ejected the set forgives them all once
  and re-probes, so a restarted replica rejoins without a control
  plane round-trip;
* **drain-then-exit** — ``python -m horovod_tpu.serving.replica_set``
  installs the preemption handler (elastic/preemption.py): SIGTERM
  stops admission, flushes the batcher and in-flight HTTP requests,
  then exits with ``PREEMPTED_EXIT_CODE`` (83) so the launcher knows
  the host went away healthy.

Fault points: ``serving.dispatch`` fires before every routed attempt
(front door), ``serving.replica_exec`` before every executed batch
(replica, engine.py) — see docs/faults.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils import faults, flight, metrics, retry
from . import tracing
from .batcher import RequestTimeout
from .server import AUTH_HEADER, REQUEST_ID_HEADER, ServingServer, sign_body

SERVING_KIND = "serving"
#: decode replicas register under their own kind: a front door pools
#: ONE capability, so /v1/generate can never be least-loaded-routed to
#: a predict replica (whose 404 is a terminal client error, not a
#: retryable failover) in a mixed fleet
SERVING_DECODE_KIND = "serving-decode"


def _build_body(x: np.ndarray,
                timeout_s: Optional[float] = None) -> bytes:
    """Serialize one predict request ONCE — the dispatch tier reuses
    these bytes across failover attempts instead of re-running
    tolist/dumps/HMAC on every retry."""
    x = np.asarray(x)
    body_obj = {"inputs": x.tolist(), "dtype": str(x.dtype)}
    if timeout_s:
        body_obj["timeout_ms"] = int(timeout_s * 1e3)
    return json.dumps(body_obj).encode()


def _post_body(addr: str, body: bytes, sock_timeout: float,
               key: Optional[bytes] = None,
               request_id: str = "") -> np.ndarray:
    req = urllib.request.Request(
        f"http://{addr}/v1/predict", data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    if key is not None:
        req.add_header(AUTH_HEADER, sign_body(key, body))
    if request_id:
        # the front door's trace id travels to the replica, so both
        # tiers' flight/timeline events name the SAME request
        req.add_header(REQUEST_ID_HEADER, request_id)
    with urllib.request.urlopen(req, timeout=sock_timeout) as resp:
        payload = json.loads(resp.read())
    return np.asarray(payload["outputs"],
                      dtype=np.dtype(payload.get("dtype", "float32")))


def predict_remote(
    addr: str,
    x: np.ndarray,
    timeout_s: Optional[float] = None,
    key: Optional[bytes] = None,
) -> np.ndarray:
    """One POST /v1/predict against ``host:port`` (no retries — that's
    the ReplicaSet's job). Raises urllib.error.HTTPError / OSError."""
    return _post_body(addr, _build_body(x, timeout_s),
                      (timeout_s or 30.0) + 5.0, key=key)


def generate_stream_remote(
    addr: str,
    req: Dict,
    timeout_s: Optional[float] = None,
    key: Optional[bytes] = None,
    request_id: str = "",
):
    """One streaming POST /v1/generate against ``host:port``: a
    generator of parsed chunk dicts, yielded as the replica's chunked
    response delivers them (urllib reassembles the chunked framing;
    each line is one JSON object — server.py's stream contract). No
    retries; failover is :meth:`ReplicaSet.generate`'s job."""
    body_obj = dict(req)
    body_obj["stream"] = True
    if timeout_s:
        body_obj["timeout_ms"] = int(timeout_s * 1e3)
    body = json.dumps(body_obj).encode()
    r = urllib.request.Request(
        f"http://{addr}/v1/generate", data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    if key is not None:
        r.add_header(AUTH_HEADER, sign_body(key, body))
    if request_id:
        r.add_header(REQUEST_ID_HEADER, request_id)
    with urllib.request.urlopen(r, timeout=(timeout_s or 30.0) + 5.0) \
            as resp:
        for line in resp:
            line = line.strip()
            if not line:
                continue
            chunk = json.loads(line)
            yield chunk
            if chunk.get("done"):
                return


def generate_remote(addr: str, req: Dict,
                    timeout_s: Optional[float] = None,
                    key: Optional[bytes] = None):
    """Blocking convenience over :func:`generate_stream_remote`:
    returns ``(tokens, finish_reason)``."""
    tokens, reason = [], None
    for chunk in generate_stream_remote(addr, req, timeout_s, key):
        tokens.extend(int(t) for t in chunk.get("tokens", ()))
        if chunk.get("done"):
            reason = chunk.get("finish_reason")
            if chunk.get("error"):
                raise RuntimeError(f"generation failed mid-stream: "
                                   f"{chunk['error']}")
    return tokens, reason


def _dispatch_retryable(exc: BaseException) -> bool:
    """5xx (replica dying/draining) and 429 (that replica saturated)
    retry on another replica; other HTTP codes are client errors and
    propagate. Transport failures retry — except the dispatch tier's
    own deadline marker, which says the request budget is SPENT."""
    if isinstance(exc, RequestTimeout):
        return False
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code == 429 or (exc.code >= 500 and exc.code != 504)
    return isinstance(exc, (OSError, EOFError))


def _ejects_replica(exc: BaseException) -> bool:
    """Failures that mean the REPLICA is gone (eject it from dispatch),
    vs merely busy. A 429 is backpressure from a healthy replica — the
    request retries elsewhere but the replica stays in rotation;
    ejecting it would durably cut capacity exactly when load is
    highest."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 and exc.code != 504
    return isinstance(exc, (OSError, EOFError))


class ReplicaSet:
    """Least-loaded dispatch with transparent failover.

    ``replicas`` maps index -> "host:port" (usually the
    ComputeService's WorkersResponse). Thread-safe: the front end calls
    ``predict`` from concurrent request threads.
    """

    def __init__(
        self,
        replicas: Dict[int, str],
        *,
        key: Optional[bytes] = None,
        policy: Optional[retry.RetryPolicy] = None,
        default_timeout_s: float = 30.0,
    ):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self._replicas = dict(replicas)
        self._key = key
        # failover must outlast losing every replica but one: give the
        # policy enough attempts to walk the whole set and then some
        self._policy = policy or retry.RetryPolicy(
            max_attempts=max(len(replicas) + 2, 4),
            base_delay_s=0.05, max_delay_s=0.5,
        )
        self._default_timeout_s = default_timeout_s
        self._lock = threading.Lock()
        self._inflight: Dict[int, int] = {i: 0 for i in replicas}
        self._dead: Dict[int, str] = {}
        self._rr = 0

    # -- bookkeeping --------------------------------------------------------

    @property
    def replicas(self) -> Dict[int, str]:
        return dict(self._replicas)

    @property
    def dead(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._dead)

    def _pick(self) -> Tuple[int, str]:
        with self._lock:
            live = [i for i in self._replicas if i not in self._dead]
            if not live:
                # total eclipse: forgive everyone once instead of
                # locking the front door shut — a restarted replica
                # answers, a still-dead one re-ejects on its next miss
                self._dead.clear()
                live = list(self._replicas)
            self._rr += 1
            idx = min(live, key=lambda i: (self._inflight[i],
                                           (i + self._rr) % len(live)))
            self._inflight[idx] += 1
            n = self._inflight[idx]
        metrics.set_serving_inflight(n, replica=str(idx))
        return idx, self._replicas[idx]

    def _release(self, idx: int) -> None:
        with self._lock:
            self._inflight[idx] -= 1
            n = self._inflight[idx]
        metrics.set_serving_inflight(n, replica=str(idx))

    def _mark_dead(self, idx: int, why: BaseException) -> None:
        with self._lock:
            already = idx in self._dead
            self._dead[idx] = f"{type(why).__name__}: {why}"
        if not already:
            metrics.record_serving_failover(str(idx))

    def revive(self, idx: Optional[int] = None) -> None:
        """Forgive one replica (or all) — e.g. after an external
        health check saw it come back."""
        with self._lock:
            if idx is None:
                self._dead.clear()
            else:
                self._dead.pop(idx, None)

    # -- live membership (the autoscaler's hooks) ---------------------------

    def add_replica(self, idx: int, addr: str) -> None:
        """Bring a new replica into rotation (autoscaler grow path).
        Idempotent on the same (idx, addr); re-adding a dead index
        revives it — the spawned process is fresh."""
        with self._lock:
            self._replicas[idx] = addr
            self._inflight.setdefault(idx, 0)
            self._dead.pop(idx, None)
            n = len(self._replicas)
        metrics.set_serving_replicas(n)

    def remove_replica(self, idx: int) -> None:
        """Take a replica out of rotation BEFORE draining it
        (autoscaler shrink path): no new requests route to it, its
        in-flight work finishes under the SIGTERM drain contract. The
        last replica cannot be removed — an empty set would turn every
        request into an instant failure."""
        with self._lock:
            if idx not in self._replicas:
                return
            if len(self._replicas) <= 1:
                raise ValueError(
                    "refusing to remove the last serving replica")
            self._replicas.pop(idx)
            self._dead.pop(idx, None)
            n = len(self._replicas)
        metrics.set_serving_replicas(n)

    # -- dispatch -----------------------------------------------------------

    def predict(self, x: np.ndarray,
                timeout_s: Optional[float] = None) -> np.ndarray:
        """Route one request; retried across replicas on failure so a
        replica death is invisible to the caller."""
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        # serialize once; every failover attempt reuses the bytes
        body = _build_body(x, timeout_s)
        deadline = retry.Deadline(timeout_s)
        rid = tracing.current_request_id()

        def _attempt() -> np.ndarray:
            if deadline.expired():
                # stop the retry ladder once the request's own budget
                # is gone — more attempts only delay the 504 the
                # client has already paid for
                raise RequestTimeout(
                    f"request budget {timeout_s}s exhausted during "
                    f"dispatch/failover")
            idx, addr = self._pick()
            flight.record("serving_dispatch", str(idx),
                          n=int(x.shape[0]), req=rid)
            try:
                faults.inject("serving.dispatch", replica=idx)
                remaining = max(deadline.remaining(), 0.5)
                # a replica that accepts the connection but never
                # answers must not swallow the whole request budget:
                # with peers available, cap each attempt at half the
                # remaining deadline so the socket timeout leaves room
                # for at least one failover
                att = (remaining / 2.0 if len(self._replicas) > 1
                       else remaining)
                att = max(att, 0.5)
                return _post_body(addr, body, att + 1.0, key=self._key,
                                  request_id=rid)
            except BaseException as e:
                if _ejects_replica(e):
                    self._mark_dead(idx, e)
                    flight.record("serving_failover", str(idx),
                                  error=str(e)[:120])
                raise
            finally:
                self._release(idx)

        return self._policy.call(
            _attempt, point="serving.dispatch",
            retryable=_dispatch_retryable,
        )

    def __call__(self, x: np.ndarray,
                 timeout_s: Optional[float] = None) -> np.ndarray:
        return self.predict(x, timeout_s)

    def generate(self, req: Dict, timeout_s: Optional[float] = None):
        """Route one generation request, streaming chunks through as
        the chosen replica produces them. Failover is
        **pre-first-chunk only**: a replica that fails before emitting
        anything (draining 503, queue-full 429, death) is retried on a
        peer exactly like predict; once tokens flowed, the stream is
        committed to that replica and a mid-stream death ends it with
        an in-band ``{"done": true, "error": ...}`` chunk — the
        front-door 200 is already on the wire, and replaying a prefix
        of generated tokens on another replica would emit them twice.
        """
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        deadline = retry.Deadline(timeout_s)
        rid = tracing.current_request_id()
        attempts = max(len(self._replicas) + 2, 4)
        last_exc: Optional[BaseException] = None
        for attempt in range(attempts):
            if deadline.expired():
                raise last_exc or RequestTimeout(
                    f"request budget {timeout_s}s exhausted during "
                    "generate dispatch/failover")
            idx, addr = self._pick()
            flight.record("serving_dispatch", str(idx), req=rid,
                          route="generate")
            yielded = False
            try:
                faults.inject("serving.dispatch", replica=idx)
                for chunk in generate_stream_remote(
                        addr, req, max(deadline.remaining(), 0.5),
                        key=self._key, request_id=rid):
                    yielded = True
                    yield chunk
                return
            except GeneratorExit:
                # the consumer stopped reading (done chunk seen,
                # client hung up): not a replica failure
                raise
            except BaseException as e:
                if _ejects_replica(e):
                    self._mark_dead(idx, e)
                    flight.record("serving_failover", str(idx),
                                  error=str(e)[:120])
                if yielded:
                    yield {"done": True,
                           "error": f"{type(e).__name__}: {e}"}
                    return
                if not _dispatch_retryable(e):
                    raise
                last_exc = e
                metrics.record_retry("serving.dispatch")
                time.sleep(min(0.05 * (attempt + 1), 0.5))
            finally:
                self._release(idx)
        raise last_exc or RuntimeError("generate dispatch exhausted")


# ---------------------------------------------------------------------------
# replica autoscaling: supervisor (spawn/drain) + the metrics-driven
# control loop (docs/generation.md)
# ---------------------------------------------------------------------------

class ReplicaSupervisor:
    """Owns autoscaler-spawned replicas: process lifecycle only.

    ``spawn_fn(index) -> (addr, handle)`` starts one replica and blocks
    until it is serving (the decode_check spawns the real
    ``python -m horovod_tpu.serving.replica_set --decode`` subprocess
    and waits for its READY line; tests pass fakes). ``handle`` needs
    ``send_signal``/``wait`` (a ``subprocess.Popen`` works as-is).

    Drain reuses the preemption contract the elastic driver
    established: SIGTERM → the replica stops admission, finishes every
    resident sequence, exits ``PREEMPTED_EXIT_CODE`` (83) — "host went
    away healthy", never blacklisted (elastic/preemption.py). The
    replica is removed from dispatch BEFORE the signal, so the drain
    is invisible to clients.
    """

    def __init__(self, spawn_fn, replica_set: ReplicaSet,
                 *, base_index: int = 100):
        self._spawn = spawn_fn
        self._rs = replica_set
        self._next_index = base_index
        self._owned: Dict[int, object] = {}  # index -> handle
        self._lock = threading.Lock()

    @property
    def owned(self) -> Dict[int, object]:
        with self._lock:
            return dict(self._owned)

    def grow(self) -> int:
        with self._lock:
            idx = self._next_index
            self._next_index += 1
        addr, handle = self._spawn(idx)
        with self._lock:
            self._owned[idx] = handle
        self._rs.add_replica(idx, addr)
        flight.record("autoscale_grow", str(idx), addr=addr)
        return idx

    def shrink(self, timeout_s: float = 60.0) -> Optional[int]:
        """Drain the newest supervisor-owned replica; returns its
        index (None when this supervisor owns nothing — replicas it
        did not spawn are never its to kill)."""
        import signal as signal_mod

        with self._lock:
            if not self._owned:
                return None
            idx = max(self._owned)
            handle = self._owned[idx]
        # out of rotation first: no new work routes to it while the
        # SIGTERM drain flushes what it already accepted. The handle
        # leaves _owned only once the process is actually reaped — a
        # refused removal (last replica) or a drain timeout must not
        # orphan a live subprocess nobody can signal again.
        self._rs.remove_replica(idx)
        handle.send_signal(signal_mod.SIGTERM)
        rc = handle.wait(timeout=timeout_s)
        with self._lock:
            self._owned.pop(idx, None)
        flight.record("autoscale_shrink", str(idx), exit_code=rc)
        return idx

    def stop_all(self, timeout_s: float = 30.0) -> None:
        while True:
            with self._lock:
                if not self._owned:
                    return
            try:
                self.shrink(timeout_s=timeout_s)
            except ValueError:
                # last replica in the set: leave it serving
                return


class ReplicaAutoscaler:
    """Grow/shrink the replica fleet off the live ``hvd_serving_*``
    decode signals: slot occupancy (``hvd_serving_decode_slots``,
    surfaced as ``slots{}`` on every replica's unauthenticated
    /healthz) and admission queue wait
    (``hvd_serving_queue_wait_seconds`` deltas from /metrics).

    Policy: a poll is *hot* when aggregate occupancy ≥ ``hi_occupancy``
    or prefills are queueing while recent queue wait ≥
    ``queue_wait_hi_s``; *cold* when occupancy ≤ ``lo_occupancy`` with
    an empty queue. ``sustain`` consecutive hot (cold) polls outside
    the ``cooldown_s`` window grow (shrink) by one replica, clamped to
    [min_replicas, max_replicas]. Every action lands in
    ``hvd_serving_autoscale_events_total{action=}`` and the flight
    ring, so a scaling decision is as traceable as a failover.
    """

    def __init__(
        self,
        supervisor: ReplicaSupervisor,
        replica_set: ReplicaSet,
        *,
        signal_fn=None,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        hi_occupancy: Optional[float] = None,
        lo_occupancy: Optional[float] = None,
        queue_wait_hi_s: Optional[float] = None,
        sustain: Optional[int] = None,
        cooldown_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        from .engine import serving_knobs

        k = serving_knobs()

        def _k(v, name, default):
            return v if v is not None else getattr(k, name, default)

        self._sup = supervisor
        self._rs = replica_set
        self._signal_fn = signal_fn or self._scrape_signals
        self.min_replicas = int(_k(min_replicas,
                                   "serving_autoscale_min_replicas", 1))
        self.max_replicas = int(_k(max_replicas,
                                   "serving_autoscale_max_replicas", 4))
        self.hi_occupancy = float(_k(hi_occupancy,
                                     "serving_autoscale_hi_occupancy",
                                     0.85))
        self.lo_occupancy = float(_k(lo_occupancy,
                                     "serving_autoscale_lo_occupancy",
                                     0.25))
        self.queue_wait_hi_s = float(_k(queue_wait_hi_s,
                                        "serving_autoscale_queue_wait_s",
                                        0.5))
        self.sustain = int(_k(sustain, "serving_autoscale_sustain", 2))
        self.cooldown_s = float(_k(cooldown_s,
                                   "serving_autoscale_cooldown_s", 10.0))
        self._clock = clock
        self._hot_streak = 0
        self._cold_streak = 0
        self._last_action_t = -1e9
        self._last_wait: Dict[str, Tuple[float, float]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self.decisions: list = []  # (t, action) trail for tests/checks

    # -- signals -------------------------------------------------------------

    def _scrape_signals(self) -> Dict:
        """Aggregate occupancy/queue state across the live replicas:
        slots{} from /healthz, queue-wait sum/count deltas from
        /metrics. A replica that fails to answer contributes nothing
        (the dispatch tier's failover owns dead-replica handling)."""
        total = occupied = queued = 0
        dsum = dcount = 0.0
        for idx, addr in self._rs.replicas.items():
            try:
                with urllib.request.urlopen(
                        f"http://{addr}/healthz", timeout=2.0) as r:
                    h = json.loads(r.read())
                slots = h.get("slots") or {}
                total += int(slots.get("total", 0))
                occupied += int(slots.get("occupied", 0))
                queued += int(slots.get("queued_prefills", 0))
            except Exception:
                continue
            try:
                with urllib.request.urlopen(
                        f"http://{addr}/metrics", timeout=2.0) as r:
                    text = r.read().decode()
                s = c = 0.0
                # the histogram is labeled by SLO class — accumulate
                # across the {slo="..."} lines rather than keeping
                # whichever label happened to print last
                for line in text.splitlines():
                    if line.startswith(
                            "hvd_serving_queue_wait_seconds_sum"):
                        s += float(line.rsplit(" ", 1)[1])
                    elif line.startswith(
                            "hvd_serving_queue_wait_seconds_count"):
                        c += float(line.rsplit(" ", 1)[1])
                ps, pc = self._last_wait.get(addr, (0.0, 0.0))
                self._last_wait[addr] = (s, c)
                dsum += max(s - ps, 0.0)
                dcount += max(c - pc, 0.0)
            except Exception:
                continue
        return {
            "occupancy": (occupied / total) if total else 0.0,
            "queued": queued,
            "queue_wait_s": (dsum / dcount) if dcount else 0.0,
        }

    # -- the control loop ----------------------------------------------------

    def poll_once(self) -> Optional[str]:
        """One observe-decide-act cycle; returns "grow"/"shrink" when
        an action fired, else None."""
        sig = self._signal_fn()
        now = self._clock()
        n = len(self._rs.replicas)
        hot = (sig.get("occupancy", 0.0) >= self.hi_occupancy
               or (sig.get("queued", 0) > 0
                   and sig.get("queue_wait_s", 0.0)
                   >= self.queue_wait_hi_s))
        cold = (sig.get("occupancy", 0.0) <= self.lo_occupancy
                and sig.get("queued", 0) == 0)
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._cold_streak = self._cold_streak + 1 if cold else 0
        if now - self._last_action_t < self.cooldown_s:
            return None
        action = None
        if (self._hot_streak >= self.sustain
                and n < self.max_replicas):
            self._sup.grow()
            action = "grow"
        elif (self._cold_streak >= self.sustain
                and n > self.min_replicas
                and self._sup.owned):
            if self._sup.shrink() is not None:
                action = "shrink"
        if action:
            self._last_action_t = now
            self._hot_streak = self._cold_streak = 0
            self.decisions.append((now, action))
            metrics.record_autoscale(action)
        return action

    def start(self, interval_s: Optional[float] = None) -> None:
        from .engine import serving_knobs

        if interval_s is None:
            interval_s = float(getattr(
                serving_knobs(), "serving_autoscale_interval_s", 2.0))
        if self._thread is not None:
            return
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — keep scaling
                    flight.record("autoscale_error", "",
                                  error=str(e)[:120])

        t = threading.Thread(target=loop, daemon=True,
                             name="hvd-serving-autoscaler")
        t.start()
        self._thread, self._stop = t, stop

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
            self._stop = None


# ---------------------------------------------------------------------------
# process entry points: one replica, or the front door
# ---------------------------------------------------------------------------

def _secret_or_none() -> Optional[bytes]:
    from ..runner.util import secret

    try:
        return secret.secret_from_env()
    except RuntimeError:
        return None


def _install_drain_handler(server: ServingServer, batcher,
                           drain_timeout_s: float) -> None:
    from ..elastic import preemption

    def _drain():
        server.draining = True          # stop admission first
        if batcher is not None:
            batcher.close(drain=True, timeout_s=drain_timeout_s)
        server.drain(timeout_s=drain_timeout_s)
        # settle: in-flight handlers decrement before their response
        # write; give those last writes a beat before os._exit
        time.sleep(0.25)

    preemption.install(on_preempt=_drain)


def _register(register: str, index: int, port: int,
              key: Optional[bytes], kind: str = SERVING_KIND) -> None:
    from ..runner.compute_service import ComputeClient
    from ..runner.util.network import routable_host_address

    if key is None:
        raise RuntimeError(
            "--register needs the per-job secret in the environment "
            "(HVD_TPU_SECRET_KEY) — the registry authenticates")
    host, _, p = register.rpartition(":")
    client = ComputeClient([(host, int(p))], key)
    address = f"{routable_host_address()}:{port}"
    client.register_worker(kind, index, address)
    _mirror_registration_kv(kind, index, address)


#: rendezvous KV scope mirroring serving registrations — the
#: federation's pod relays batch these upward so the root's view of
#: the serving fleet costs O(pods) requests, and ops tooling can list
#: replicas from the KV surface without speaking the authenticated
#: ComputeService protocol (docs/multipod.md)
SERVING_REGISTRY_SCOPE = "serving_registry"


def _mirror_registration_kv(kind: str, index: int, address: str) -> None:
    """Best-effort KV mirror of one replica registration, sent ONLY
    when a pod relay is configured (``HVD_TPU_RELAY_ADDR/PORT``) — a
    non-federated deployment must not grow new direct-to-root PUTs.
    The authoritative registry stays the ComputeService; a mirror
    failure costs nothing but the federated view."""
    try:
        from ..multipod.relay import relay_endpoint_from_env

        ep = relay_endpoint_from_env()
        if ep is None:
            return
        body = json.dumps({
            "kind": kind, "index": int(index), "address": address,
            "time_unix": time.time(),
        }).encode()
        req = urllib.request.Request(
            f"http://{ep[0]}:{ep[1]}/{SERVING_REGISTRY_SCOPE}/"
            f"{kind}_{index}", data=body, method="PUT")
        with urllib.request.urlopen(req, timeout=2.0):
            pass
    except Exception as e:
        flight.record("serving_registry_mirror_failed", str(e))


def serve_replica(argv=None) -> int:
    """``python -m horovod_tpu.serving.replica_set --checkpoint ...``:
    restore, AOT-warm the buckets, serve until SIGTERM drains us."""
    ap = argparse.ArgumentParser(
        description="horovod_tpu serving replica / front door")
    ap.add_argument("--checkpoint", default="",
                    help="orbax checkpoint dir (save_model/save_params)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--register", default="",
                    help="host:port of the ComputeService registry")
    ap.add_argument("--buckets", default="",
                    help="override HOROVOD_SERVING_BUCKETS")
    ap.add_argument("--decode", action="store_true",
                    help="serve autoregressive generation "
                         "(/v1/generate) from a transformer_lm "
                         "checkpoint instead of one-shot predict "
                         "(docs/generation.md)")
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-limit", type=int, default=None)
    ap.add_argument("--no-warmup", action="store_true",
                    help="compile buckets lazily on first use")
    ap.add_argument("--drain-timeout", type=float, default=30.0)
    ap.add_argument("--front-door", action="store_true",
                    help="serve as the dispatch tier instead of a "
                         "replica (needs --register + --wait-replicas "
                         "or --replicas)")
    ap.add_argument("--registry", action="store_true",
                    help="run the standalone ComputeService registry "
                         "replicas/front door --register against "
                         "(binds --port)")
    ap.add_argument("--wait-replicas", type=int, default=0,
                    help="front door: replicas to wait for in the "
                         "registry before serving")
    ap.add_argument("--wait-timeout", type=float, default=300.0,
                    help="front door: seconds to wait for "
                         "--wait-replicas registrations (replicas "
                         "register only after checkpoint restore + "
                         "bucket AOT warmup)")
    ap.add_argument("--replicas", default="",
                    help="front door: comma list of host:port "
                         "(skips the registry)")
    args = ap.parse_args(argv)

    metrics.enable()  # serving is an observability-first workload
    faults.configure()  # arm HOROVOD_TPU_FAULT_SPEC if the env set one
    key = _secret_or_none()

    if args.registry:
        from ..runner.compute_service import ComputeService

        if key is None:
            raise RuntimeError("--registry needs HVD_TPU_SECRET_KEY")
        svc = ComputeService(key, port=args.port)
        print(f"SERVING_REGISTRY_READY index=0 port={svc.port}",
              flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            svc.shutdown()
            return 0

    # a front door pools ONE capability: --decode selects the
    # serving-decode registry kind and mounts /v1/generate; otherwise
    # the predict kind and /v1/predict. Mixed fleets run one front
    # door per capability — pooling both would least-loaded-route
    # generates onto predict replicas, whose 404 is terminal.
    fleet_kind = SERVING_DECODE_KIND if args.decode else SERVING_KIND
    batcher = None
    if args.front_door:
        if args.replicas:
            replicas = {i: a for i, a in
                        enumerate(args.replicas.split(","))}
        elif args.register and args.wait_replicas:
            from ..runner.compute_service import ComputeClient

            host, _, p = args.register.rpartition(":")
            if key is None:
                raise RuntimeError("--register needs HVD_TPU_SECRET_KEY")
            client = ComputeClient([(host, int(p))], key)
            replicas = client.wait_for_workers(
                fleet_kind, args.wait_replicas,
                timeout_s=args.wait_timeout)
            if len(replicas) < args.wait_replicas:
                # the registry returns whatever registered on timeout;
                # silently serving at partial capacity despite
                # --wait-replicas N would hide a broken replica fleet
                raise RuntimeError(
                    f"only {len(replicas)}/{args.wait_replicas} "
                    f"{fleet_kind} replicas registered within "
                    f"{args.wait_timeout}s")
        else:
            raise RuntimeError(
                "front door needs --replicas or --register + "
                "--wait-replicas")
        rs = ReplicaSet(replicas, key=key)
        server = ServingServer(
            predict_fn=None if args.decode else rs.predict,
            generate_fn=rs.generate if args.decode else None,
            port=args.port, key=key,
            health_extra=lambda: {"replicas": rs.replicas,
                                  "dead": rs.dead})
        role = "front-door"
    elif args.decode:
        from .decode import GenerationEngine
        from .scheduler import DecodeScheduler

        if not args.checkpoint:
            ap.error("--checkpoint is required for a replica")
        engine = GenerationEngine.from_checkpoint(args.checkpoint)
        if not args.no_warmup:
            engine.warmup()
        scheduler = DecodeScheduler(
            engine, queue_limit=args.queue_limit).start()
        batcher = scheduler  # close(drain=) shares the batcher contract

        def generate_local(req, timeout_s, _s=scheduler):
            pending = _s.submit(
                req["prompt"],
                max_new_tokens=req.get("max_new_tokens"),
                timeout_s=timeout_s,
                slo=req.get("slo", "standard"))
            return pending.stream(
                timeout_s=(timeout_s
                           or _s._default_timeout_s) + 5.0)

        server = ServingServer(
            generate_fn=generate_local, port=args.port, key=key,
            # probe body: the slots triple is what lets probes (and
            # the autoscaler) tell "full" from "wedged"
            health_extra=lambda: {
                "slots": scheduler.slot_stats(),
                "queued": scheduler.pending,
                "bucket_cache": engine.cached_executables,
            },
        )
        role = "replica"
    else:
        from .batcher import DynamicBatcher
        from .engine import InferenceEngine, SERVING_META_KEY, parse_buckets

        if not args.checkpoint:
            ap.error("--checkpoint is required for a replica")
        engine = InferenceEngine.from_checkpoint(
            args.checkpoint,
            buckets=parse_buckets(args.buckets) if args.buckets else None,
        )
        meta = getattr(engine, "metadata", {}).get(SERVING_META_KEY, {})
        if not args.no_warmup and meta.get("input_shape"):
            engine.warmup(tuple(meta["input_shape"]),
                          meta.get("dtype", "float32"))
        batcher = DynamicBatcher(
            engine, max_batch=engine.buckets[-1],
            max_wait_ms=args.max_wait_ms, queue_limit=args.queue_limit,
        ).start()
        server = ServingServer(
            batcher.__call__, port=args.port, key=key,
            # probe body: queue depth + bucket-cache size (in-flight
            # count comes from ServingServer.health itself) — enough
            # for a probe to tell "idle" from "wedged" without auth
            health_extra=lambda: {
                "buckets": list(engine.buckets),
                "queued": batcher.pending,
                "bucket_cache": engine.cached_executables,
            },
        )
        role = "replica"

    port = server.start()
    if args.register and not args.front_door:
        _register(args.register, args.index, port, key,
                  kind=fleet_kind)
    _install_drain_handler(server, batcher, args.drain_timeout)
    print(f"SERVING_{role.upper().replace('-', '_')}_READY "
          f"index={args.index} port={port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(serve_replica() or 0)
