"""Multi-replica data-parallel dispatch for the serving tier.

One replica = one process owning its accelerator(s), running
engine + batcher behind the HTTP front end (server.py). This module is
everything around them:

* **registration** — replicas announce ``(kind="serving", index,
  host:port)`` through the launcher's authenticated registry
  (``runner/compute_service.py``), exactly as data-service compute
  workers do; the front door waits for N replicas the same way
  trainers wait for data workers;
* **routing** — :class:`ReplicaSet` tracks per-replica in-flight
  counts locally and routes each request to the least-loaded live
  replica;
* **failover** — a replica that dies mid-request (connection error or
  5xx) is ejected and the request retried on another replica under the
  shared :class:`~horovod_tpu.utils.retry.RetryPolicy`
  (``serving.dispatch`` retry point) — the client never sees the
  death. When every replica is ejected the set forgives them all once
  and re-probes, so a restarted replica rejoins without a control
  plane round-trip;
* **drain-then-exit** — ``python -m horovod_tpu.serving.replica_set``
  installs the preemption handler (elastic/preemption.py): SIGTERM
  stops admission, flushes the batcher and in-flight HTTP requests,
  then exits with ``PREEMPTED_EXIT_CODE`` (83) so the launcher knows
  the host went away healthy.

Fault points: ``serving.dispatch`` fires before every routed attempt
(front door), ``serving.replica_exec`` before every executed batch
(replica, engine.py) — see docs/faults.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils import faults, flight, metrics, retry
from . import tracing
from .batcher import RequestTimeout
from .server import AUTH_HEADER, REQUEST_ID_HEADER, ServingServer, sign_body

SERVING_KIND = "serving"


def _build_body(x: np.ndarray,
                timeout_s: Optional[float] = None) -> bytes:
    """Serialize one predict request ONCE — the dispatch tier reuses
    these bytes across failover attempts instead of re-running
    tolist/dumps/HMAC on every retry."""
    x = np.asarray(x)
    body_obj = {"inputs": x.tolist(), "dtype": str(x.dtype)}
    if timeout_s:
        body_obj["timeout_ms"] = int(timeout_s * 1e3)
    return json.dumps(body_obj).encode()


def _post_body(addr: str, body: bytes, sock_timeout: float,
               key: Optional[bytes] = None,
               request_id: str = "") -> np.ndarray:
    req = urllib.request.Request(
        f"http://{addr}/v1/predict", data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    if key is not None:
        req.add_header(AUTH_HEADER, sign_body(key, body))
    if request_id:
        # the front door's trace id travels to the replica, so both
        # tiers' flight/timeline events name the SAME request
        req.add_header(REQUEST_ID_HEADER, request_id)
    with urllib.request.urlopen(req, timeout=sock_timeout) as resp:
        payload = json.loads(resp.read())
    return np.asarray(payload["outputs"],
                      dtype=np.dtype(payload.get("dtype", "float32")))


def predict_remote(
    addr: str,
    x: np.ndarray,
    timeout_s: Optional[float] = None,
    key: Optional[bytes] = None,
) -> np.ndarray:
    """One POST /v1/predict against ``host:port`` (no retries — that's
    the ReplicaSet's job). Raises urllib.error.HTTPError / OSError."""
    return _post_body(addr, _build_body(x, timeout_s),
                      (timeout_s or 30.0) + 5.0, key=key)


def _dispatch_retryable(exc: BaseException) -> bool:
    """5xx (replica dying/draining) and 429 (that replica saturated)
    retry on another replica; other HTTP codes are client errors and
    propagate. Transport failures retry — except the dispatch tier's
    own deadline marker, which says the request budget is SPENT."""
    if isinstance(exc, RequestTimeout):
        return False
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code == 429 or (exc.code >= 500 and exc.code != 504)
    return isinstance(exc, (OSError, EOFError))


def _ejects_replica(exc: BaseException) -> bool:
    """Failures that mean the REPLICA is gone (eject it from dispatch),
    vs merely busy. A 429 is backpressure from a healthy replica — the
    request retries elsewhere but the replica stays in rotation;
    ejecting it would durably cut capacity exactly when load is
    highest."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 and exc.code != 504
    return isinstance(exc, (OSError, EOFError))


class ReplicaSet:
    """Least-loaded dispatch with transparent failover.

    ``replicas`` maps index -> "host:port" (usually the
    ComputeService's WorkersResponse). Thread-safe: the front end calls
    ``predict`` from concurrent request threads.
    """

    def __init__(
        self,
        replicas: Dict[int, str],
        *,
        key: Optional[bytes] = None,
        policy: Optional[retry.RetryPolicy] = None,
        default_timeout_s: float = 30.0,
    ):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self._replicas = dict(replicas)
        self._key = key
        # failover must outlast losing every replica but one: give the
        # policy enough attempts to walk the whole set and then some
        self._policy = policy or retry.RetryPolicy(
            max_attempts=max(len(replicas) + 2, 4),
            base_delay_s=0.05, max_delay_s=0.5,
        )
        self._default_timeout_s = default_timeout_s
        self._lock = threading.Lock()
        self._inflight: Dict[int, int] = {i: 0 for i in replicas}
        self._dead: Dict[int, str] = {}
        self._rr = 0

    # -- bookkeeping --------------------------------------------------------

    @property
    def replicas(self) -> Dict[int, str]:
        return dict(self._replicas)

    @property
    def dead(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._dead)

    def _pick(self) -> Tuple[int, str]:
        with self._lock:
            live = [i for i in self._replicas if i not in self._dead]
            if not live:
                # total eclipse: forgive everyone once instead of
                # locking the front door shut — a restarted replica
                # answers, a still-dead one re-ejects on its next miss
                self._dead.clear()
                live = list(self._replicas)
            self._rr += 1
            idx = min(live, key=lambda i: (self._inflight[i],
                                           (i + self._rr) % len(live)))
            self._inflight[idx] += 1
            n = self._inflight[idx]
        metrics.set_serving_inflight(n, replica=str(idx))
        return idx, self._replicas[idx]

    def _release(self, idx: int) -> None:
        with self._lock:
            self._inflight[idx] -= 1
            n = self._inflight[idx]
        metrics.set_serving_inflight(n, replica=str(idx))

    def _mark_dead(self, idx: int, why: BaseException) -> None:
        with self._lock:
            already = idx in self._dead
            self._dead[idx] = f"{type(why).__name__}: {why}"
        if not already:
            metrics.record_serving_failover(str(idx))

    def revive(self, idx: Optional[int] = None) -> None:
        """Forgive one replica (or all) — e.g. after an external
        health check saw it come back."""
        with self._lock:
            if idx is None:
                self._dead.clear()
            else:
                self._dead.pop(idx, None)

    # -- dispatch -----------------------------------------------------------

    def predict(self, x: np.ndarray,
                timeout_s: Optional[float] = None) -> np.ndarray:
        """Route one request; retried across replicas on failure so a
        replica death is invisible to the caller."""
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        # serialize once; every failover attempt reuses the bytes
        body = _build_body(x, timeout_s)
        deadline = retry.Deadline(timeout_s)
        rid = tracing.current_request_id()

        def _attempt() -> np.ndarray:
            if deadline.expired():
                # stop the retry ladder once the request's own budget
                # is gone — more attempts only delay the 504 the
                # client has already paid for
                raise RequestTimeout(
                    f"request budget {timeout_s}s exhausted during "
                    f"dispatch/failover")
            idx, addr = self._pick()
            flight.record("serving_dispatch", str(idx),
                          n=int(x.shape[0]), req=rid)
            try:
                faults.inject("serving.dispatch", replica=idx)
                remaining = max(deadline.remaining(), 0.5)
                # a replica that accepts the connection but never
                # answers must not swallow the whole request budget:
                # with peers available, cap each attempt at half the
                # remaining deadline so the socket timeout leaves room
                # for at least one failover
                att = (remaining / 2.0 if len(self._replicas) > 1
                       else remaining)
                att = max(att, 0.5)
                return _post_body(addr, body, att + 1.0, key=self._key,
                                  request_id=rid)
            except BaseException as e:
                if _ejects_replica(e):
                    self._mark_dead(idx, e)
                    flight.record("serving_failover", str(idx),
                                  error=str(e)[:120])
                raise
            finally:
                self._release(idx)

        return self._policy.call(
            _attempt, point="serving.dispatch",
            retryable=_dispatch_retryable,
        )

    def __call__(self, x: np.ndarray,
                 timeout_s: Optional[float] = None) -> np.ndarray:
        return self.predict(x, timeout_s)


# ---------------------------------------------------------------------------
# process entry points: one replica, or the front door
# ---------------------------------------------------------------------------

def _secret_or_none() -> Optional[bytes]:
    from ..runner.util import secret

    try:
        return secret.secret_from_env()
    except RuntimeError:
        return None


def _install_drain_handler(server: ServingServer, batcher,
                           drain_timeout_s: float) -> None:
    from ..elastic import preemption

    def _drain():
        server.draining = True          # stop admission first
        if batcher is not None:
            batcher.close(drain=True, timeout_s=drain_timeout_s)
        server.drain(timeout_s=drain_timeout_s)
        # settle: in-flight handlers decrement before their response
        # write; give those last writes a beat before os._exit
        time.sleep(0.25)

    preemption.install(on_preempt=_drain)


def _register(register: str, index: int, port: int,
              key: Optional[bytes]) -> None:
    from ..runner.compute_service import ComputeClient
    from ..runner.util.network import routable_host_address

    if key is None:
        raise RuntimeError(
            "--register needs the per-job secret in the environment "
            "(HVD_TPU_SECRET_KEY) — the registry authenticates")
    host, _, p = register.rpartition(":")
    client = ComputeClient([(host, int(p))], key)
    client.register_worker(
        SERVING_KIND, index, f"{routable_host_address()}:{port}")


def serve_replica(argv=None) -> int:
    """``python -m horovod_tpu.serving.replica_set --checkpoint ...``:
    restore, AOT-warm the buckets, serve until SIGTERM drains us."""
    ap = argparse.ArgumentParser(
        description="horovod_tpu serving replica / front door")
    ap.add_argument("--checkpoint", default="",
                    help="orbax checkpoint dir (save_model/save_params)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--register", default="",
                    help="host:port of the ComputeService registry")
    ap.add_argument("--buckets", default="",
                    help="override HOROVOD_SERVING_BUCKETS")
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-limit", type=int, default=None)
    ap.add_argument("--no-warmup", action="store_true",
                    help="compile buckets lazily on first use")
    ap.add_argument("--drain-timeout", type=float, default=30.0)
    ap.add_argument("--front-door", action="store_true",
                    help="serve as the dispatch tier instead of a "
                         "replica (needs --register + --wait-replicas "
                         "or --replicas)")
    ap.add_argument("--registry", action="store_true",
                    help="run the standalone ComputeService registry "
                         "replicas/front door --register against "
                         "(binds --port)")
    ap.add_argument("--wait-replicas", type=int, default=0,
                    help="front door: replicas to wait for in the "
                         "registry before serving")
    ap.add_argument("--wait-timeout", type=float, default=300.0,
                    help="front door: seconds to wait for "
                         "--wait-replicas registrations (replicas "
                         "register only after checkpoint restore + "
                         "bucket AOT warmup)")
    ap.add_argument("--replicas", default="",
                    help="front door: comma list of host:port "
                         "(skips the registry)")
    args = ap.parse_args(argv)

    metrics.enable()  # serving is an observability-first workload
    faults.configure()  # arm HOROVOD_TPU_FAULT_SPEC if the env set one
    key = _secret_or_none()

    if args.registry:
        from ..runner.compute_service import ComputeService

        if key is None:
            raise RuntimeError("--registry needs HVD_TPU_SECRET_KEY")
        svc = ComputeService(key, port=args.port)
        print(f"SERVING_REGISTRY_READY index=0 port={svc.port}",
              flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            svc.shutdown()
            return 0

    batcher = None
    if args.front_door:
        if args.replicas:
            replicas = {i: a for i, a in
                        enumerate(args.replicas.split(","))}
        elif args.register and args.wait_replicas:
            from ..runner.compute_service import ComputeClient

            host, _, p = args.register.rpartition(":")
            if key is None:
                raise RuntimeError("--register needs HVD_TPU_SECRET_KEY")
            client = ComputeClient([(host, int(p))], key)
            replicas = client.wait_for_workers(
                SERVING_KIND, args.wait_replicas,
                timeout_s=args.wait_timeout)
            if len(replicas) < args.wait_replicas:
                # the registry returns whatever registered on timeout;
                # silently serving at partial capacity despite
                # --wait-replicas N would hide a broken replica fleet
                raise RuntimeError(
                    f"only {len(replicas)}/{args.wait_replicas} "
                    f"serving replicas registered within "
                    f"{args.wait_timeout}s")
        else:
            raise RuntimeError(
                "front door needs --replicas or --register + "
                "--wait-replicas")
        rs = ReplicaSet(replicas, key=key)
        server = ServingServer(
            rs.predict, port=args.port, key=key,
            health_extra=lambda: {"replicas": rs.replicas,
                                  "dead": rs.dead})
        role = "front-door"
    else:
        from .batcher import DynamicBatcher
        from .engine import InferenceEngine, SERVING_META_KEY, parse_buckets

        if not args.checkpoint:
            ap.error("--checkpoint is required for a replica")
        engine = InferenceEngine.from_checkpoint(
            args.checkpoint,
            buckets=parse_buckets(args.buckets) if args.buckets else None,
        )
        meta = getattr(engine, "metadata", {}).get(SERVING_META_KEY, {})
        if not args.no_warmup and meta.get("input_shape"):
            engine.warmup(tuple(meta["input_shape"]),
                          meta.get("dtype", "float32"))
        batcher = DynamicBatcher(
            engine, max_batch=engine.buckets[-1],
            max_wait_ms=args.max_wait_ms, queue_limit=args.queue_limit,
        ).start()
        server = ServingServer(
            batcher.__call__, port=args.port, key=key,
            # probe body: queue depth + bucket-cache size (in-flight
            # count comes from ServingServer.health itself) — enough
            # for a probe to tell "idle" from "wedged" without auth
            health_extra=lambda: {
                "buckets": list(engine.buckets),
                "queued": batcher.pending,
                "bucket_cache": engine.cached_executables,
            },
        )
        role = "replica"

    port = server.start()
    if args.register and not args.front_door:
        _register(args.register, args.index, port, key)
    _install_drain_handler(server, batcher, args.drain_timeout)
    print(f"SERVING_{role.upper().replace('-', '_')}_READY "
          f"index={args.index} port={port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(serve_replica() or 0)
