"""Autoregressive generation engine: AOT prefill/decode + slotted KV cache.

The one-shot serving engine (engine.py) answers ``predict`` — one
forward pass per request. The dominant production LM workload is
*decode*: one forward pass per generated TOKEN, hundreds per request,
with all the state between passes living in the KV cache. This module
is the mechanism layer for that workload (the policy layer — which
sequence runs when — is serving/scheduler.py):

* **Slotted KV cache** (:class:`SlottedKVCache`): one pair of
  ``(slots, layers, kv_heads, max_len, head_dim)`` buffers. A *slot* is
  a resident sequence's cache lane; slots are claimed at prefill,
  written in place every decode iteration, and recycled the moment a
  sequence finishes — no copy, no restart of co-resident sequences.
  Rows above a slot's current length hold the previous occupant's
  stale bytes; the attention validity mask (``position <= query
  position``) makes them unreachable, so recycling is free.
* **int8 block-quantized cache** (``HOROVOD_SERVING_KV_DTYPE=int8``):
  K/V rows are quantized with the same per-block symmetric int8
  primitives the collective wire uses (optim/compression.py
  ``quantize_blocks``/``dequantize_blocks``, docs/compression.md).
  Rows are quantized ONCE, on write; decode iterations dequantize for
  the attention read but never re-quantize old rows, so there is no
  step-over-step error accumulation — the cache holds exactly the
  codes written at append time (the error-feedback question the wire
  path has does not arise). ~4x cache HBM at a documented tolerance
  (docs/generation.md).
* **AOT executables**: like engine.py's batch-size buckets, programs
  are compiled up front and cached by shape — one *decode* program per
  ``(slots, max_len)`` bucket (one token for every slot per call) and
  one *prefill* program per prompt-length bucket (whole prompt through
  the model, K/V inserted into the claimed slot, first token emitted).
  ``HOROVOD_SERVING_DECODE_BUCKETS`` ("4x128,8x256") names the
  slot/len ladder; prefill lengths default to powers of two up to
  max_len.

The model side is ``models/transformer.py``'s ``kv_cache`` apply path:
this module owns the cache layout and quantization, the model stays a
pure function of (params, tokens, positions, cache).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import faults, metrics
from .engine import serving_knobs

KV_DTYPES = ("fp32", "bf16", "int8")


def parse_kv_dtype(name: Optional[str] = None) -> str:
    """``HOROVOD_SERVING_KV_DTYPE`` -> one of :data:`KV_DTYPES`."""
    if name is None:
        name = getattr(serving_knobs(), "serving_kv_dtype", "") or "fp32"
    name = str(name).strip().lower()
    aliases = {"float32": "fp32", "f32": "fp32", "bfloat16": "bf16",
               "": "fp32"}
    name = aliases.get(name, name)
    if name not in KV_DTYPES:
        raise ValueError(
            f"unknown KV cache dtype {name!r}; expected one of "
            f"{KV_DTYPES} (HOROVOD_SERVING_KV_DTYPE)")
    return name


def parse_decode_buckets(
        spec: Optional[str] = None) -> Tuple[Tuple[int, int], ...]:
    """``HOROVOD_SERVING_DECODE_BUCKETS`` ("4x128,8x256") -> sorted
    unique ``(slots, max_len)`` pairs."""
    if spec is None:
        spec = (getattr(serving_knobs(), "serving_decode_buckets", "")
                or "4x128")
    out = set()
    for part in str(spec).replace(";", ",").split(","):
        part = part.strip().lower()
        if not part:
            continue
        s, _, m = part.partition("x")
        try:
            pair = (int(s), int(m))
        except ValueError:
            raise ValueError(
                f"invalid decode bucket {part!r} in {spec!r}; expected "
                "SLOTSxMAXLEN, e.g. 4x128")
        if pair[0] < 1 or pair[1] < 2:
            raise ValueError(f"invalid decode bucket {part!r} in {spec!r}")
        out.add(pair)
    if not out:
        raise ValueError(f"empty decode bucket spec {spec!r}")
    return tuple(sorted(out))


def default_prefill_buckets(max_len: int) -> Tuple[int, ...]:
    """Power-of-two prompt-length ladder up to ``max_len`` (engine.py's
    bucket idea applied to sequence length)."""
    out = []
    b = 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(sorted(set(out)))


# ---------------------------------------------------------------------------
# slotted KV cache
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static shape/dtype contract of one slotted cache: buffers are
    ``(slots, layers, kv_heads, max_len, head_dim)``; ``dtype`` in
    {fp32, bf16, int8}; ``block`` the int8 quantization granularity
    along head_dim (0 = one scale per row, i.e. block = head_dim)."""

    slots: int
    layers: int
    kv_heads: int
    max_len: int
    head_dim: int
    dtype: str = "fp32"
    block: int = 0
    compute_dtype: Any = None  # jnp dtype the model computes in

    @property
    def resolved_block(self) -> int:
        b = int(self.block) if self.block else self.head_dim
        if b <= 0 or self.head_dim % b:
            # a block that does not divide head_dim cannot tile the
            # row; fall back to per-row scales rather than mis-scale
            b = self.head_dim
        return b

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.slots, self.layers, self.kv_heads, self.max_len,
                self.head_dim)

    @property
    def scale_shape(self) -> Tuple[int, ...]:
        return (self.slots, self.layers, self.kv_heads, self.max_len,
                self.head_dim // self.resolved_block)

    def buffer_structs(self) -> Dict[str, Any]:
        """jax.ShapeDtypeStruct per buffer — the AOT lowering inputs."""
        import jax
        import jax.numpy as jnp

        if self.dtype == "int8":
            return {
                "k": jax.ShapeDtypeStruct(self.shape, jnp.int8),
                "v": jax.ShapeDtypeStruct(self.shape, jnp.int8),
                "k_scale": jax.ShapeDtypeStruct(self.scale_shape,
                                                jnp.float32),
                "v_scale": jax.ShapeDtypeStruct(self.scale_shape,
                                                jnp.float32),
            }
        dt = jnp.bfloat16 if self.dtype == "bf16" else jnp.float32
        return {"k": jax.ShapeDtypeStruct(self.shape, dt),
                "v": jax.ShapeDtypeStruct(self.shape, dt)}

    def allocate(self) -> Dict[str, Any]:
        """Zero-initialized device buffers (stale rows are masked, so
        zeros are merely a defined starting point)."""
        import jax.numpy as jnp

        return {name: jnp.zeros(s.shape, s.dtype)
                for name, s in self.buffer_structs().items()}

    def nbytes(self) -> int:
        return sum(int(np.prod(s.shape)) * np.dtype(s.dtype).itemsize
                   for s in self.buffer_structs().values())


def _quantize_rows(x, block: int):
    """Per-block symmetric int8 quantization along the LAST axis of
    ``x`` (block divides it): the cache-row application of
    optim/compression.quantize_blocks. Returns (codes int8 same shape,
    scales f32 with last axis D/block)."""
    from ..optim.compression import quantize_blocks

    q, s = quantize_blocks(x.astype("float32").reshape(-1), block)
    return (q.reshape(x.shape),
            s.reshape(x.shape[:-1] + (x.shape[-1] // block,)))


def _dequantize_rows(q, s, block: int):
    """Inverse of :func:`_quantize_rows` (float32)."""
    import jax.numpy as jnp

    qf = q.astype(jnp.float32)
    shaped = qf.reshape(q.shape[:-1] + (q.shape[-1] // block, block))
    out = shaped * s.astype(jnp.float32)[..., None]
    return out.reshape(q.shape)


class SlottedKVCache:
    """Traced cache carrier for the model's ``kv_cache`` apply path.

    Constructed INSIDE a jitted function around the buffer arguments;
    ``update`` rebinds the buffers functionally (single-pass tracing
    makes attribute rebinding safe) and the caller returns
    ``cache.buffers`` as outputs, closing the loop.
    """

    def __init__(self, spec: KVCacheSpec, buffers: Dict[str, Any]):
        self.spec = spec
        self.buffers = dict(buffers)

    def update(self, layer: int, k_new, v_new, positions):
        """Append ``k_new``/``v_new`` ``[B, T, KH, D]`` at absolute
        ``positions`` ``[B, T]`` in layer ``layer``'s slice, returning
        ``(k_full, v_full, valid)``: the whole dequantized layer slice
        ``[B, KH, M, D]`` in the compute dtype and the position
        validity mask ``[B, T, M]``.

        The write is a one-hot merge: positions >= max_len produce an
        all-zero one-hot row (a saturated slot writes nothing instead
        of corrupting row 0), and the merge arithmetic runs in f32 —
        int8 codes are integers <= 127, exactly representable, so the
        round-trip through the merge is bit-exact for untouched rows.
        """
        import jax
        import jax.numpy as jnp

        spec = self.spec
        M = spec.max_len
        oh = jax.nn.one_hot(positions, M, dtype=jnp.float32)  # [B,T,M]
        cov = jnp.clip(jnp.sum(oh, axis=1), 0.0, 1.0)         # [B,M]
        keep = (1.0 - cov)[:, None, :, None]                  # [B,1,M,1]
        compute_dtype = spec.compute_dtype or jnp.float32

        def merge(cache_slice, new_btkd):
            # [B,KH,M,*] * keep + one-hot-scattered new rows
            delta = jnp.einsum("btm,btkd->bkmd",
                               oh, new_btkd.astype(jnp.float32))
            return cache_slice.astype(jnp.float32) * keep + delta

        outs = []
        for name, new in (("k", k_new), ("v", v_new)):
            buf = self.buffers[name]
            layer_slice = buf[:, layer]  # [B,KH,M,D]
            if spec.dtype == "int8":
                block = spec.resolved_block
                codes, scales = _quantize_rows(new, block)  # [B,T,KH,*]
                merged_codes = jnp.round(
                    merge(layer_slice, codes)).astype(jnp.int8)
                sbuf = self.buffers[name + "_scale"]
                merged_scales = merge(sbuf[:, layer], scales)
                self.buffers[name] = buf.at[:, layer].set(merged_codes)
                self.buffers[name + "_scale"] = sbuf.at[:, layer].set(
                    merged_scales)
                full = _dequantize_rows(merged_codes, merged_scales,
                                        block)
            else:
                merged = merge(layer_slice, new).astype(buf.dtype)
                self.buffers[name] = buf.at[:, layer].set(merged)
                full = merged
            outs.append(full.astype(compute_dtype))
        m_idx = jnp.arange(M, dtype=positions.dtype)
        valid = m_idx[None, None, :] <= positions[:, :, None]  # [B,T,M]
        return outs[0], outs[1], valid

    def append_attend(self, layer: int, q, k_new, v_new, positions):
        """Fused append + attention (the model's kv_cache fast path,
        models/transformer.Attention): merge the new K/V rows into
        ``layer`` and return the attention output ``[B, T, H, D]`` in
        one step. With HOROVOD_FUSED_COLLECTIVES this runs the Pallas
        append+attend kernel (ops/pallas_collectives.py) — int8
        quantize-on-write, merge, dequantize and attention in one
        kernel per batch row; otherwise it is exactly
        :meth:`update` + ``cached_attention`` (unchanged lowering).
        Either way the buffers are rebound like :meth:`update`."""
        from ..ops import pallas_collectives as _pc

        return _pc.decode_append_attend(self, layer, q, k_new, v_new,
                                        positions)


# ---------------------------------------------------------------------------
# checkpoint metadata <-> TransformerConfig
# ---------------------------------------------------------------------------

#: serving-metadata model name for a generation-capable transformer LM
TRANSFORMER_LM = "transformer_lm"

_CFG_DTYPES = {"float32": "float32", "fp32": "float32",
               "bfloat16": "bfloat16", "bf16": "bfloat16"}


def config_to_meta(cfg) -> Dict[str, Any]:
    """TransformerConfig -> a JSON-safe dict for checkpoint metadata
    (the generation twin of engine.py's mlp ``features`` block)."""
    import jax.numpy as jnp

    d = dataclasses.asdict(cfg)
    d["dtype"] = ("bfloat16" if cfg.dtype == jnp.bfloat16 else "float32")
    return d


def config_from_meta(d: Dict[str, Any]):
    """Inverse of :func:`config_to_meta`."""
    import jax.numpy as jnp

    from ..models.transformer import TransformerConfig

    d = dict(d)
    name = _CFG_DTYPES.get(str(d.get("dtype", "bfloat16")).lower(),
                           "bfloat16")
    d["dtype"] = jnp.bfloat16 if name == "bfloat16" else jnp.float32
    fields = {f.name for f in dataclasses.fields(TransformerConfig)}
    return TransformerConfig(**{k: v for k, v in d.items()
                                if k in fields})


# ---------------------------------------------------------------------------
# generation engine
# ---------------------------------------------------------------------------

class GenerationEngine:
    """AOT prefill + single-token greedy decode over a slotted cache.

    Mechanism only: ``claim_slot``/``release_slot`` hand out cache
    lanes, ``prefill`` runs a prompt into a claimed slot and returns
    the first generated token, ``decode`` advances EVERY slot one
    token (callers ignore outputs of inactive slots). The scheduler
    (serving/scheduler.py) owns which sequence occupies which slot and
    when; this class owns shapes, compilation and the cache.

    Thread-safety: one lock around execution (one accelerator per
    replica, same discipline as InferenceEngine); compilation has its
    own lock so a cold prefill bucket never stalls decode iterations.
    """

    MAX_CACHED_EXECUTABLES = 16

    def __init__(
        self,
        model,
        params: Any,
        *,
        slots: Optional[int] = None,
        max_len: Optional[int] = None,
        prefill_buckets: Optional[Sequence[int]] = None,
        kv_dtype: Optional[str] = None,
        kv_block: Optional[int] = None,
        eos_id: Optional[int] = None,
    ):
        import jax

        cfg = model.cfg
        if not cfg.causal:
            raise ValueError(
                "autoregressive generation needs a causal LM "
                "(TransformerConfig.causal=True)")
        if getattr(cfg, "remat", False):
            # remat exists to trade activation memory for backward
            # recompute; inference has no backward, and nn.remat
            # cannot abstractify the SlottedKVCache carrier — a
            # remat-trained checkpoint must still serve
            from ..models.transformer import Transformer

            cfg = dataclasses.replace(cfg, remat=False)
            model = Transformer(cfg,
                                attention_fn=model.attention_fn)
        sk = serving_knobs()
        if slots is None or max_len is None:
            # largest configured (slots, max_len) bucket: the decode
            # program every iteration runs; smaller buckets stay
            # available through the ladder spec for smaller replicas
            ladder = parse_decode_buckets()
            pick = ladder[-1]
            slots = slots if slots is not None else pick[0]
            max_len = max_len if max_len is not None else pick[1]
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"cache max_len {max_len} exceeds the model's "
                f"max_seq_len {cfg.max_seq_len} (rope/pos tables)")
        if kv_dtype is None:
            kv_dtype = parse_kv_dtype()
        if kv_block is None:
            kv_block = int(getattr(sk, "serving_kv_block", 0) or 0)
        self.model = model
        self.cfg = cfg
        self.eos_id = eos_id
        self.spec = KVCacheSpec(
            slots=int(slots), layers=cfg.num_layers,
            kv_heads=cfg.kv_heads, max_len=int(max_len),
            head_dim=cfg.head_dim, dtype=parse_kv_dtype(kv_dtype),
            block=int(kv_block), compute_dtype=cfg.dtype,
        )
        self._params = jax.device_put(params)
        self._cache = self.spec.allocate()
        if prefill_buckets is None:
            knob = getattr(sk, "serving_prefill_buckets", "") or ""
            prefill_buckets = ([int(b) for b in
                                knob.replace(";", ",").split(",")
                                if b.strip()] if knob
                               else default_prefill_buckets(
                                   self.spec.max_len))
        self._prefill_buckets = tuple(sorted(set(
            int(b) for b in prefill_buckets
            if int(b) <= self.spec.max_len)))
        if not self._prefill_buckets:
            raise ValueError("no prefill bucket fits under max_len")
        self._exe: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._compile_lock = threading.Lock()
        self._free = list(range(self.spec.slots))
        self._slot_lock = threading.Lock()

    # -- construction from a checkpoint -------------------------------------

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "GenerationEngine":
        """Restore a generation-capable LM checkpoint: metadata
        ``{"serving": {"model": "transformer_lm", "config": {...},
        "eos": id}}`` (save side: :func:`config_to_meta`)."""
        from ..checkpoint import load_params
        from ..models.transformer import Transformer
        from .engine import SERVING_META_KEY

        params, metadata = load_params(path)
        meta = dict(metadata.get(SERVING_META_KEY, {}))
        if meta.get("model") != TRANSFORMER_LM:
            raise ValueError(
                f"checkpoint is not a generation LM (metadata model = "
                f"{meta.get('model')!r}; expected {TRANSFORMER_LM!r})")
        cfg = config_from_meta(meta.get("config", {}))
        kwargs.setdefault("eos_id", meta.get("eos"))
        eng = cls(Transformer(cfg), params, **kwargs)
        eng.metadata = metadata
        return eng

    # -- shape bookkeeping ---------------------------------------------------

    @property
    def slots(self) -> int:
        return self.spec.slots

    @property
    def max_len(self) -> int:
        return self.spec.max_len

    @property
    def prefill_buckets(self) -> Tuple[int, ...]:
        return self._prefill_buckets

    @property
    def cached_executables(self) -> int:
        return len(self._exe)

    @property
    def free_slots(self) -> int:
        with self._slot_lock:
            return len(self._free)

    def claim_slot(self) -> Optional[int]:
        """Take a free cache lane (None when full); the claim is just
        index bookkeeping — the lane's stale rows are masked until the
        prefill overwrites them."""
        with self._slot_lock:
            return self._free.pop(0) if self._free else None

    def release_slot(self, slot: int) -> None:
        with self._slot_lock:
            if slot in self._free:
                raise ValueError(f"slot {slot} already free")
            self._free.append(int(slot))
            self._free.sort()

    def prefill_bucket_for(self, n: int) -> int:
        for b in self._prefill_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"prompt of {n} tokens exceeds the top prefill bucket "
            f"{self._prefill_buckets[-1]} (cache max_len "
            f"{self.spec.max_len})")

    # -- compiled programs ---------------------------------------------------

    def _cache_structs(self):
        return self.spec.buffer_structs()

    def _executable(self, key: Tuple, build_fn):
        import jax

        with self._compile_lock:
            ex = self._exe.get(key)
            if ex is not None:
                self._exe.move_to_end(key)
                return ex
            t0 = time.perf_counter()
            fn, args = build_fn()
            # donate the cache buffers (arg 1 of both decode_fn and
            # prefill_fn): the caller rebinds self._cache to the
            # returned buffers and never reads the old ones, and
            # without donation every generated token would copy the
            # whole cache — the dominant HBM object here — doubling
            # its peak footprint. CPU has no donation (jax warns per
            # compile), so only the accelerator path asks for it.
            donate = ((1,) if jax.default_backend() != "cpu" else ())
            ex = jax.jit(fn, donate_argnums=donate).lower(
                *args).compile()
            self._exe[key] = ex
            while len(self._exe) > self.MAX_CACHED_EXECUTABLES:
                self._exe.popitem(last=False)
            metrics.record_serving_compile(
                key[1] if len(key) > 1 else self.spec.slots,
                time.perf_counter() - t0)
            return ex

    def _decode_exe(self, return_logits: bool = False):
        import jax
        import jax.numpy as jnp

        spec = self.spec

        def build():
            def decode_fn(params, buffers, tokens, lengths):
                cache = SlottedKVCache(spec, buffers)
                logits = self.model.apply(
                    {"params": params}, tokens[:, None],
                    positions=lengths[:, None], kv_cache=cache)
                last = logits[:, -1].astype(jnp.float32)
                nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                if return_logits:
                    return cache.buffers, nxt, last
                # steady-state program: the [slots, vocab] logits
                # never leave the device — at production vocab sizes
                # that copy would be ~1 MB of device→host traffic per
                # generated token on the hottest loop in the system
                return cache.buffers, nxt

            s = jax.ShapeDtypeStruct
            return decode_fn, (
                self._params, self._cache_structs(),
                s((spec.slots,), jnp.int32), s((spec.slots,), jnp.int32))

        return self._executable(
            ("decode", spec.slots, spec.max_len, bool(return_logits)),
            build)

    def _prefill_exe(self, bucket: int):
        import jax
        import jax.numpy as jnp

        spec = self.spec
        local_spec = dataclasses.replace(
            spec, slots=1, max_len=bucket, dtype="fp32")

        def build():
            def prefill_fn(params, buffers, tokens, slot, length):
                # the prompt runs through a LOCAL fp32 cache (M = the
                # prompt bucket) — prefill attention is exactly the
                # causal forward, expressed through the same cache
                # path — then the computed rows are converted to the
                # slotted cache's storage (cast, or int8-quantized
                # once) and inserted at the claimed slot
                local = SlottedKVCache(
                    local_spec,
                    {n: jnp.zeros(s.shape, s.dtype) for n, s in
                     local_spec.buffer_structs().items()})
                pos = jnp.arange(bucket, dtype=jnp.int32)[None]
                logits = self.model.apply(
                    {"params": params}, tokens, positions=pos,
                    kv_cache=local)
                last = jnp.take_along_axis(
                    logits.astype(jnp.float32),
                    (length - 1)[None, None, None].astype(jnp.int32)
                    .repeat(logits.shape[-1], axis=-1),
                    axis=1)[0, 0]
                first = jnp.argmax(last).astype(jnp.int32)
                out = dict(buffers)
                zeros5 = (slot.astype(jnp.int32), 0, 0, 0, 0)
                for name in ("k", "v"):
                    rows = local.buffers[name]  # [1,L,KH,T,D] f32
                    if spec.dtype == "int8":
                        block = spec.resolved_block
                        codes, scales = _quantize_rows(rows, block)
                        out[name] = jax.lax.dynamic_update_slice(
                            out[name], codes, zeros5)
                        out[name + "_scale"] = (
                            jax.lax.dynamic_update_slice(
                                out[name + "_scale"], scales, zeros5))
                    else:
                        out[name] = jax.lax.dynamic_update_slice(
                            out[name],
                            rows.astype(out[name].dtype), zeros5)
                return out, first, last

            s = jax.ShapeDtypeStruct
            return prefill_fn, (
                self._params, self._cache_structs(),
                s((1, bucket), jnp.int32), s((), jnp.int32),
                s((), jnp.int32))

        return self._executable(("prefill", bucket), build)

    def warmup(self) -> None:
        """AOT-compile the decode program and every prefill bucket so
        the first request of each shape pays no compile."""
        self._decode_exe()
        for b in self._prefill_buckets:
            self._prefill_exe(b)

    # -- execution -----------------------------------------------------------

    def prefill(self, slot: int, tokens: Sequence[int]) -> Tuple[int,
                                                                 np.ndarray]:
        """Run ``tokens`` into slot ``slot``; returns ``(first_token,
        last_logits)`` — the greedy continuation and its logits (the
        tolerance tests compare these across KV dtypes)."""
        import jax.numpy as jnp

        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if n < 1:
            raise ValueError("prefill needs at least one prompt token")
        if n >= self.spec.max_len:
            raise ValueError(
                f"prompt of {n} tokens leaves no room to generate "
                f"under max_len {self.spec.max_len}")
        bucket = self.prefill_bucket_for(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        ex = self._prefill_exe(bucket)
        t0 = time.perf_counter()
        with self._lock:
            faults.inject("serving.decode_prefill", bucket=bucket)
            self._cache, first, last = ex(
                self._params, self._cache, jnp.asarray(padded),
                jnp.int32(slot), jnp.int32(n))
        first = int(first)
        metrics.record_decode_prefill(bucket, time.perf_counter() - t0)
        return first, np.asarray(last)

    def decode(self, tokens: np.ndarray, lengths: np.ndarray,
               return_logits: bool = False,
               slos: Optional[Sequence[str]] = None,
               ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """One iteration: append ``tokens[i]`` at position
        ``lengths[i]`` in every slot i and return ``(next_tokens,
        last_logits)`` (``[slots]``, and ``[slots, vocab]`` only under
        ``return_logits`` — the steady-state program keeps logits on
        device; the flag exists for the tolerance tests). Inactive
        slots ride along (their outputs are ignored; pass length 0 so
        their write lands in a row the next prefill overwrites).

        ``slos`` names the SLO class of each LIVE sequence this
        iteration advances (the scheduler passes one entry per
        occupied slot): the iteration's wall time is then billed to
        each as its time-per-output-token
        (``hvd_serving_tpot_seconds{slo=...}``)."""
        import jax.numpy as jnp

        tokens = np.asarray(tokens, np.int32).reshape(self.spec.slots)
        lengths = np.asarray(lengths, np.int32).reshape(self.spec.slots)
        ex = self._decode_exe(return_logits)
        t0 = time.perf_counter()
        with self._lock:
            faults.inject("serving.decode_step")
            out = ex(self._params, self._cache, jnp.asarray(tokens),
                     jnp.asarray(lengths))
            if return_logits:
                self._cache, nxt, last = out
            else:
                self._cache, nxt = out
                last = None
        dt = time.perf_counter() - t0
        metrics.record_decode_iteration(int(self.spec.slots), dt)
        if slos:
            # every live sequence got exactly one token out of this
            # iteration, so the iteration's wall time IS each one's
            # per-output-token latency
            for slo in slos:
                metrics.record_serving_tpot(dt, slo=slo)
        return (np.asarray(nxt),
                np.asarray(last) if last is not None else None)

    def cache_nbytes(self) -> int:
        return self.spec.nbytes()
