"""TPU-native inference serving: the first non-training workload.

The training stack's spine — orbax checkpoints (checkpoint.py), mesh +
sharding rules (parallel/), the authenticated control plane (runner/),
live metrics (utils/metrics.py), fault injection (utils/faults.py) and
preemption-safe shutdown (elastic/preemption.py) — is exactly what a
serving tier needs; this package adds the one genuinely new piece
(dynamic batching over bucketed AOT executables) and composes the rest:

* :class:`~horovod_tpu.serving.engine.InferenceEngine` — checkpoint
  restore + padded batch-size buckets AOT-compiled per
  ``HOROVOD_SERVING_BUCKETS``, cached by (bucket, dtype);
* :class:`~horovod_tpu.serving.batcher.DynamicBatcher` — bounded
  admission, deadline-aware coalescing into the smallest covering
  bucket;
* :class:`~horovod_tpu.serving.server.ServingServer` — POST
  /v1/predict + /healthz + /metrics over the per-job shared secret;
* :class:`~horovod_tpu.serving.replica_set.ReplicaSet` — least-loaded
  multi-replica dispatch with transparent failover and SIGTERM
  drain-then-exit (exit code 83);
* :class:`~horovod_tpu.serving.decode.GenerationEngine` +
  :class:`~horovod_tpu.serving.scheduler.DecodeScheduler` — the
  autoregressive workload: AOT prefill/decode executables over a
  slotted (optionally int8 block-quantized) KV cache, continuously
  batched at iteration granularity with SLO-class admission and token
  streaming (docs/generation.md);
* :class:`~horovod_tpu.serving.replica_set.ReplicaAutoscaler` +
  :class:`~horovod_tpu.serving.replica_set.ReplicaSupervisor` —
  metrics-driven replica growth/drain over the preemption (exit 83)
  contract.

See docs/serving.md for architecture, knobs and the load-generator
recipe (scripts/serving_loadgen.py); docs/generation.md for the
decode path.
"""

from .batcher import (  # noqa: F401
    Draining,
    DynamicBatcher,
    QueueFull,
    RequestTimeout,
)
from .decode import (  # noqa: F401
    GenerationEngine,
    KVCacheSpec,
    SlottedKVCache,
    TRANSFORMER_LM,
    config_from_meta,
    config_to_meta,
    parse_decode_buckets,
    parse_kv_dtype,
)
from .engine import (  # noqa: F401
    InferenceEngine,
    SERVING_META_KEY,
    build_apply_fn,
    parse_buckets,
)
from .replica_set import (  # noqa: F401
    SERVING_DECODE_KIND,
    SERVING_KIND,
    ReplicaAutoscaler,
    ReplicaSet,
    ReplicaSupervisor,
    generate_remote,
    generate_stream_remote,
    predict_remote,
    serve_replica,
)
from .scheduler import (  # noqa: F401
    DecodeScheduler,
    GenRequest,
    SLO_CLASSES,
)
from .server import AUTH_HEADER, ServingServer, sign_body  # noqa: F401
