"""Deadline-aware dynamic batching behind a bounded admission queue.

The engine (engine.py) executes fixed-bucket batches; this module
manufactures them from a stream of small independent requests — the
serving analog of the training runtime's fusion cycle (one negotiation
window coalescing many tensors into one collective). A single worker
thread holds the first request of a window open for ``max_wait_ms`` of
co-arrivals, cuts the batch at ``max_batch`` examples, runs the model
once, and fans results back out to per-request futures.

Contract points:

* admission is **bounded** (``queue_limit`` pending examples) — beyond
  it ``submit`` raises :class:`QueueFull` immediately instead of
  building unbounded latency (the front end maps it to HTTP 429);
* every request carries a **deadline**; a request that expires while
  queued completes with :class:`RequestTimeout` and never wastes a
  bucket slot;
* ``close(drain=True)`` is the preemption path (elastic/preemption.py
  SIGTERM handler): admission stops (:class:`Draining`), the wait
  window collapses to zero, and every in-flight request flushes before
  the call returns — drain-then-exit, not drop-then-exit.

The ``serving.admit`` fault point fires inside ``submit`` so chaos
specs can reject admissions; queue wait and batch fill land in the
metrics registry (docs/metrics.md).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..utils import faults, flight, metrics
from ..utils.timeline import active_timeline
from . import tracing
from .engine import serving_knobs

SERVING_EXEC = "SERVING_EXEC"  # timeline activity around a batch run

# per-batcher timeline span key: two batchers in one process (in-process
# replicas, loopback tests) must not overwrite each other's open span
# in the shared Timeline table — same collision server.py's request
# span suffix guards against
_batcher_seq = itertools.count(1)


class QueueFull(RuntimeError):
    """Admission queue at capacity — shed load now, retry later."""


class Draining(RuntimeError):
    """The batcher is draining for shutdown; no new admissions."""


class RequestTimeout(TimeoutError):
    """The request's deadline expired before results arrived."""


class _Pending:
    __slots__ = ("x", "n", "enqueue_t", "deadline_t", "req_id",
                 "_event", "_result", "_error")

    def __init__(self, x: np.ndarray, enqueue_t: float,
                 deadline_t: Optional[float]):
        self.x = x
        self.n = x.shape[0]
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        # trace id bound by the HTTP handler (serving/tracing.py);
        # carried on the pending because the worker thread that
        # executes the batch runs outside the request's context
        self.req_id = tracing.current_request_id()
        self._event = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    # future surface ---------------------------------------------------------

    def set_result(self, y: np.ndarray) -> None:
        self._result = y
        self._event.set()

    def set_error(self, e: BaseException) -> None:
        self._error = e
        self._event.set()

    def result(self, timeout_s: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout_s):
            raise RequestTimeout(
                f"no result within {timeout_s}s (queue stuck?)")
        if self._error is not None:
            raise self._error
        return self._result


class DynamicBatcher:
    """Coalesce requests into covering batches for ``run_fn``.

    ``run_fn(x)`` gets a ``[n, ...]`` array with ``n <= max_batch`` and
    returns ``[n, ...]`` results in order (the engine pads to its
    bucket internally). ``clock``/``sleep`` are injectable for
    deterministic tests, same idiom as utils/retry.py.
    """

    def __init__(
        self,
        run_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 0,
        max_wait_ms: Optional[float] = None,
        queue_limit: Optional[int] = None,
        default_timeout_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        knobs = serving_knobs()
        self._run = run_fn
        self._max_batch = int(max_batch) or 64
        if max_wait_ms is None:
            max_wait_ms = knobs.serving_max_wait_ms
        self._max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self._queue_limit = (int(queue_limit) if queue_limit is not None
                             else int(knobs.serving_queue_limit))
        if default_timeout_s is None:
            default_timeout_s = knobs.serving_request_timeout_seconds
        self._default_timeout_s = float(default_timeout_s)
        self._clock = clock
        self._span_key = f"serving_batch#{next(_batcher_seq)}"
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_Pending] = []
        self._queued_examples = 0
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DynamicBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="hvd-serving-batcher")
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop admission; with ``drain`` flush everything already
        queued (the wait window collapses to zero once draining) before
        stopping the worker, else fail queued requests immediately."""
        with self._cv:
            self._draining = True
            if not drain:
                for p in self._queue:
                    p.set_error(Draining("batcher closed"))
                self._queue.clear()
                self._queued_examples = 0
            self._cv.notify_all()
        if self._thread is not None:
            # the worker flushes remaining batches back-to-back (the
            # draining flag skips the co-arrival wait) and exits once
            # the queue is empty
            self._thread.join(timeout=timeout_s)
            self._stopped = True

    @property
    def pending(self) -> int:
        with self._lock:
            return self._queued_examples

    # -- admission ----------------------------------------------------------

    def submit(self, x: np.ndarray,
               timeout_s: Optional[float] = None) -> _Pending:
        """Admit one request (``[n, ...]`` examples); returns its
        future. Raises :class:`QueueFull` / :class:`Draining` /
        :class:`~horovod_tpu.utils.faults.InjectedFault` synchronously."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"submit needs [n, ...] input, got {x.shape}")
        if x.shape[0] > self._queue_limit:
            # bigger than the queue can EVER hold: that's a client
            # error (reject permanently, 400), not backpressure — a
            # 429 would send the dispatch tier retrying a request that
            # can never succeed across every replica
            raise ValueError(
                f"request of {x.shape[0]} examples exceeds this "
                f"replica's admission capacity ({self._queue_limit}); "
                "split the batch client-side")
        faults.inject("serving.admit", n=x.shape[0])
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        now = self._clock()
        p = _Pending(x, now, now + timeout_s if timeout_s else None)
        with self._cv:
            if self._draining:
                raise Draining("serving replica is draining")
            if self._queued_examples + p.n > self._queue_limit:
                raise QueueFull(
                    f"admission queue at capacity "
                    f"({self._queued_examples}/{self._queue_limit} examples)")
            self._queue.append(p)
            self._queued_examples += p.n
            self._cv.notify_all()
        return p

    def __call__(self, x: np.ndarray,
                 timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit + wait for the result."""
        if timeout_s is None:
            timeout_s = self._default_timeout_s
        # the worker enforces the queue-side deadline; the +1s margin
        # covers result delivery so a stuck worker still unblocks us
        return self.submit(x, timeout_s).result(
            timeout_s + 1.0 if timeout_s else None)

    # -- worker -------------------------------------------------------------

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a batch is ready (first arrival + wait window /
        max_batch / drain); None once draining and empty."""
        with self._cv:
            while not self._queue:
                if self._draining:
                    return None
                self._cv.wait(0.1)
            first_t = self._clock()
            cutoff = first_t + self._max_wait_s
            while (self._queued_examples < self._max_batch
                   and not self._draining):
                remaining = cutoff - self._clock()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            # coalesce only shape/dtype-compatible requests: one
            # concatenated array feeds one executable, so a request
            # with a different example shape (or a dtype that would
            # silently upcast its batchmates) forms its OWN batch next
            # iteration instead of failing innocents or changing their
            # numerics
            head = self._queue[0]
            sig = (head.x.shape[1:], head.x.dtype)
            batch: List[_Pending] = [self._queue.pop(0)]
            total = head.n
            i = 0
            while i < len(self._queue):
                p = self._queue[i]
                if (p.x.shape[1:], p.x.dtype) != sig:
                    i += 1
                    continue
                if total + p.n > self._max_batch:
                    break
                batch.append(self._queue.pop(i))
                total += p.n
            self._queued_examples -= total
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            now = self._clock()
            live: List[_Pending] = []
            for p in batch:
                if p.deadline_t is not None and now > p.deadline_t:
                    if p.req_id:
                        flight.record("serving_timeout", p.req_id,
                                      queued_s=round(
                                          now - p.enqueue_t, 4))
                    p.set_error(RequestTimeout(
                        f"request expired after {now - p.enqueue_t:.3f}s "
                        "in the admission queue"))
                else:
                    metrics.record_serving_queue_wait(now - p.enqueue_t)
                    live.append(p)
            if not live:
                continue
            x = (live[0].x if len(live) == 1
                 else np.concatenate([p.x for p in live], axis=0))
            # batch-level trace: which request ids rode this executable
            # run — the hop that lets a slow /v1/predict be followed
            # from its SERVING_REQUEST span into the batch that served
            # it (docs/timeline.md). Assembly gated so the off state
            # stays one branch per batch.
            tl = active_timeline()
            ids = ([p.req_id for p in live if p.req_id]
                   if (tl is not None or flight.enabled()) else [])
            if ids:
                flight.record("serving_batch", ids[0],
                              ids=ids, n=int(x.shape[0]))
            if tl is not None:
                tl.activity_start(self._span_key, SERVING_EXEC,
                                  args={"ids": ids,
                                        "n": int(x.shape[0])})
            try:
                y = self._run(x)
            except BaseException as e:
                if ids:
                    flight.record("serving_batch_error", ids[0],
                                  ids=ids, error=str(e)[:120])
                for p in live:
                    p.set_error(e)
                continue
            finally:
                if tl is not None:
                    tl.activity_end(self._span_key, SERVING_EXEC)
            off = 0
            done_t = self._clock()
            for p in live:
                p.set_result(np.asarray(y)[off:off + p.n])
                off += p.n
                # one-shot predict: the whole answer IS the first
                # token, so TTFT = enqueue to result. Classless
                # requests bill to the default "standard" SLO class.
                metrics.record_serving_ttft(done_t - p.enqueue_t)
