"""Per-request trace ids for the serving tier.

Every ``POST /v1/predict`` carries one id end-to-end: accepted from the
client's ``X-Request-Id`` header (or minted at the front end), stamped
into the handler thread's context, picked up by the dynamic batcher at
admission, forwarded by the dispatch tier to the chosen replica, and
recorded into the timeline (``SERVING_REQUEST`` / ``SERVING_EXEC``
spans keyed by the id) and the flight recorder (``serving_request`` /
``serving_batch`` / ``serving_dispatch`` events). A slow or failed
request is then one grep — or one highlighted track in the merged
Perfetto trace (``scripts/trace_merge.py``, docs/timeline.md) — away
from the batch, replica and device window that served it.

Propagation is a ``contextvars.ContextVar``: the HTTP handler sets it
for the duration of the request, so everything on the synchronous call
path (batcher admission, replica dispatch) reads it without plumbing a
parameter through every signature; the batcher's worker thread runs
outside that context and therefore carries the id on the pending
request object instead.
"""

from __future__ import annotations

import contextvars
import re
import uuid

REQUEST_ID_HEADER = "X-Request-Id"

_request_id: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "hvd_serving_request_id", default="")

_UNSAFE = re.compile(r"[^A-Za-z0-9._:\-]")
_MAX_LEN = 64


def new_request_id() -> str:
    return uuid.uuid4().hex[:12]


def sanitize(rid: str) -> str:
    """A usable id from a client-supplied header value: length-bounded,
    shell/json/label-safe charset; empty or all-unsafe input gets a
    fresh id (a client must not be able to blank out tracing)."""
    rid = _UNSAFE.sub("", (rid or "").strip()[:_MAX_LEN])
    return rid or new_request_id()


def set_request_id(rid: str):
    """Bind the id to the current context; returns the reset token."""
    return _request_id.set(rid)


def reset_request_id(token) -> None:
    _request_id.reset(token)


def current_request_id() -> str:
    """The id bound to this context ('' outside a traced request)."""
    return _request_id.get()
