"""Inference engine: checkpoint restore + bucketed AOT execution.

The training side already owns everything an inference tier needs
except one piece: a way to run *variable-size* request batches through
*fixed-shape* compiled programs. XLA recompiles on every new input
shape, and a recompile mid-request is a multi-second latency cliff, so
the engine AOT-compiles a small ladder of padded batch-size buckets up
front (``HOROVOD_SERVING_BUCKETS``, default ``1,4,16,64`` — the same
pad-to-bucket idea the fusion planner applies to gradient tensors) and
serves every request from the smallest covering bucket. Executables are
cached by ``(bucket, input dtype)``; parameters come back from the
orbax checkpoint layer (``checkpoint.load_params``) and are placed per
the ``parallel/`` sharding rules when a mesh is given.

The ``serving.replica_exec`` fault point fires before every executed
batch, so the chaos tooling (utils/faults.py) can kill or error a
replica mid-request and prove the dispatch tier's retry path works
(tests/test_serving.py, docs/faults.md).
"""

from __future__ import annotations

import importlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils import faults, metrics

#: metadata key ``save_model``/``save_params`` users set so a replica
#: process can rebuild the apply_fn from the checkpoint alone
#: (see :func:`build_apply_fn`).
SERVING_META_KEY = "serving"


def serving_knobs():
    """The serving_* knob source: the live global Knobs when
    ``hvd.init()`` ran in this process (so programmatic
    ``Knobs(serving_...=...)`` works like every other knob), else a
    fresh env parse — serving replica processes never init the
    training world."""
    from ..core.state import global_state

    gs = global_state()
    if gs.initialized:
        return gs.knobs
    from ..core.knobs import Knobs

    return Knobs.from_env()


def parse_buckets(spec: Optional[str] = None) -> Tuple[int, ...]:
    """``HOROVOD_SERVING_BUCKETS`` ("1,4,16,64") → sorted unique ints."""
    if spec is None:
        spec = serving_knobs().serving_buckets or "1,4,16,64"
    out = sorted({int(b) for b in str(spec).replace(";", ",").split(",")
                  if str(b).strip()})
    if not out or out[0] < 1:
        raise ValueError(f"invalid serving bucket spec {spec!r}")
    return tuple(out)


def build_apply_fn(metadata: Dict[str, Any]) -> Callable:
    """Rebuild ``apply_fn(params, x)`` from checkpoint metadata.

    The ``serving`` metadata block names the model the checkpoint was
    trained with, so a replica process needs nothing but the checkpoint
    path — the serving analog of ``load_model`` rebuilding the optimizer
    from its saved spec:

    * ``{"model": "mlp", "features": [128, 64, 10]}`` — the built-in
      MLP family (models/mlp.py);
    * ``{"model": "pkg.mod:factory", "kwargs": {...}}`` — an import
      path to a factory returning ``apply_fn``.
    """
    m = dict(metadata.get(SERVING_META_KEY, {}))
    name = m.get("model", "")
    if name == "mlp":
        from ..models.mlp import MLP

        mod = MLP(features=tuple(m.get("features", (128, 64, 10))))
        return lambda p, x: mod.apply({"params": p}, x)
    if ":" in name:
        mod_name, _, attr = name.partition(":")
        factory = getattr(importlib.import_module(mod_name), attr)
        return factory(**m.get("kwargs", {}))
    raise ValueError(
        f"checkpoint metadata has no rebuildable serving model "
        f"(metadata[{SERVING_META_KEY!r}] = {m!r}); pass apply_fn "
        "explicitly or save metadata={'serving': {'model': ...}}"
    )


class InferenceEngine:
    """Run padded request batches through AOT-compiled bucket programs.

    ``apply_fn(params, x)`` is the pure forward pass; ``params`` are
    host or device arrays (typically from ``checkpoint.load_params``).
    With a ``mesh``, parameters are placed by the ``parallel/`` rules
    (default: every leaf replicated — the data-parallel serving layout,
    where throughput comes from more replicas, not sharded weights) and
    inputs/outputs are mesh-committed; without one, plain single-device
    jit.
    """

    #: executables kept per engine; beyond this the least-recently-used
    #: program is dropped (shape/dtype-diverse traffic must not grow
    #: the cache for the process lifetime)
    MAX_CACHED_EXECUTABLES = 32

    def __init__(
        self,
        apply_fn: Callable,
        params: Any,
        *,
        buckets: Optional[Sequence[int]] = None,
        mesh=None,
        sharding_rules=None,
        default_dtype: str = "float32",
        feature_shape: Optional[Sequence[int]] = None,
    ):
        import jax

        self._apply = apply_fn
        self._buckets = (tuple(sorted(set(int(b) for b in buckets)))
                         if buckets else parse_buckets())
        self._mesh = mesh
        self._default_dtype = default_dtype
        # the declared per-example shape contract (checkpoint
        # metadata input_shape): requests violating it are CLIENT
        # errors (ValueError → 400), not model crashes — a flax
        # shape error would surface as a 500 and read as replica
        # death to the dispatch tier
        self._feature_shape = (tuple(int(d) for d in feature_shape)
                               if feature_shape else None)
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        # execution is serialized (one accelerator per replica);
        # compilation has its OWN lock so a cold shape's multi-second
        # AOT compile never stalls warm-bucket traffic
        self._lock = threading.Lock()
        self._compile_lock = threading.Lock()
        self._in_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.sharding import make_param_shardings

            shardings = make_param_shardings(params, mesh, sharding_rules)
            params = jax.tree_util.tree_map(
                jax.device_put, params, shardings)
            # requests are replicated over the mesh: bucket sizes (1, 4,
            # ...) rarely divide the data axes, and per-replica
            # throughput is the batcher's job, not the mesh's
            self._in_sharding = NamedSharding(mesh, P())
        else:
            params = jax.device_put(params)
        self._params = params
        # autotune warm start (ops/autotune.py, docs/autotune.md): a
        # replica pointed at the training run's HOROVOD_AUTOTUNE_CACHE
        # pins the model's tuned configuration at init — fingerprint
        # matched on the restored params, topology-relaxed (an
        # inference tier rarely shares the training world's shape;
        # numerics-changing winners transfer only under the
        # HOROVOD_AUTOTUNE_WIRE opt-in)
        self.autotune_config: Optional[Dict[str, Any]] = None
        sk = serving_knobs()
        if getattr(sk, "autotune_cache", ""):
            from ..ops import autotune as autotune_mod

            # opt-in resolved from the env-parsed serving knobs: an
            # uninitialized replica's global Knobs never saw the env,
            # so reading HOROVOD_AUTOTUNE_WIRE off it would silently
            # ignore the operator's consent
            self.autotune_config = autotune_mod.warm_start(
                params, cache_path=sk.autotune_cache,
                allow_numerics=bool(getattr(sk, "autotune_wire",
                                            False)),
                context="serving")

    @classmethod
    def from_checkpoint(
        cls,
        path: str,
        apply_fn: Optional[Callable] = None,
        **kwargs,
    ) -> "InferenceEngine":
        """Restore params from an orbax checkpoint (checkpoint.py) and
        build the engine; ``apply_fn`` defaults to the model named in
        the checkpoint's ``serving`` metadata block."""
        from ..checkpoint import load_params

        params, metadata = load_params(path)
        if apply_fn is None:
            apply_fn = build_apply_fn(metadata)
        meta = metadata.get(SERVING_META_KEY, {})
        kwargs.setdefault("default_dtype", meta.get("dtype", "float32"))
        kwargs.setdefault("feature_shape", meta.get("input_shape"))
        eng = cls(apply_fn, params, **kwargs)
        eng.metadata = metadata
        return eng

    # -- bucket machinery ---------------------------------------------------

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    @property
    def cached_executables(self) -> int:
        """Compiled programs currently cached — surfaced on /healthz so
        probes can tell a warm replica from one that will pay AOT
        compiles on the next cold shape (docs/serving.md)."""
        return len(self._cache)

    def bucket_for(self, n: int) -> int:
        """Smallest compiled bucket covering ``n`` examples (callers
        split batches larger than the top bucket — see __call__)."""
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    @staticmethod
    def _canonical_dtype(dtype) -> str:
        """The dtype jax will actually compile for: without x64, a
        float64 request lowers to the SAME program as float32 — keying
        the cache on the raw request dtype would compile and cache
        duplicates."""
        import jax

        return str(jax.dtypes.canonicalize_dtype(np.dtype(dtype)))

    def _executable(self, bucket: int, feature_shape: Tuple[int, ...],
                    dtype: str):
        import jax

        # feature shape is part of the compiled program's identity: a
        # (4, 8) executable cannot serve (4, 16) inputs, so a workload
        # mixing example shapes compiles one program per shape instead
        # of poisoning the bucket's cache slot with whichever came
        # first
        key = (bucket, tuple(feature_shape), dtype)
        with self._compile_lock:
            ex = self._cache.get(key)
            if ex is not None:
                self._cache.move_to_end(key)
                return ex
            t0 = time.perf_counter()
            x_s = jax.ShapeDtypeStruct((bucket,) + tuple(feature_shape),
                                       np.dtype(dtype))
            if self._in_sharding is not None:
                jitted = jax.jit(
                    self._apply, in_shardings=(None, self._in_sharding))
            else:
                jitted = jax.jit(self._apply)
            ex = jitted.lower(self._params, x_s).compile()
            self._cache[key] = ex
            while len(self._cache) > self.MAX_CACHED_EXECUTABLES:
                self._cache.popitem(last=False)
            metrics.record_serving_compile(
                bucket, time.perf_counter() - t0)
            return ex

    def warmup(self, feature_shape: Sequence[int],
               dtype: Optional[str] = None) -> None:
        """AOT-compile every bucket for one example shape up front, so
        the first real request of each size pays no compile."""
        dtype = self._canonical_dtype(dtype or self._default_dtype)
        for b in self._buckets:
            self._executable(b, tuple(feature_shape), dtype)

    # -- execution ----------------------------------------------------------

    def __call__(self, x: np.ndarray) -> np.ndarray:
        """Predict on ``x`` ([n, ...features]): pad to the covering
        bucket, execute, slice the padding back off. Batches above the
        top bucket run as multiple top-bucket chunks."""
        import jax

        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"predict needs [n, ...] input, got {x.shape}")
        if (self._feature_shape is not None
                and tuple(x.shape[1:]) != self._feature_shape):
            raise ValueError(
                f"example shape {tuple(x.shape[1:])} does not match the "
                f"model's declared input_shape {self._feature_shape}")
        n = x.shape[0]
        top = self._buckets[-1]
        if n > top:
            return np.concatenate(
                [self(x[i:i + top]) for i in range(0, n, top)], axis=0)
        bucket = self.bucket_for(n)
        dtype = self._canonical_dtype(x.dtype)
        if str(x.dtype) != dtype:
            x = x.astype(dtype)
        # compile (if cold) OUTSIDE the execution lock — a new shape's
        # multi-second AOT must not stall warm traffic
        ex = self._executable(bucket, x.shape[1:], dtype)
        with self._lock:
            faults.inject("serving.replica_exec", bucket=bucket)
            if bucket != n:
                pad = np.zeros((bucket - n,) + x.shape[1:], x.dtype)
                xb = np.concatenate([x, pad], axis=0)
            else:
                xb = x
            xb = jax.numpy.asarray(xb)
            if self._in_sharding is not None:
                xb = jax.device_put(xb, self._in_sharding)
            out = ex(self._params, xb)
        metrics.record_serving_batch(bucket, n)
        return np.asarray(out)[:n]
