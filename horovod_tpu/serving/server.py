"""HTTP front end for inference: POST /v1/predict + /v1/generate,
/healthz, /metrics.

Same transport family as the control plane: a threaded stdlib HTTP
server in the mold of ``runner/http/http_server.py`` (per-request
threads, silent logging, Content-Length replies, the shared
``utils.metrics.exposition()`` mount for ``GET /metrics``), carrying
the launcher's per-job shared secret (``runner/util/secret.py``) as
request authentication: when a key is set, every predict body must be
accompanied by ``X-Hvd-Auth: hex(hmac_sha256(key, body))`` — the HTTP
twin of the HMAC framing every TCP control-plane message already has
(``runner/util/network.py``). Probe routes (``/healthz``, ``/metrics``)
stay unauthenticated, k8s-style.

Protocol::

    POST /v1/predict
    {"inputs": [[...], ...], "dtype": "float32", "timeout_ms": 2000}
    -> 200 {"outputs": [[...], ...], "n": 2}
       401 bad/missing auth        413 oversized body
       429 admission queue full    503 draining / injected failure
       504 request deadline expired

    POST /v1/generate                     (decode replicas / front door)
    {"prompt": [17, 4, ...], "max_new_tokens": 64, "timeout_ms": 5000,
     "slo": "interactive", "stream": true}
    -> 200 chunked, one JSON object per line:
       {"tokens": [92]} ... {"done": true, "finish_reason": "eos", "n": 7}
       (stream=false collapses to one {"tokens": [...], "n",
       "finish_reason"} body; the error statuses mirror /v1/predict,
       and an error AFTER streaming began arrives as a final
       {"done": true, "error": ...} chunk — the 200 is already on the
       wire)

The same class fronts a single replica (predict_fn = the batcher) and
the multi-replica dispatch tier (predict_fn = ReplicaSet.predict) — the
wire surface is identical either way, which is what lets the load
generator and the chaos tooling drive both.
"""

from __future__ import annotations

import hashlib
import hmac
import itertools
import json
import threading
import time
import urllib.error
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

import numpy as np

from ..utils import flight, metrics
from ..utils.timeline import active_timeline
from . import tracing
from .batcher import Draining, QueueFull, RequestTimeout

AUTH_HEADER = "X-Hvd-Auth"
REQUEST_ID_HEADER = tracing.REQUEST_ID_HEADER
MAX_BODY_BYTES = 64 << 20  # one request can't swallow the heap
SERVING_REQUEST = "SERVING_REQUEST"  # timeline activity, tid = request id

# timeline span keys get a process-unique suffix: the request id is
# client-controlled, and two concurrent requests reusing one id would
# collide in the open-span table (wrong phase latency) and interleave
# B/E pairs on one trace track. "rid#7" still matches a search for rid.
_span_seq = itertools.count(1)


def sign_body(key: bytes, body: bytes) -> str:
    """The predict-request auth token: hex HMAC-SHA256 over the raw
    body with the per-job secret (client side of the check above)."""
    return hmac.new(key, body, hashlib.sha256).hexdigest()


class _ServingHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    _request_id = ""  # set per predict request; echoed on the reply
    _streamed = False  # a chunked 200 is already on the wire

    # -- helpers ------------------------------------------------------------

    def _reply(self, code: int, body: bytes,
               ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if self._request_id:
            # the client (or the front door retrying on its behalf)
            # gets the trace id back — it names this request in the
            # flight ring, the timeline and the merged trace
            self.send_header(REQUEST_ID_HEADER, self._request_id)
        if self.close_connection:
            # tell HTTP/1.1 keep-alive clients the stream ends here
            # (set on paths that left request bytes unread, e.g. 413)
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, obj: Dict) -> None:
        self._reply(code, json.dumps(obj).encode())

    # manual chunked framing (token streaming): the stdlib server never
    # writes Transfer-Encoding itself, so the handler frames each JSON
    # line as one HTTP/1.1 chunk — clients see tokens the iteration
    # they were generated, and urllib's chunked decoding reassembles
    # the line stream transparently on the other end
    def _start_stream(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Transfer-Encoding", "chunked")
        if self._request_id:
            self.send_header(REQUEST_ID_HEADER, self._request_id)
        self.end_headers()
        self._streamed = True

    def _stream_chunk(self, obj: Dict) -> None:
        data = json.dumps(obj).encode() + b"\n"
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _end_stream(self) -> None:
        self.wfile.write(b"0\r\n\r\n")

    def log_message(self, *args):  # silence per-request logging
        pass

    # -- routes -------------------------------------------------------------

    def do_GET(self):
        srv: "ServingServer" = self.server.serving  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/metrics":
            ctype, body = metrics.exposition()
            self._reply(200, body, ctype)
        elif path == "/healthz":
            self._reply_json(200 if not srv.draining else 503,
                             srv.health())
        elif path == "/health":
            # fleet-health verdict only (rendezvous serves the same
            # route for the training fleet — docs/health.md)
            try:
                from .. import health as _health
                self._reply_json(200, _health.verdict())
            except Exception:
                self._reply_json(200, {"health": "off"})
        else:
            self._reply_json(404, {"error": "not found"})

    def do_POST(self):
        srv: "ServingServer" = self.server.serving  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/")
        generate = path == "/v1/generate"
        if generate and srv.generate_fn is None:
            self._reply_json(404, {"error": "no generation engine "
                                            "behind this server"})
            return
        if not generate and (path != "/v1/predict"
                             or srv.predict_fn is None):
            self._reply_json(404, {"error": "not found"})
            return
        self._streamed = False
        t0 = time.perf_counter()
        # request trace id: the client's X-Request-Id (sanitized) or a
        # fresh one — bound to this handler thread's context so the
        # batcher/dispatch tier downstream stamp the same id into their
        # flight + timeline events (serving/tracing.py)
        rid = tracing.sanitize(self.headers.get(REQUEST_ID_HEADER, ""))
        self._request_id = rid
        rid_token = tracing.set_request_id(rid)
        span = f"{rid}#{next(_span_seq)}"
        tl = active_timeline()
        if tl is not None:
            tl.activity_start(span, SERVING_REQUEST, args={"id": rid})
        # count ourselves in-flight BEFORE touching the body: body
        # read + parse of a large request takes real time, and drain()
        # must not report empty (and let SIGTERM os._exit) while a
        # request is mid-read — the whole handling INCLUDING the
        # response write sits inside the in-flight window
        srv._inflight_delta(+1)
        code, resp = 500, {"error": "internal"}
        try:
            try:
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_BODY_BYTES:
                    # the oversized body is NOT read: close the
                    # connection so a keep-alive client can't have its
                    # next request parsed out of the unconsumed bytes
                    self.close_connection = True
                    code, resp = 413, {"error": "body too large"}
                    return
                body = self.rfile.read(length)
                if srv.key is not None:
                    token = self.headers.get(AUTH_HEADER, "")
                    if not hmac.compare_digest(
                            token, sign_body(srv.key, body)):
                        code, resp = 401, {"error": "bad auth"}
                        return
                if srv.draining:
                    code, resp = 503, {"error": "draining"}
                    return
                if generate:
                    try:
                        req = json.loads(body)
                        if not isinstance(req, dict) \
                                or "prompt" not in req:
                            raise KeyError("prompt")
                        timeout_s = (float(req["timeout_ms"]) / 1e3
                                     if req.get("timeout_ms") else None)
                    except (ValueError, KeyError, TypeError) as e:
                        code, resp = 400, {"error": f"bad request: {e}"}
                        return
                    code, resp = self._generate(srv, req, timeout_s)
                    return
                try:
                    req = json.loads(body)
                    x = np.asarray(
                        req["inputs"],
                        dtype=np.dtype(req.get("dtype", "float32")))
                    timeout_s = (float(req["timeout_ms"]) / 1e3
                                 if req.get("timeout_ms") else None)
                except (ValueError, KeyError, TypeError) as e:
                    code, resp = 400, {"error": f"bad request: {e}"}
                    return
                y = np.asarray(srv.predict_fn(x, timeout_s))
                code, resp = 200, {"outputs": y.tolist(),
                                   "dtype": str(y.dtype),
                                   "n": int(y.shape[0])}
            except QueueFull as e:
                code, resp = 429, {"error": str(e)}
            except (RequestTimeout, TimeoutError) as e:
                code, resp = 504, {"error": str(e)}
            except Draining as e:
                code, resp = 503, {"error": str(e)}
            except urllib.error.HTTPError as e:
                # front-door role: an upstream replica's verdict (a
                # 400 the dispatch tier rightly refused to retry, an
                # exhausted-retry 429/503) passes through with its own
                # status — a client error or backpressure must not be
                # re-reported as a front-door 500
                code, resp = e.code, {"error": f"replica: {e}"}
            except ValueError as e:
                # batcher.submit/engine reject malformed inputs
                # (empty batch, bad shape) with ValueError — that is
                # the CLIENT's error; a 500 here would read as replica
                # death to the dispatch tier and eject a healthy
                # replica
                code, resp = 400, {"error": f"bad request: {e}"}
            except ConnectionError as e:
                # includes faults.InjectedFault — a chaos rule at
                # serving.admit / serving.replica_exec surfaces as a
                # retryable 503, the same class a dying replica
                # produces
                code, resp = 503, {"error": f"transient: {e}"}
            except Exception as e:  # noqa: BLE001 — must answer
                code, resp = 500, {"error": f"{type(e).__name__}: {e}"}
        finally:
            try:
                self._finish(code, resp, t0)
            finally:
                srv._inflight_delta(-1)
                if tl is not None:
                    tl.activity_end(span, SERVING_REQUEST)
                tracing.reset_request_id(rid_token)
                self._request_id = ""

    def _generate(self, srv: "ServingServer", req: Dict,
                  timeout_s: Optional[float]):
        """Run one /v1/generate request through ``srv.generate_fn``
        (an iterator of chunk dicts — scheduler.GenRequest.stream or
        the front door's upstream relay). Admission errors raise
        BEFORE anything is written, so the do_POST ladder maps them to
        429/503/504 like predict."""
        chunks = iter(srv.generate_fn(req, timeout_s))
        if not req.get("stream"):
            tokens, fin = [], {}
            for chunk in chunks:  # admission errors raise on first next
                tokens.extend(int(t) for t in chunk.get("tokens", ()))
                if chunk.get("done"):
                    fin = chunk
                    break
            resp = {"tokens": tokens, "n": len(tokens),
                    "finish_reason": fin.get("finish_reason")}
            if fin.get("error"):
                # tokens flowed, then the engine failed: the partial
                # output is real — deliver it with the error attached
                resp["error"] = fin["error"]
            return 200, resp
        first = next(chunks)
        self._start_stream()
        try:
            self._stream_chunk(first)
            if not first.get("done"):
                for chunk in chunks:
                    self._stream_chunk(chunk)
                    if chunk.get("done"):
                        break
        except Exception as e:  # noqa: BLE001 — 200 is on the wire
            # the in-band error contract: a generator failure
            # mid-stream must reach the client as an explicit error
            # chunk, or a truncated generation reads as a completed
            # one. Best-effort (the socket itself may be the failure),
            # then re-raise so the request is METERED as a failure.
            try:
                self._stream_chunk({"done": True,
                                    "error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass
            raise
        finally:
            self._end_stream()
        return 200, None

    def _finish(self, code: int, resp: Dict, t0: float) -> None:
        dt = time.perf_counter() - t0
        metrics.record_serving_request(dt, code)
        flight.record("serving_request", self._request_id,
                      code=code, ms=round(dt * 1e3, 3))
        if not self._streamed:
            self._reply_json(code, resp)


class ServingServer:
    """Threaded HTTP server around a ``predict_fn(x, timeout_s)``.

    ``key`` enables the shared-secret auth (pass
    ``secret.secret_from_env()`` in launcher-spawned replicas).
    ``drain()`` implements the preemption contract: stop admission,
    wait for in-flight requests to flush, return — the SIGTERM handler
    (elastic/preemption.py) calls it before exiting 83.
    """

    def __init__(
        self,
        predict_fn: Optional[Callable[[np.ndarray, Optional[float]],
                                      np.ndarray]] = None,
        *,
        generate_fn: Optional[Callable] = None,
        port: int = 0,
        key: Optional[bytes] = None,
        health_extra: Optional[Callable[[], Dict]] = None,
    ):
        if predict_fn is None and generate_fn is None:
            raise ValueError(
                "ServingServer needs predict_fn and/or generate_fn")
        self.predict_fn = predict_fn
        #: ``generate_fn(req_dict, timeout_s) -> iterator of chunk
        #: dicts`` — the /v1/generate backend (decode scheduler on a
        #: replica, upstream relay on the front door)
        self.generate_fn = generate_fn
        self.key = key
        self.draining = False
        self._health_extra = health_extra
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                          _ServingHandler)
        self._httpd.serving = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="hvd-serving-http")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        self._thread.start()
        return self.port

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def health(self) -> Dict:
        h = {"status": "draining" if self.draining else "ok",
             "inflight": self._inflight}
        if self._health_extra is not None:
            try:
                h.update(self._health_extra())
            except Exception:
                pass
        # fold in the fleet-health verdict so the autoscaler and
        # external probes read ONE route: "health" (off/ok/degraded),
        # active-alert count, and the firing rule names ride alongside
        # slots/occupancy (docs/health.md)
        try:
            from .. import health as _health
            h.update(_health.verdict())
        except Exception:
            pass
        return h

    def _inflight_delta(self, d: int) -> None:
        with self._inflight_lock:
            self._inflight += d
            n = self._inflight
        metrics.set_serving_inflight(n)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admitting predicts and wait for in-flight ones to
        finish; True when the server emptied within the budget."""
        self.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    return True
            time.sleep(0.01)
        return False

    def shutdown(self) -> None:
        if self._thread.is_alive():
            self._httpd.shutdown()
            self._thread.join(timeout=5)
        self._httpd.server_close()
