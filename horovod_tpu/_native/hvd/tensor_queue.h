// Thread-safe queue of pending collective requests.
//
// Reference: /root/reference/horovod/common/tensor_queue.h:28
// (`TensorQueue`: AddToTensorQueueMulti / PopMessagesFromQueue /
// GetTensorEntriesFromResponse). The execution side holds no tensor data
// here (XLA owns buffers); entries carry metadata + a handle the Python
// layer resolves.
#pragma once

#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace hvd {

struct PendingEntry {
  int64_t handle = 0;
  Request request;
};

class TensorQueue {
 public:
  // Returns false (duplicate) if a tensor of this name is already pending.
  bool Add(const Request& req, int64_t handle);

  // Drain up to `max` queued requests for a negotiation cycle
  // (reference PopMessagesFromQueue).
  std::vector<Request> PopMessages(size_t max);

  // Resolve the entries for a negotiated response's tensors, removing
  // them from the pending table (reference GetTensorEntriesFromResponse).
  // Each entry keeps its original Request — the response cache needs true
  // per-tensor metadata, not the fused response's representative shape.
  std::vector<PendingEntry> PopEntriesWithRequests(
      const std::vector<std::string>& names);

  // Handles of everything pending (used to fail all on shutdown/error).
  std::vector<int64_t> DrainAll();

  size_t pending() const;

 private:
  mutable std::mutex mu_;
  std::deque<Request> queue_;
  std::unordered_map<std::string, PendingEntry> table_;
};

}  // namespace hvd
