// Binary wire format for Request/Response lists.
//
// Reference: /root/reference/horovod/common/wire/message.fbs +
// message.cc:541 — the reference serializes with flatbuffers; this is a
// dependency-free length-prefixed binary encoding with the same payload
// (SURVEY.md §2.1 "Message / wire format").
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvd {

class Writer {
 public:
  void U8(uint8_t v) { buf_.push_back(v); }
  void I32(int32_t v) { Raw(&v, 4); }
  void I64(int64_t v) { Raw(&v, 8); }
  void U64(uint64_t v) { Raw(&v, 8); }
  void F64(double v) { Raw(&v, 8); }
  void Str(const std::string& s) {
    I32(static_cast<int32_t>(s.size()));
    Raw(s.data(), s.size());
  }
  template <typename T>
  void Vec(const std::vector<T>& v) {
    I32(static_cast<int32_t>(v.size()));
    for (const auto& x : v) Raw(&x, sizeof(T));
  }
  const std::vector<uint8_t>& data() const { return buf_; }

 private:
  void Raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  std::vector<uint8_t> buf_;
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : p_(data), end_(data + len) {}
  bool ok() const { return ok_; }
  uint8_t U8() { uint8_t v = 0; Raw(&v, 1); return v; }
  int32_t I32() { int32_t v = 0; Raw(&v, 4); return v; }
  int64_t I64() { int64_t v = 0; Raw(&v, 8); return v; }
  uint64_t U64() { uint64_t v = 0; Raw(&v, 8); return v; }
  double F64() { double v = 0; Raw(&v, 8); return v; }
  std::string Str() {
    int32_t n = I32();
    if (!Bounded(n)) return {};
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }
  template <typename T>
  std::vector<T> Vec() {
    int32_t n = I32();
    std::vector<T> v;
    if (!Bounded(static_cast<int64_t>(n) * sizeof(T))) return v;
    v.resize(n);
    for (auto& x : v) Raw(&x, sizeof(T));
    return v;
  }

 private:
  bool Bounded(int64_t n) {
    if (n < 0 || p_ + n > end_) { ok_ = false; return false; }
    return true;
  }
  void Raw(void* out, size_t n) {
    if (!Bounded(static_cast<int64_t>(n))) return;
    std::copy(p_, p_ + n, static_cast<uint8_t*>(out));
    p_ += n;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

std::vector<uint8_t> SerializeRequestList(const RequestList& rl);
bool DeserializeRequestList(const uint8_t* data, size_t len, RequestList* rl);
std::vector<uint8_t> SerializeResponseList(const ResponseList& rl);
bool DeserializeResponseList(const uint8_t* data, size_t len,
                             ResponseList* rl);

}  // namespace hvd
