#include "bayes.h"

#include <cmath>

namespace hvd {

namespace {

// standard normal pdf / cdf for expected improvement
double NormPdf(double z) {
  return 0.3989422804014327 * std::exp(-0.5 * z * z);
}

double NormCdf(double z) { return 0.5 * std::erfc(-z * 0.7071067811865476); }

}  // namespace

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0.0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-0.5 * d2 / (l_ * l_));
}

bool GaussianProcess::Fit(const std::vector<std::vector<double>>& xs,
                          const std::vector<double>& ys) {
  const size_t n = xs.size();
  xs_ = xs;

  y_mean_ = 0.0;
  for (double y : ys) y_mean_ += y;
  y_mean_ /= n;
  double var = 0.0;
  for (double y : ys) var += (y - y_mean_) * (y - y_mean_);
  y_std_ = std::sqrt(var / n);
  if (y_std_ < 1e-12) y_std_ = 1.0;  // flat scores: GP sees all-zeros

  std::vector<double> yn(n);
  for (size_t i = 0; i < n; ++i) yn[i] = (ys[i] - y_mean_) / y_std_;

  // K + noise I, then in-place Cholesky (row-major lower triangle)
  chol_.assign(n * n, 0.0);
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      chol_[i * n + j] = Kernel(xs_[i], xs_[j]) + (i == j ? noise_ : 0.0);
    }
  }
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double s = chol_[i * n + j];
      for (size_t k = 0; k < j; ++k) {
        s -= chol_[i * n + k] * chol_[j * n + k];
      }
      if (i == j) {
        if (s <= 0.0) return false;
        chol_[i * n + i] = std::sqrt(s);
      } else {
        chol_[i * n + j] = s / chol_[j * n + j];
      }
    }
  }

  // alpha = K^-1 y via L L^T: forward then backward substitution
  alpha_.assign(n, 0.0);
  for (size_t i = 0; i < n; ++i) {
    double s = yn[i];
    for (size_t k = 0; k < i; ++k) s -= chol_[i * n + k] * alpha_[k];
    alpha_[i] = s / chol_[i * n + i];
  }
  for (size_t ii = n; ii-- > 0;) {
    double s = alpha_[ii];
    for (size_t k = ii + 1; k < n; ++k) s -= chol_[k * n + ii] * alpha_[k];
    alpha_[ii] = s / chol_[ii * n + ii];
  }
  return true;
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mu,
                              double* var) const {
  const size_t n = xs_.size();
  std::vector<double> kx(n);
  for (size_t i = 0; i < n; ++i) kx[i] = Kernel(x, xs_[i]);

  double m = 0.0;
  for (size_t i = 0; i < n; ++i) m += kx[i] * alpha_[i];
  *mu = m;

  // v = L^-1 kx; var = k(x,x) - v.v
  std::vector<double> v(n);
  for (size_t i = 0; i < n; ++i) {
    double s = kx[i];
    for (size_t k = 0; k < i; ++k) s -= chol_[i * n + k] * v[k];
    v[i] = s / chol_[i * n + i];
  }
  double vv = 0.0;
  for (size_t i = 0; i < n; ++i) vv += v[i] * v[i];
  double out = 1.0 + noise_ - vv;
  *var = out > 1e-12 ? out : 1e-12;
}

BayesianTuner::BayesianTuner(int dims, uint64_t seed, int pre_samples)
    : dims_(dims), rng_(seed ? seed : 1) {
  // seeding design: center + scrambled corners/edges keeps the first GP
  // fit spread across the cube (a Latin square would need bookkeeping
  // for arbitrary dims; for the 2-3 knobs tuned here this is equivalent)
  pre_.push_back(std::vector<double>(dims_, 0.5));
  for (int s = 1; s < pre_samples; ++s) {
    std::vector<double> p(dims_);
    for (int d = 0; d < dims_; ++d) {
      int bit = (s >> (d % 3)) & 1;
      p[d] = bit ? 0.85 : 0.15;
    }
    // nudge so repeated corners never coincide (degenerate kernel rows)
    p[s % dims_] += 0.02 * s * ((s & 1) ? 1 : -1);
    if (p[s % dims_] < 0.0) p[s % dims_] = 0.0;
    if (p[s % dims_] > 1.0) p[s % dims_] = 1.0;
    pre_.push_back(std::move(p));
  }
  next_ = pre_[0];
}

double BayesianTuner::Rand01() {
  // xorshift64*: deterministic, no <random> state-size baggage
  rng_ ^= rng_ >> 12;
  rng_ ^= rng_ << 25;
  rng_ ^= rng_ >> 27;
  return double((rng_ * 2685821657736338717ull) >> 11) /
         9007199254740992.0;
}

void BayesianTuner::Observe(const std::vector<double>& x, double y) {
  xs_.push_back(x);
  ys_.push_back(y);

  const size_t n = ys_.size();
  if (n < pre_.size()) {
    next_ = pre_[n];
    return;
  }

  GaussianProcess gp;
  if (!gp.Fit(xs_, ys_)) {
    // degenerate fit: fall back to a random probe
    next_.assign(dims_, 0.0);
    for (int d = 0; d < dims_; ++d) next_[d] = Rand01();
    return;
  }

  double best_y = ys_[0];
  for (double v : ys_) best_y = v > best_y ? v : best_y;
  double best_std = (best_y - gp.y_mean()) / gp.y_std();

  // EI argmax over random candidates (the reference polishes with LBFGS;
  // 512 draws over a 2-3D unit cube lands within the kernel length
  // scale of the optimum, which is all the noisy objective supports)
  const double xi = 0.01;
  double best_ei = -1.0;
  std::vector<double> cand(dims_), best_cand(dims_, 0.5);
  for (int t = 0; t < 512; ++t) {
    for (int d = 0; d < dims_; ++d) cand[d] = Rand01();
    double mu, var;
    gp.Predict(cand, &mu, &var);
    double sigma = std::sqrt(var);
    double z = (mu - best_std - xi) / sigma;
    double ei = (mu - best_std - xi) * NormCdf(z) + sigma * NormPdf(z);
    if (ei > best_ei) {
      best_ei = ei;
      best_cand = cand;
    }
  }
  next_ = best_cand;
}

std::vector<double> BayesianTuner::Best() const {
  size_t bi = 0;
  for (size_t i = 1; i < ys_.size(); ++i) {
    if (ys_[i] > ys_[bi]) bi = i;
  }
  return xs_.empty() ? std::vector<double>(dims_, 0.5) : xs_[bi];
}

}  // namespace hvd
