// Detect ranks that fail to submit tensors other ranks submitted.
//
// Reference: /root/reference/horovod/common/stall_inspector.h:30 —
// coordinator-side: per uncompleted tensor, record first-seen time and
// which ranks reported; warn after `warning_time` (default 60 s,
// stall_inspector.h:75-83), optionally signal shutdown after
// `shutdown_time`.
#pragma once

#include <chrono>
#include <functional>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace hvd {

class StallInspector {
 public:
  StallInspector(double warning_s, double shutdown_s)
      : warning_s_(warning_s), shutdown_s_(shutdown_s) {}

  void RecordRank(const std::string& tensor, int32_t rank);
  void RemoveTensor(const std::string& tensor);

  // Check all uncompleted entries; logs via `log` and returns true if the
  // shutdown threshold was exceeded (reference CheckForStalledTensors).
  bool Check(int32_t world_size,
             const std::function<void(const std::string&)>& log);

  bool enabled() const { return warning_s_ > 0; }

 private:
  struct Entry {
    std::chrono::steady_clock::time_point first_seen;
    std::set<int32_t> ranks;
    bool warned = false;
  };
  double warning_s_;
  double shutdown_s_;
  std::unordered_map<std::string, Entry> entries_;
};

}  // namespace hvd
