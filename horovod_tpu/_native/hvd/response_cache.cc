#include "response_cache.h"

#include <algorithm>

namespace hvd {

ResponseCache::State ResponseCache::Lookup(const Request& req) const {
  auto it = entries_.find(req.name);
  if (it == entries_.end()) return State::kMiss;
  const Entry& e = it->second;
  if (e.dtype != req.dtype || e.shape != req.shape ||
      e.splits != req.splits ||
      e.response.op != req.op || e.response.reduce_op != req.reduce_op ||
      e.response.root_rank != req.root_rank ||
      e.response.prescale != req.prescale ||
      e.response.postscale != req.postscale) {
    return State::kInvalid;
  }
  return State::kHit;
}

uint32_t ResponseCache::Position(const std::string& name) const {
  return entries_.at(name).position;
}

const Response& ResponseCache::Get(uint32_t position) const {
  return entries_.at(by_position_.at(position)).response;
}

const std::string& ResponseCache::NameAt(uint32_t position) const {
  static const std::string kEmpty;
  return position < by_position_.size() ? by_position_[position] : kEmpty;
}

void ResponseCache::Put(const Response& resp, const Request& req) {
  auto it = entries_.find(req.name);
  if (it != entries_.end()) {
    lru_.erase(it->second.lru_it);
    lru_.push_front(req.name);
    it->second.response = resp;
    it->second.dtype = req.dtype;
    it->second.shape = req.shape;
    it->second.splits = req.splits;
    it->second.lru_it = lru_.begin();
    return;
  }
  if (capacity_ == 0) return;
  uint32_t pos;
  if (entries_.size() >= capacity_) {
    // evict least-recently-used, reuse its position slot
    const std::string victim = lru_.back();
    lru_.pop_back();
    pos = entries_[victim].position;
    entries_.erase(victim);
  } else {
    if (by_position_.size() < capacity_) {
      by_position_.resize(capacity_);
    }
    pos = 0;
    while (pos < capacity_ && !by_position_[pos].empty()) ++pos;
  }
  by_position_[pos] = req.name;
  lru_.push_front(req.name);
  Entry e;
  e.response = resp;
  e.dtype = req.dtype;
  e.shape = req.shape;
  e.splits = req.splits;
  e.position = pos;
  e.lru_it = lru_.begin();
  entries_[req.name] = std::move(e);
}

void ResponseCache::Erase(const std::string& name) {
  auto it = entries_.find(name);
  if (it == entries_.end()) return;
  by_position_[it->second.position].clear();
  lru_.erase(it->second.lru_it);
  entries_.erase(it);
}

void ResponseCache::Clear() {
  entries_.clear();
  by_position_.clear();
  lru_.clear();
}

std::vector<uint64_t> ResponseCache::HitBits(
    const std::vector<uint32_t>& positions) const {
  std::vector<uint64_t> bits((capacity_ + 63) / 64, 0);
  for (uint32_t p : positions) {
    if (p / 64 < bits.size()) bits[p / 64] |= (1ull << (p % 64));
  }
  return bits;
}

std::vector<uint32_t> ResponseCache::BitsToPositions(
    const std::vector<uint64_t>& bits) {
  std::vector<uint32_t> out;
  for (size_t w = 0; w < bits.size(); ++w) {
    uint64_t word = bits[w];
    while (word) {
      int b = __builtin_ctzll(word);
      out.push_back(static_cast<uint32_t>(w * 64 + b));
      word &= word - 1;
    }
  }
  return out;
}

std::vector<uint64_t> ResponseCache::Intersect(
    const std::vector<std::vector<uint64_t>>& all) {
  if (all.empty()) return {};
  size_t words = 0;
  for (const auto& v : all) words = std::max(words, v.size());
  std::vector<uint64_t> out(words, ~0ull);
  for (const auto& v : all) {
    for (size_t i = 0; i < words; ++i) {
      out[i] &= (i < v.size() ? v[i] : 0ull);
    }
  }
  return out;
}

}  // namespace hvd
