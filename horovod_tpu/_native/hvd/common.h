// Core types for the native control-plane runtime.
//
// Reference surface: /root/reference/horovod/common/common.h:318-349
// (Tensor/OpContext abstractions), message.h:50,159 (Request/Response).
//
// TPU-native split: the reference's C++ runtime owns both negotiation
// (which tensors are globally ready, in what fused order) and execution
// (NCCL/MPI). Here the data plane is XLA collectives driven from Python,
// so this runtime is the *control plane only*: readiness negotiation,
// deterministic fusion order, response caching, stall detection. What it
// hands back to the caller is an ordered stream of fused execution
// batches, the exact analog of the reference controller's ResponseList
// (controller.cc:75 ComputeResponseList).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvd {

enum class DataType : int32_t {
  kUint8 = 0,
  kInt8 = 1,
  kUint16 = 2,
  kInt16 = 3,
  kInt32 = 4,
  kInt64 = 5,
  kFloat16 = 6,
  kFloat32 = 7,
  kFloat64 = 8,
  kBool = 9,
  kBFloat16 = 10,  // TPU-native wire type
};

inline int64_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kUint8:
    case DataType::kInt8:
    case DataType::kBool:
      return 1;
    case DataType::kUint16:
    case DataType::kInt16:
    case DataType::kFloat16:
    case DataType::kBFloat16:
      return 2;
    case DataType::kInt32:
    case DataType::kFloat32:
      return 4;
    default:
      return 8;
  }
}

enum class OpType : int32_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kAlltoall = 3,
  kReducescatter = 4,
  kJoin = 5,
  kBarrier = 6,
  kError = 7,  // response-only: negotiation failure delivered to all ranks
  // process-set registration (reference process_set.h:89 ProcessSetTable
  // + process_sets.py:123 add_process_set): membership rides Request.shape,
  // the set id rides Request.root_rank. Negotiated like any tensor — all
  // world ranks must submit identical membership (the reference's
  // synchronized registration), mismatches fail via the ordinary
  // metadata-validation channel.
  kRegisterSet = 8,
  kDeregisterSet = 9,
};

enum class StatusType : int32_t {
  kOk = 0,
  kUnknownError = 1,
  kPreconditionError = 2,
  kAborted = 3,
  kInvalidArgument = 4,
  kInProgress = 5,
};

struct Status {
  StatusType type = StatusType::kOk;
  std::string reason;
  bool ok() const { return type == StatusType::kOk; }
  static Status OK() { return {}; }
  static Status Invalid(std::string r) {
    return {StatusType::kInvalidArgument, std::move(r)};
  }
  static Status Error(std::string r) {
    return {StatusType::kUnknownError, std::move(r)};
  }
};

// Worker -> coordinator: "rank R is ready to run op on tensor N"
// (reference message.h:50).
struct Request {
  int32_t rank = 0;
  OpType op = OpType::kAllreduce;
  DataType dtype = DataType::kFloat32;
  std::string name;
  int32_t root_rank = 0;          // broadcast only
  int32_t reduce_op = 0;          // ReduceOp id (mpi_ops.py:60 values)
  double prescale = 1.0;
  double postscale = 1.0;
  std::vector<int64_t> shape;
  // alltoall only: rows of dim 0 destined to each rank (reference
  // operations.cc:1858 uneven splits); empty = even split
  std::vector<int64_t> splits;
  // grouped collectives (reference group_table.h:25): all members of a
  // group become ready together or not at all. The tag is derived from
  // the member names (identical across ranks); group_size is the member
  // count the coordinator waits for. Empty tag = ungrouped.
  std::string group;
  int32_t group_size = 0;
  // process set this op negotiates in (reference process_set.h:89): 0 =
  // global. Readiness counts only the set's members; the Python layer
  // qualifies tensor names per set so name-keyed tables never collide
  // across sets.
  int32_t process_set_id = 0;

  int64_t NumElements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  int64_t ByteSize() const { return NumElements() * DataTypeSize(dtype); }
};

// Coordinator -> all ranks: "run this (possibly fused) op now"
// (reference message.h:159). tensor_names order is the fusion order every
// rank must follow.
struct Response {
  OpType op = OpType::kAllreduce;
  std::vector<std::string> tensor_names;
  std::string error_reason;  // op == kError
  int32_t root_rank = 0;
  int32_t reduce_op = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  DataType dtype = DataType::kFloat32;
  int64_t total_bytes = 0;
  std::vector<int64_t> first_shape;  // representative shape (validation)
  // per-tensor shapes parallel to tensor_names: lets ranks without a
  // local pending entry (joined ranks) replicate exact cache metadata
  // for fused batches instead of guessing from first_shape
  std::vector<std::vector<int64_t>> tensor_shapes;
  // allgather: every rank's dim-0 extent in rank order — the negotiated
  // size collection of reference ConstructResponse (controller.cc:497)
  // that makes ragged (variable first-dim) allgather executable
  std::vector<int64_t> rank_dim0;
  // alltoall: the full splits matrix, row r = rank r's outgoing splits,
  // flattened [rank * size + dest]; empty when every rank is even
  std::vector<int64_t> all_splits;
  // non-empty when the constituent tensors were group members: joined
  // ranks must also skip caching them (grouped responses are uncached so
  // the cache fast path can never split a group across cycles)
  std::string group;
  // the process set this response belongs to; non-member ranks still
  // mutate their response cache identically (replicated positions) but
  // never execute the batch. For kRegisterSet acks, first_shape carries
  // the agreed membership.
  int32_t process_set_id = 0;
  // kError only: the single rank this error addresses, or -1 for all.
  // A non-member enqueue fails just the offender — the broadcast error
  // must not pop a member's legitimately pending entry of the same name.
  int32_t error_rank = -1;
};

struct RequestList {
  std::vector<Request> requests;
  std::vector<uint64_t> cache_bits;    // bitvector of cache-hit positions
  std::vector<uint64_t> invalid_bits;  // positions whose cached metadata no
                                       // longer matches this rank's request
  bool shutdown = false;
  bool join = false;
};

struct ResponseList {
  std::vector<Response> responses;
  // OR of every rank's invalid_bits: all ranks erase these cache positions
  // in the same cycle, keeping position tables replicated (reference
  // CacheCoordinator, controller.cc:802).
  std::vector<uint64_t> agreed_invalid_bits;
  bool shutdown = false;
  int32_t join_count = 0;
  // ranks whose kJoin is pending (not yet full coverage), broadcast
  // every cycle: the Python plan cache checks this before dispatching a
  // bypassed step so peers of a joining rank fall back to negotiation
  // (the joiner's zero-contribution semantics only exist there)
  int32_t pending_joins = 0;
  // Control-plane autotune (reference parameter_manager.cc:528, which
  // broadcasts the winning parameters): the coordinator owns the search
  // and ships the currently-applied values with every cycle, so all
  // ranks hold identical parameters by construction. 0 = autotune off.
  double tuned_cycle_ms = 0.0;
  int64_t tuned_threshold = 0;
  bool tuned_pinned = false;
  // Widened search space (reference parameter_manager.h:186 also flips
  // response-cache and hierarchical-collective toggles): shipped every
  // cycle like the scalar knobs above.
  bool tuned_cache_enabled = true;
  bool tuned_hierarchical = false;
  // ranks per inner (ICI) domain for hierarchical collectives
  // (ops/hierarchical.py resolve_block); 0 = launcher-topology default
  int64_t tuned_hier_block = 0;
  // true only when the 5-D Bayes search owns the cache/hierarchical
  // dims; the 2-D coordinate-descent tuner never explores them, so its
  // defaults must not override user-set knobs at pin time (ADVICE r4 #2)
  bool tuned_bayes = false;
};

}  // namespace hvd
